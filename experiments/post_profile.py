"""Profile the volume POST serving path end to end, single-threaded.

Drives util/httpd.serve_connection directly over a socketpair (a
feeder thread writes pipelined POSTs; the serving side runs under
cProfile in the main thread), so the profile attributes every
microsecond of the per-request cost: mini-loop head parse, dispatch,
handler prologue (fid parse, auth, body read), the write work itself
(C hot loop or Python fallback per WEED_NATIVE_POST), and the reply.

Usage: python experiments/post_profile.py [n] [0|1 native]
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8000
    native = sys.argv[2] if len(sys.argv) > 2 else "1"
    os.environ["WEED_NATIVE_POST"] = native

    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.util import httpd

    with tempfile.TemporaryDirectory() as d:
        vs = VolumeServer([d], port=0, master="")
        vs.store.add_volume(1)
        handler_cls = vs._http_handler_class()

        payload = b"\x00\x07profile-payload\xff" * 64  # 1 KiB binary
        reqs = []
        for i in range(n):
            fid = f"1,{i + 1:x}00bbccdd"
            reqs.append(
                b"POST /%s HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/octet-stream\r\n"
                b"Content-Length: %d\r\n\r\n" % (fid.encode(), len(payload))
                + payload
            )
        blob = b"".join(reqs)

        a, b = socket.socketpair()

        def send():
            a.sendall(blob)
            a.shutdown(socket.SHUT_WR)  # EOF ends the serve loop cleanly

        def drain():
            # separate thread: draining must overlap the send or the
            # pair deadlocks on full buffers in both directions
            while True:
                try:
                    if not a.recv(1 << 20):
                        return
                except OSError:
                    return

        for fn in (send, drain):
            threading.Thread(target=fn, daemon=True).start()

        class Srv:  # the surface serve_connection touches
            pass

        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        httpd.serve_connection(b, ("127.0.0.1", 1), Srv(), handler_cls)
        prof.disable()
        wall = time.perf_counter() - t0
        a.close()
        b.close()
        vs.store.close()

    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.sort_stats("cumulative").print_stats(22)
    print(out.getvalue())
    print(
        f"ARM={'c-hot-loop' if native != '0' else 'python'} "
        f"n={n} wall_us_per_req={wall / n * 1e6:.1f}"
    )


if __name__ == "__main__":
    main()
