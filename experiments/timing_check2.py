"""Timing with a real host-side sync: fetch a scalar reduction."""
import time
import jax, jax.numpy as jnp
import numpy as np

N = 64 * 1024 * 1024

def main():
    xs = [jax.random.randint(jax.random.PRNGKey(i), (10, N), 0, 256,
                             dtype=jnp.int32).astype(jnp.uint8) for i in range(4)]
    jax.block_until_ready(xs)
    probe = jax.jit(lambda x: x ^ jnp.uint8(1))
    red = jax.jit(lambda ys: sum(y[0, 0].astype(jnp.int32) for y in ys))

    def t(args_list):
        outs = [probe(a) for a in args_list]
        _ = int(red(outs))  # warm compile of reducer
        t0 = time.perf_counter()
        outs = [probe(a) for a in args_list]
        _ = int(red(outs))
        return time.perf_counter() - t0

    tr = 2 * 10 * N
    t1 = t([xs[0]])
    t4s = t([xs[0]] * 4)
    t4d = t(xs)
    print(f"1 call   : {t1*1e3:8.3f} ms {tr/t1/1e9:9.1f} GB/s traffic")
    print(f"4 same   : {t4s*1e3:8.3f} ms {4*tr/t4s/1e9:9.1f} GB/s")
    print(f"4 diff   : {t4d*1e3:8.3f} ms {4*tr/t4d/1e9:9.1f} GB/s")

if __name__ == "__main__":
    main()
