"""Perf experiment: RS(10,4) encode kernel variants on one chip.

Roofline: the fused kernel moves 10N bytes in + 4N out; at ~819 GB/s
v5e HBM that caps data throughput at ~585 GB/s. The unfused XLA kernel
additionally materializes [80,N] int8 bit-planes and a [32,N] int32
accumulator in HBM (~43 bytes moved per payload byte -> ~190 GB/s cap,
less in practice).

Run:  python experiments/kernel_variants.py
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels, gf_matrix_to_bits

K, P = 10, 4


def build_perm_bits(matrix_rows: np.ndarray, k: int) -> np.ndarray:
    """gf_matrix_to_bits output permuted for the fused kernel layout.

    Rows: fused acc row = i * R + r  (bit-plane-major over output rows)
    Cols: fused bits row = j * k + c (bit-plane-major over input shards),
    padded to 128 columns with zeros.
    """
    a = gf_matrix_to_bits(matrix_rows)  # [R*8, k*8], row=r*8+i, col=c*8+j
    r_out = matrix_rows.shape[0]
    perm = np.zeros((r_out * 8, 128), dtype=np.int8)
    for r in range(r_out):
        for i in range(8):
            for c in range(k):
                for j in range(8):
                    perm[i * r_out + r, j * k + c] = a[r * 8 + i, c * 8 + j]
    return perm


def fused_kernel(a_ref, x_ref, o_ref, *, r_out: int, k: int):
    x = x_ref[:].astype(jnp.int32)  # [k, TN]
    planes = [((x >> j) & 1).astype(jnp.int8) for j in range(8)]
    bits = jnp.concatenate(planes, axis=0)  # [k*8, TN] row j*k+c
    pad = jnp.zeros((128 - 8 * k, bits.shape[1]), jnp.int8)
    bits = jnp.concatenate([bits, pad], axis=0)  # [128, TN]
    acc = jax.lax.dot_general(
        a_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [r_out*8, TN]
    out = jnp.zeros((r_out, acc.shape[1]), jnp.int32)
    for i in range(8):
        out = out | (((acc[i * r_out:(i + 1) * r_out] & 1)) << i)
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tn", "r_out", "k"))
def fused_apply(a_bits, data, tn=8192, r_out=P, k=K):
    n = data.shape[1]
    grid = (n // tn,)
    return pl.pallas_call(
        functools.partial(fused_kernel, r_out=r_out, k=k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r_out * 8, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint8),
    )(a_bits, data)


def timeit(fn, *args, iters=8):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    shard_len = (64 if on_tpu else 2) * 1024 * 1024
    rng = jax.random.PRNGKey(0)
    data = jax.random.randint(rng, (K, shard_len), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    data = jax.device_put(data)
    jax.block_until_ready(data)
    payload = K * shard_len

    # roofline probe: single elementwise pass, 2 bytes/byte traffic
    probe = jax.jit(lambda x: x ^ jnp.uint8(1))
    t = timeit(probe, data)
    print(f"copy-probe: {payload / t / 1e9:.1f} GB/s payload "
          f"({2 * payload / t / 1e9:.1f} GB/s traffic)")

    kern = TpuCodecKernels(K, P)
    enc = jax.jit(kern.encode)
    t = timeit(enc, data)
    print(f"xla-unfused encode: {payload / t / 1e9:.2f} GB/s")
    baseline_parity = np.asarray(enc(data))

    matrix = gf256.build_code_matrix(K, K + P)
    a_perm = jnp.asarray(build_perm_bits(matrix[K:], K))
    for tn in (2048, 4096, 8192, 16384, 32768):
        t = timeit(lambda d: fused_apply(a_perm, d, tn=tn), data)
        parity = np.asarray(fused_apply(a_perm, data, tn=tn))
        ok = np.array_equal(parity, baseline_parity)
        print(f"pallas-fused tn={tn:6d}: {payload / t / 1e9:8.2f} GB/s "
              f"correct={ok}")


if __name__ == "__main__":
    main()
