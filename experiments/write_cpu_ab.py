"""Same-method A/B: volume-server CPU per write, C hot loop on vs off.

Method (the OPERATIONS.md §round 5 discipline — both arms measured the
same way on the same host, minutes apart): the volume server runs ALONE
in a subprocess (no master, no heartbeats, volume 1 pre-allocated); the
parent drives N serial 1 KiB binary POSTs over one pooled keep-alive
connection and reads the CHILD's /proc/<pid>/stat utime+stime around
the timed region — so the number is volume-server-only CPU, not wall,
not client, not master. WEED_NATIVE_POST=0/1 selects the arm; arms are
interleaved twice so host-throttle drift stays common-mode.

Usage: python experiments/write_cpu_ab.py [n_per_arm]
Prints one JSON line per arm-round plus the medians.
"""

from __future__ import annotations

import json
import os
import secrets
import socket
import statistics
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CLK = os.sysconf("SC_CLK_TCK")

CHILD = r"""
import sys, time
from seaweedfs_tpu.server.volume_server import VolumeServer
vs = VolumeServer([sys.argv[1]], port=int(sys.argv[2]), master="")
vs.store.add_volume(1)
vs.start()
print("READY", flush=True)
time.sleep(3600)
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_cpu_s(pid: int) -> float:
    with open(f"/proc/{pid}/stat", "rb") as f:
        fields = f.read().rsplit(b")", 1)[1].split()
    return (int(fields[11]) + int(fields[12])) / CLK  # utime + stime


def run_arm(native: str, n: int, payload: bytes) -> float:
    """Per-write volume-server CPU in us for one arm-round."""
    sys.path.insert(0, REPO)
    from seaweedfs_tpu.client.operation import _drop_conn, _pooled_conn

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        port = free_port()
        env = dict(os.environ, WEED_NATIVE_POST=native, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", CHILD, d, str(port)],
            stdout=subprocess.PIPE,
            env=env,
            cwd=REPO,
        )
        try:
            line = proc.stdout.readline()
            assert b"READY" in line, line
            addr = f"127.0.0.1:{port}"
            c, _ = _pooled_conn(addr, 30.0)
            warm = max(50, n // 10)
            cpu0 = None
            for i in range(n + warm):
                if i == warm:
                    cpu0 = child_cpu_s(proc.pid)
                fid = f"1,{i + 1:x}00bbccdd"
                c.send_request(
                    "POST", f"/{fid}", payload,
                    {"Content-Type": "application/octet-stream"},
                )
                status, _h, _b, will_close = c.read_response("POST")
                assert status == 201, (fid, status)
                if will_close:
                    _drop_conn(addr)
                    c, _ = _pooled_conn(addr, 30.0)
            cpu1 = child_cpu_s(proc.pid)
            _drop_conn(addr)
            return (cpu1 - cpu0) / n * 1e6
        finally:
            proc.kill()
            proc.wait()


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4000
    payload = secrets.token_bytes(1024)  # binary: both arms store raw
    arms: dict[str, list[float]] = {"0": [], "1": []}
    for round_ in range(2):
        for native in ("0", "1"):
            us = run_arm(native, n, payload)
            arms[native].append(us)
            print(json.dumps({
                "arm": "python" if native == "0" else "c-hot-loop",
                "round": round_,
                "volume_cpu_us_per_write": round(us, 1),
                "n": n,
            }), flush=True)
    py_us = statistics.median(arms["0"])
    c_us = statistics.median(arms["1"])
    print(json.dumps({
        "metric": "volume_write_cpu_ab",
        "python_us": round(py_us, 1),
        "c_us": round(c_us, 1),
        "ratio": round(c_us / py_us, 3),
    }))


if __name__ == "__main__":
    main()
