"""V5: pure-VPU SWAR kernel. 4 bytes packed per uint32 lane.

For each data shard c, build the GF-doubling chain t_j = data[c] * 2^j
(SWAR: 6 ops per doubling), XOR t_j into parity row p whenever bit j of
M[p,c] is set. No MXU, no bit-plane expansion.
"""
import functools, time
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from experiments.kernel_variants3 import marginal_chain
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

K, P = 10, 4
SHARD = 64 * 1024 * 1024  # bytes per shard
W = SHARD // 4


def plan_from_matrix(rows: np.ndarray):
    """rows [R, k] GF coefficients -> per-shard XOR schedule.

    Returns list over c of (max_bit, {j: [p, ...]}).
    """
    r_out, k = rows.shape
    plan = []
    for c in range(k):
        byj = {}
        maxb = -1
        for p in range(r_out):
            m = int(rows[p, c])
            for j in range(8):
                if (m >> j) & 1:
                    byj.setdefault(j, []).append(p)
                    maxb = max(maxb, j)
        plan.append((maxb, byj))
    return plan


def make_v5_kernel(plan, r_out, k):
    def kernel(x_ref, o_ref):
        M_FE = jnp.uint32(0xFEFEFEFE)
        M_HB = jnp.uint32(0x80808080)
        RED = jnp.uint32(0x1D)
        acc = [None] * r_out
        for c in range(k):
            maxb, byj = plan[c]
            t = x_ref[c, :]
            for j in range(maxb + 1):
                for p in byj.get(j, ()):
                    acc[p] = t if acc[p] is None else acc[p] ^ t
                if j < maxb:
                    hb = t & M_HB
                    t = ((t << 1) & M_FE) ^ ((hb >> 7) * RED)
        for p in range(r_out):
            o_ref[p, :] = acc[p]

    return kernel


@functools.partial(jax.jit, static_argnames=("tn", "r_out", "k", "plan_key"))
def v5_apply(data_u32, tn, r_out, k, plan_key):
    plan = _PLANS[plan_key]
    n = data_u32.shape[1]
    return pl.pallas_call(
        make_v5_kernel(plan, r_out, k),
        grid=(n // tn,),
        in_specs=[pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint32),
    )(data_u32)


_PLANS = {}


def main():
    matrix = gf256.build_code_matrix(K, K + P)
    plan = plan_from_matrix(matrix[K:])
    _PLANS["enc"] = tuple(
        (maxb, tuple(sorted((j, tuple(ps)) for j, ps in byj.items())))
        for maxb, byj in plan
    )
    # rebuild dict-form for kernel
    _PLANS["enc"] = tuple((maxb, {j: list(ps) for j, ps in items})
                          for maxb, items in _PLANS["enc"])
    nxors = sum(len(ps) for _, byj in plan for ps in byj.values())
    ndoubles = sum(maxb for maxb, _ in plan)
    print(f"schedule: {nxors} xors + {ndoubles} doublings per word")

    data = jax.random.randint(jax.random.PRNGKey(0), (K, W), 0, (1 << 31) - 1,
                              dtype=jnp.int32).astype(jnp.uint32)
    jax.block_until_ready(data)
    payload = K * SHARD

    kern = TpuCodecKernels(K, P)
    data_u8 = np.asarray(data).view(np.uint8).reshape(K, SHARD)
    ref = np.asarray(jax.jit(kern.encode)(jnp.asarray(data_u8))[:, :4096])

    def mk_step(fn):
        def s(d):
            par = fn(d)
            return d.at[0].set(d[0] ^ par[0])
        return jax.jit(s, donate_argnums=0)

    for tn in (2048, 4096, 8192, 16384, 32768):
        out = np.asarray(v5_apply(data, tn, P, K, "enc")).view(np.uint8)[:, :4096]
        ok = np.array_equal(out, ref)
        t = marginal_chain(mk_step(lambda d: v5_apply(d, tn, P, K, "enc")),
                           data, iters=6)
        print(f"v5 tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms) correct={ok}")


if __name__ == "__main__":
    main()
