"""V6: Horner-form SWAR. u_j[p] = XOR of x[c] where bit j of M[p,c];
y[p] = Horner(u_7..u_0) with GF doubling. 28 doublings vs V5's 60."""
import functools
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from experiments.kernel_variants3 import marginal_chain
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

K, P = 10, 4
SHARD = 64 * 1024 * 1024
W = SHARD // 4


def make_v6_kernel(rows_tuple, r_out, k):
    rows = np.array(rows_tuple, dtype=np.uint8).reshape(r_out, k)
    # sel[p][j] = list of c with bit j set in rows[p, c]
    sel = [[[c for c in range(k) if (rows[p, c] >> j) & 1] for j in range(8)]
           for p in range(r_out)]
    maxj = [max((j for j in range(8) if sel[p][j]), default=0) for p in range(r_out)]

    def kernel(x_ref, o_ref):
        M_FE = jnp.uint32(0xFEFEFEFE)
        M_HB = jnp.uint32(0x80808080)
        RED = jnp.uint32(0x1D)
        xs = [x_ref[c, :] for c in range(k)]

        def xor_set(cs):
            acc = xs[cs[0]]
            for c in cs[1:]:
                acc = acc ^ xs[c]
            return acc

        for p in range(r_out):
            y = None
            for j in range(maxj[p], -1, -1):
                if y is not None:
                    hb = y & M_HB
                    y = ((y << 1) & M_FE) ^ ((hb >> 7) * RED)
                if sel[p][j]:
                    u = xor_set(sel[p][j])
                    y = u if y is None else y ^ u
            o_ref[p, :] = y if y is not None else jnp.zeros_like(xs[0])

    return kernel


@functools.partial(jax.jit, static_argnames=("tn", "r_out", "k", "rows_tuple"))
def v6_apply(data_u32, tn, r_out, k, rows_tuple):
    n = data_u32.shape[1]
    return pl.pallas_call(
        make_v6_kernel(rows_tuple, r_out, k),
        grid=(n // tn,),
        in_specs=[pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint32),
    )(data_u32)


def main():
    matrix = gf256.build_code_matrix(K, K + P)
    rows_tuple = tuple(int(v) for v in matrix[K:].reshape(-1))

    data = jax.random.randint(jax.random.PRNGKey(0), (K, W), 0, (1 << 31) - 1,
                              dtype=jnp.int32).astype(jnp.uint32)
    jax.block_until_ready(data)
    payload = K * SHARD

    kern = TpuCodecKernels(K, P)
    data_u8 = np.asarray(data).view(np.uint8).reshape(K, SHARD)
    ref = np.asarray(jax.jit(kern.encode)(jnp.asarray(data_u8))[:, :4096])

    def mk_step(fn):
        def s(d):
            par = fn(d)
            return d.at[0].set(d[0] ^ par[0])
        return jax.jit(s, donate_argnums=0)

    for tn in (4096, 8192, 16384):
        out = np.asarray(v6_apply(data, tn, P, K, rows_tuple)).view(np.uint8)[:, :4096]
        ok = np.array_equal(out, ref)
        t = marginal_chain(mk_step(lambda d: v6_apply(d, tn, P, K, rows_tuple)),
                           data, iters=6)
        print(f"v6 tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms) correct={ok}")


if __name__ == "__main__":
    main()
