"""V2: int8-native unpack (no int32 lane expansion) + MXU pack epilogue."""
import functools, time
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from experiments.kernel_variants import build_perm_bits, K, P
from experiments.kernel_variants3 import marginal_chain
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

SHARD = 64 * 1024 * 1024


def v2_kernel(a_ref, w2_ref, x_ref, o_ref, *, r_out, k):
    x = x_ref[:]  # [k, TN] uint8
    planes = [
        (jax.lax.shift_right_logical(x, jnp.uint8(j)) & jnp.uint8(1)).astype(jnp.int8)
        for j in range(8)
    ]
    bits = jnp.concatenate(planes, axis=0)  # [k*8, TN] int8, row j*k+c
    pad = jnp.zeros((128 - 8 * k, bits.shape[1]), jnp.int8)
    bits = jnp.concatenate([bits, pad], axis=0)
    acc = jax.lax.dot_general(a_ref[:], bits, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)  # [r8, TN]
    par_bits = (acc & 1).astype(jnp.int8)  # [r_out*8, TN]
    out = jax.lax.dot_general(w2_ref[:], par_bits, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)  # [r_out, TN]
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tn", "r_out", "k"))
def v2_apply(a_bits, w2, data, tn=16384, r_out=P, k=K):
    n = data.shape[1]
    return pl.pallas_call(
        functools.partial(v2_kernel, r_out=r_out, k=k),
        grid=(n // tn,),
        in_specs=[
            pl.BlockSpec((r_out * 8, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r_out, r_out * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint8),
    )(a_bits, w2, data)


def pack_weights(r_out):
    # acc rows ordered i*r_out + r ; W2[r, i*r_out + r] = 2^i
    w = np.zeros((r_out, r_out * 8), dtype=np.int8)
    for i in range(8):
        for r in range(r_out):
            v = 1 << i
            w[r, i * r_out + r] = v if v < 128 else -128  # 2^7 wraps, fix below
    return w


def main():
    data = jax.random.randint(jax.random.PRNGKey(0), (K, SHARD), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    jax.block_until_ready(data)
    payload = K * SHARD
    matrix = gf256.build_code_matrix(K, K + P)
    a_perm = jnp.asarray(build_perm_bits(matrix[K:], K))
    w2 = jnp.asarray(pack_weights(P))

    kern = TpuCodecKernels(K, P)
    ref = np.asarray(jax.jit(kern.encode)(data)[:, :4096])

    def mk_step(fn):
        def s(d):
            par = fn(d)
            return d.at[0].set(d[0] ^ par[0])
        return jax.jit(s, donate_argnums=0)

    for tn in (16384, 32768, 65536):
        out = np.asarray(v2_apply(a_perm, w2, data, tn=tn)[:, :4096])
        # -128 stands in for +128: fix sign on byte reinterpret
        ok = np.array_equal(out.astype(np.uint8), ref)
        t = marginal_chain(mk_step(lambda d: v2_apply(a_perm, w2, d, tn=tn)),
                           data, iters=6)
        print(f"v2 tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms) correct={ok}")


if __name__ == "__main__":
    main()
