"""Validate timing methodology on the tunneled TPU.

Checks whether repeated dispatch of the same (fn, args) is deduplicated
by the runtime (which would inflate throughput numbers) by comparing:
  a) 1 call vs N identical calls
  b) N calls on N distinct buffers
"""

import time

import jax
import jax.numpy as jnp

N = 64 * 1024 * 1024


def t(fn, args_list):
    out = fn(args_list[0])
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = [fn(a) for a in args_list]
    jax.block_until_ready(outs)
    return time.perf_counter() - t0


def main():
    xs = [
        jax.random.randint(jax.random.PRNGKey(i), (10, N), 0, 256,
                           dtype=jnp.int32).astype(jnp.uint8)
        for i in range(4)
    ]
    jax.block_until_ready(xs)
    probe = jax.jit(lambda x: x ^ jnp.uint8(1))

    t1 = t(probe, [xs[0]])
    t4_same = t(probe, [xs[0]] * 4)
    t4_diff = t(probe, xs)
    tr = 2 * 10 * N
    print(f"probe 1 call        : {t1*1e3:8.3f} ms  {tr/t1/1e9:9.1f} GB/s traffic")
    print(f"probe 4 same calls  : {t4_same*1e3:8.3f} ms  {4*tr/t4_same/1e9:9.1f} GB/s")
    print(f"probe 4 diff calls  : {t4_diff*1e3:8.3f} ms  {4*tr/t4_diff/1e9:9.1f} GB/s")


if __name__ == "__main__":
    main()
