"""Kernel variants with tunnel-latency-corrected timing.

Times K chained iterations vs 1, reports marginal per-iter throughput.
Chains iterations through a data dependency (feed output back into a
dummy xor with the input) so the runtime cannot overlap/dedupe them.
"""
import functools, time
import jax, jax.numpy as jnp
import numpy as np
from experiments.kernel_variants import fused_apply, build_perm_bits, K, P
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

SHARD = 64 * 1024 * 1024


def marginal(fn, data, iters=8):
    """fn: data -> parity. Chain: data ^= broadcast of parity row 0."""
    @jax.jit
    def step(d):
        par = fn(d)
        # cheap dependency: xor first parity row into shard 0
        return d.at[0].set(d[0] ^ par[0])

    def run(k):
        d = data
        for _ in range(k):
            d = step(d)
        return int(jax.device_get(d[0, 0]))

    run(1)  # warm
    t0 = time.perf_counter(); run(1); t1 = time.perf_counter() - t0
    t0 = time.perf_counter(); run(1 + iters); t2 = time.perf_counter() - t0
    return (t2 - t1) / iters


def main():
    data = jax.random.randint(jax.random.PRNGKey(0), (K, SHARD), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    jax.block_until_ready(data)
    payload = K * SHARD

    probe = lambda d: d[:4] ^ jnp.uint8(1)
    t = marginal(probe, data)
    print(f"probe(read 10N+write4N): {14*SHARD/t/1e9:9.1f} GB/s traffic")

    kern = TpuCodecKernels(K, P)
    t = marginal(kern.encode, data)
    print(f"xla-unfused   : {payload/t/1e9:8.2f} GB/s payload")

    matrix = gf256.build_code_matrix(K, K + P)
    a_perm = jnp.asarray(build_perm_bits(matrix[K:], K))
    for tn in (8192, 16384, 32768, 65536):
        t = marginal(lambda d: fused_apply(a_perm, d, tn=tn), data)
        print(f"pallas tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload")


if __name__ == "__main__":
    main()
