"""Which packed-i8 vector ops does Mosaic legalize?"""
import functools
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TN = 1024

def try_kernel(name, body):
    def kern(x_ref, o_ref):
        o_ref[:] = body(x_ref[:])
    try:
        f = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((8, TN), jnp.int8),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        x = jnp.arange(8 * TN, dtype=jnp.int32).reshape(8, TN).astype(jnp.uint8)
        out = np.asarray(jax.jit(f)(x))
        print(f"{name:30s} OK   sample={out[0,:6]}")
    except Exception as e:
        msg = str(e).split("\n")[0][:100]
        print(f"{name:30s} FAIL {msg}")

try_kernel("and_i8", lambda x: (x & jnp.uint8(4)).astype(jnp.int8))
try_kernel("cmp_ne_i8", lambda x: ((x & jnp.uint8(4)) != 0).astype(jnp.int8))
try_kernel("cmp_eq_i8", lambda x: ((x & jnp.uint8(4)) == jnp.uint8(4)).astype(jnp.int8))
try_kernel("min_i8", lambda x: jnp.minimum(x & jnp.uint8(4), jnp.uint8(1)).astype(jnp.int8))
try_kernel("mul_i8", lambda x: ((x & jnp.uint8(1)) * jnp.uint8(3)).astype(jnp.int8))
