"""V4: packed-i8 compare-based unpack + MXU matmul + MXU pack epilogue."""
import functools, time
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from experiments.kernel_variants import build_perm_bits, K, P
from experiments.kernel_variants3 import marginal_chain
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

SHARD = 64 * 1024 * 1024
KPAD = 96  # 80 bit-rows padded to a multiple of 32


def v4_kernel(a_ref, w2_ref, x_ref, o_ref, *, r_out, k):
    x = x_ref[:]  # [k, TN] uint8
    planes = [((x & jnp.uint8(1 << j)) != 0).astype(jnp.int8) for j in range(8)]
    bits = jnp.concatenate(
        planes + [jnp.zeros((KPAD - 8 * k, x.shape[1]), jnp.int8)], axis=0)
    acc = jax.lax.dot_general(a_ref[:], bits, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)  # [r8, TN]
    par_bits = (acc & 1).astype(jnp.int8)
    out = jax.lax.dot_general(w2_ref[:], par_bits, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)  # [r_out, TN]
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tn", "r_out", "k"))
def v4_apply(a_bits, w2, data, tn=16384, r_out=P, k=K):
    n = data.shape[1]
    return pl.pallas_call(
        functools.partial(v4_kernel, r_out=r_out, k=k),
        grid=(n // tn,),
        in_specs=[
            pl.BlockSpec((r_out * 8, KPAD), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r_out, r_out * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r_out, tn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_out, n), jnp.uint8),
    )(a_bits, w2, data)


def pack_weights(r_out):
    # acc rows i*r_out + r ; W2[r, i*r_out+r] = 2^i mod 256 (int8 two's compl)
    w = np.zeros((r_out, r_out * 8), dtype=np.int16)
    for i in range(8):
        for r in range(r_out):
            w[r, i * r_out + r] = 1 << i
    return w.astype(np.uint8).view(np.int8)


def perm96(matrix_rows, k):
    full = build_perm_bits(matrix_rows, k)  # [R8, 128]
    return np.ascontiguousarray(full[:, :KPAD])


def main():
    data = jax.random.randint(jax.random.PRNGKey(0), (K, SHARD), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    jax.block_until_ready(data)
    payload = K * SHARD
    matrix = gf256.build_code_matrix(K, K + P)
    a_perm = jnp.asarray(perm96(matrix[K:], K))
    w2 = jnp.asarray(pack_weights(P))

    kern = TpuCodecKernels(K, P)
    ref = np.asarray(jax.jit(kern.encode)(data)[:, :4096])

    def mk_step(fn):
        def s(d):
            par = fn(d)
            return d.at[0].set(d[0] ^ par[0])
        return jax.jit(s, donate_argnums=0)

    for tn in (8192, 16384, 32768, 65536, 131072):
        out = np.asarray(v4_apply(a_perm, w2, data, tn=tn)[:, :4096]).astype(np.uint8)
        ok = np.array_equal(out, ref)
        t = marginal_chain(mk_step(lambda d: v4_apply(a_perm, w2, d, tn=tn)),
                           data, iters=6)
        print(f"v4 tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms) correct={ok}")


if __name__ == "__main__":
    main()
