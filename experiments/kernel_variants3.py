"""Donation-chained marginal timing + raw HBM bandwidth probe."""
import functools, time
import jax, jax.numpy as jnp
import numpy as np
from experiments.kernel_variants import fused_apply, build_perm_bits, K, P
from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

SHARD = 64 * 1024 * 1024


def marginal_chain(step, init, iters=8):
    """step: donated x -> x'. Returns marginal seconds/iter."""
    copy = jax.jit(lambda a: a ^ jnp.zeros((), a.dtype).astype(a.dtype)) \
        if init.dtype == jnp.uint8 else jax.jit(lambda a: a + jnp.zeros((), a.dtype))
    def run(k):
        x = copy(init)
        for _ in range(k):
            x = step(x)
        return int(jax.device_get(jax.numpy.ravel(x)[0]))
    run(2)  # warm (donated buffer shape stable after first)
    t0 = time.perf_counter(); run(1); t1 = time.perf_counter() - t0
    t0 = time.perf_counter(); run(1 + iters); t2 = time.perf_counter() - t0
    return (t2 - t1) / iters


def main():
    # --- raw BW probe: f32 in-place increment, 1 GiB array ---
    M = 256 * 1024 * 1024  # f32 elems = 1 GiB
    x0 = jnp.zeros((M,), jnp.float32)
    incr = jax.jit(lambda x: x + 1.0, donate_argnums=0)
    t = marginal_chain(incr, x0, iters=8)
    print(f"f32 R+W probe : {2*4*M/t/1e9:9.1f} GB/s traffic ({t*1e3:.2f} ms)")
    del x0

    data = jax.random.randint(jax.random.PRNGKey(0), (K, SHARD), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    jax.block_until_ready(data)
    payload = K * SHARD

    # u8 probe: read 10N write 10N donated
    u8probe = jax.jit(lambda d: d ^ jnp.uint8(3), donate_argnums=0)
    t = marginal_chain(u8probe, data, iters=8)
    print(f"u8  R+W probe : {2*payload/t/1e9:9.1f} GB/s traffic ({t*1e3:.2f} ms)")

    kern = TpuCodecKernels(K, P)
    matrix = gf256.build_code_matrix(K, K + P)
    a_perm = jnp.asarray(build_perm_bits(matrix[K:], K))

    def mk_step(fn):
        def s(d):
            par = fn(d)
            return d.at[0].set(d[0] ^ par[0])
        return jax.jit(s, donate_argnums=0)

    t = marginal_chain(mk_step(kern.encode), data, iters=6)
    print(f"xla-unfused   : {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms)")
    for tn in (16384, 32768, 65536):
        t = marginal_chain(mk_step(lambda d: fused_apply(a_perm, d, tn=tn)),
                           data, iters=6)
        print(f"pallas tn={tn:6d}: {payload/t/1e9:8.2f} GB/s payload ({t*1e3:.2f} ms)")


if __name__ == "__main__":
    main()
