"""HA master tests: compact raft election + replicated MaxVolumeId.

Reference role: weed/server/raft_server.go + topology/cluster_commands.go.
The failover test is the VERDICT's acceptance bar: 3 in-process
masters, kill the leader, assigns keep working, no volume-id reuse.
"""

import socket
import time

import pytest

from seaweedfs_tpu.cluster.raft import NotLeader, RaftNode


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def wait_for(cond, timeout=45.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestRaftCore:
    """RaftNode alone, with gRPC servers bound per node."""

    def _mk_cluster(self, n, tmp_path):
        import grpc as grpc_mod
        from concurrent import futures

        from seaweedfs_tpu.pb import rpc

        addrs = [f"127.0.0.1:{free_port()}" for _ in range(n)]
        nodes, servers, applied = [], [], []
        for addr in addrs:
            log: list = []
            applied.append(log)
            node = RaftNode(
                addr,
                addrs,
                (lambda lg: (lambda cmd: lg.append(cmd)))(log),
                data_dir=str(tmp_path),
            )
            server = grpc_mod.server(futures.ThreadPoolExecutor(max_workers=8))
            server.add_generic_rpc_handlers(
                (rpc.servicer_handler(rpc.RAFT_SERVICE, rpc.RAFT_METHODS, node),)
            )
            server.add_insecure_port(rpc.grpc_address(addr))
            server.start()
            nodes.append(node)
            servers.append(server)
        for node in nodes:
            node.start()
        return addrs, nodes, servers

    def _teardown(self, nodes, servers):
        for node in nodes:
            node.stop()
        for server in servers:
            server.stop(grace=0)

    def test_elects_single_leader_and_replicates(self, tmp_path):
        addrs, nodes, servers = self._mk_cluster(3, tmp_path)
        try:
            assert wait_for(
                lambda: sum(1 for n in nodes if n.is_leader) == 1
            ), "no single leader elected"
            leader = next(n for n in nodes if n.is_leader)
            leader.propose({"name": "MaxVolumeId", "maxVolumeId": 7})
            assert wait_for(
                lambda: all(
                    any(c.get("maxVolumeId") == 7 for c in n_applied)
                    for n_applied in self._applied_lists(nodes)
                )
            )
            # followers reject proposals with the leader hint
            follower = next(n for n in nodes if not n.is_leader)
            with pytest.raises(NotLeader):
                follower.propose({"name": "MaxVolumeId", "maxVolumeId": 8})
        finally:
            self._teardown(nodes, servers)

    def _applied_lists(self, nodes):
        # apply_fn closures append into per-node lists; recover them by
        # proposing through the leader and watching last_applied instead
        out = []
        for n in nodes:
            lst = []
            for i in range(1, n.last_applied + 1):
                e = n._entry_at(i)
                if e is not None and e.command:
                    import json

                    lst.append(json.loads(e.command))
            out.append(lst)
        return out

    def test_leader_failover(self, tmp_path):
        addrs, nodes, servers = self._mk_cluster(3, tmp_path)
        try:
            assert wait_for(lambda: sum(1 for n in nodes if n.is_leader) == 1)
            leader = next(n for n in nodes if n.is_leader)
            leader.propose({"name": "MaxVolumeId", "maxVolumeId": 3})
            # kill the leader (node + its grpc endpoint)
            idx = nodes.index(leader)
            leader.stop()
            servers[idx].stop(grace=0)
            rest = [n for i, n in enumerate(nodes) if i != idx]
            assert wait_for(
                lambda: sum(1 for n in rest if n.is_leader) == 1, timeout=45
            ), "no new leader after failover"
            new_leader = next(n for n in rest if n.is_leader)
            # the committed entry survived, and new proposals commit
            assert new_leader.last_applied >= 1
            new_leader.propose({"name": "MaxVolumeId", "maxVolumeId": 4})
        finally:
            self._teardown(nodes, servers)


class TestHaMasters:
    """3 MasterServer instances with raft + a volume server."""

    @pytest.fixture()
    def ha_cluster(self, tmp_path_factory):
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        ports = [free_port() for _ in range(3)]
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        masters = [
            MasterServer(
                port=p,
                volume_size_limit_mb=64,
                peers=peers,
                raft_dir=str(tmp_path_factory.mktemp(f"raft{p}")),
            )
            for p in ports
        ]
        for m in masters:
            m.start()
        assert wait_for(
            lambda: sum(1 for m in masters if m.is_leader) == 1, timeout=45
        ), "no leader among masters"
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("havs"))],
            port=free_port(),
            master=peers,  # all seeds; follows leader hints
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        vs.start()
        leader = next(m for m in masters if m.is_leader)
        assert wait_for(
            lambda: len(leader.topology.data_nodes()) == 1, timeout=45
        ), "volume server did not register with the leader"
        yield masters, vs
        vs.stop()
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass

    def test_assign_via_any_master_and_failover(self, ha_cluster):
        from seaweedfs_tpu.client import operation as op

        masters, vs = ha_cluster
        leader = next(m for m in masters if m.is_leader)
        followers = [m for m in masters if not m.is_leader]

        # assign through a FOLLOWER: proxied to the leader
        ar1 = op.assign(f"127.0.0.1:{followers[0].port}")
        assert ar1.fid
        vid1 = int(ar1.fid.split(",")[0])
        ur = op.upload(f"{ar1.url}/{ar1.fid}", b"ha payload")
        assert not ur.error

        # kill the leader
        leader.stop()
        rest = [m for m in masters if m is not leader]
        assert wait_for(
            lambda: sum(1 for m in rest if m.is_leader) == 1, timeout=45
        ), "no failover leader"
        new_leader = next(m for m in rest if m.is_leader)

        # the volume server re-registers with the new leader
        assert wait_for(
            lambda: len(new_leader.topology.data_nodes()) == 1, timeout=45
        ), "volume server did not follow the new leader"

        # assigns keep working via the new leader, and if growth
        # allocates new volumes their ids are NOT reused (replicated
        # max-vid survived the failover)
        ar2 = op.assign(f"127.0.0.1:{new_leader.port}")
        assert ar2.fid
        vid2 = int(ar2.fid.split(",")[0])
        max_before = max(
            vid1, new_leader.topology.id_gen.peek()
        )
        # force growth of a fresh volume in a new collection: its vid
        # must be strictly greater than anything allocated pre-failover
        ar3 = op.assign(f"127.0.0.1:{new_leader.port}", collection="post_failover")
        vid3 = int(ar3.fid.split(",")[0])
        assert vid3 > 0
        assert new_leader.topology.id_gen.peek() >= max_before
        assert vid3 != vid1 or vid2 == vid1  # fresh collection => fresh vid
        ur2 = op.upload(f"{ar3.url}/{ar3.fid}", b"post failover")
        assert not ur2.error


    def test_submit_and_vacuum_proxied_through_follower(self, ha_cluster):
        """/submit works via any master (assign proxies to the leader
        internally) and /vol/vacuum on a follower is HTTP-proxied to
        the leader (followers hold no topology)."""
        import json
        import urllib.request

        masters, vs = ha_cluster
        follower = next(m for m in masters if not m.is_leader)

        req = urllib.request.Request(
            f"http://127.0.0.1:{follower.port}/submit",
            data=b"via follower",
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            res = json.loads(r.read())
        assert res.get("fid"), res
        with urllib.request.urlopen(f"http://{res['fileUrl']}", timeout=10) as r:
            assert r.read() == b"via follower"

        with urllib.request.urlopen(
            f"http://127.0.0.1:{follower.port}/vol/vacuum", timeout=60
        ) as r:
            res = json.loads(r.read())
        assert "vacuumed" in res and "Topology" in res, res


class TestFilerHaFailover:
    def test_filer_writes_survive_leader_loss(self, tmp_path_factory):
        """A filer configured with all three masters keeps serving
        writes after the leader dies (rotation + leader proxy)."""
        import urllib.request

        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        ports = [free_port() for _ in range(3)]
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        masters = [
            MasterServer(
                port=p,
                volume_size_limit_mb=64,
                peers=peers,
                raft_dir=str(tmp_path_factory.mktemp(f"fha{p}")),
            )
            for p in ports
        ]
        for m in masters:
            m.start()
        vs = filer = None
        try:
            assert wait_for(
                lambda: sum(1 for m in masters if m.is_leader) == 1, timeout=45
            )
            vs = VolumeServer(
                [str(tmp_path_factory.mktemp("fhavs"))],
                port=free_port(),
                master=peers,
                heartbeat_interval=0.2,
                max_volume_counts=[100],
            )
            vs.start()
            leader = next(m for m in masters if m.is_leader)
            assert wait_for(
                lambda: len(leader.topology.data_nodes()) == 1, timeout=45
            )
            filer = FilerServer(
                [f"127.0.0.1:{p}" for p in ports],
                port=free_port(),
                store="memory",
            )
            filer.start()

            def put(path, data):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{filer.port}{path}",
                    data=data,
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=45).close()

            put("/a/pre.txt", b"before failover")

            leader.stop()
            rest = [m for m in masters if m is not leader]
            assert wait_for(
                lambda: sum(1 for m in rest if m.is_leader) == 1, timeout=45
            )
            new_leader = next(m for m in rest if m.is_leader)
            assert wait_for(
                lambda: len(new_leader.topology.data_nodes()) == 1, timeout=45
            )

            put("/a/post.txt", b"after failover")
            with urllib.request.urlopen(
                f"http://127.0.0.1:{filer.port}/a/post.txt", timeout=45
            ) as r:
                assert r.read() == b"after failover"
        finally:
            if filer:
                filer.stop()
            if vs:
                vs.stop()
            for m in masters:
                try:
                    m.stop()
                except Exception:
                    pass


class TestRaftLogRepair:
    """Direct AppendEntries-handler checks for the paper's §5.3
    conflict rules — stale divergent suffixes must truncate, and acks
    must never overstate replication."""

    def _node(self, tmp_path):
        n = RaftNode("127.0.0.1:19333", ["127.0.0.1:19333", "127.0.0.1:19334"],
                     lambda cmd: None, data_dir=str(tmp_path))
        return n

    def _entry(self, term, index, cmd="{}"):
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        return rpb.LogEntry(term=term, index=index, command=cmd)

    def test_conflicting_suffix_truncated(self, tmp_path):
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        # follower holds entries 1-3 from term 1
        n.current_term = 1
        n.log = [self._entry(1, 1), self._entry(1, 2), self._entry(1, 3)]

        # new leader (term 2) overwrites from index 2
        req = rpb.AppendEntriesRequest(
            term=2,
            leader_id="127.0.0.1:19334",
            prev_log_index=1,
            prev_log_term=1,
            leader_commit=1,
        )
        req.entries.add(term=2, index=2, command='{"name":"Noop"}')
        resp = n.AppendEntries(req)
        assert resp.success
        # stale index-3 entry is gone; log = [t1 i1, t2 i2]
        assert [(e.term, e.index) for e in n.log] == [(1, 1), (2, 2)]
        # ack covers exactly prev + entries, not any imagined suffix
        assert resp.match_index == 2

    def test_gap_rejected(self, tmp_path):
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 1
        n.log = [self._entry(1, 1)]
        req = rpb.AppendEntriesRequest(
            term=1,
            leader_id="127.0.0.1:19334",
            prev_log_index=5,  # follower has no entry 5
            prev_log_term=1,
        )
        resp = n.AppendEntries(req)
        assert not resp.success

    def test_heartbeat_does_not_overstate_match(self, tmp_path):
        """The §5.4 safety case behind the match_index fix: a follower
        with a stale suffix must not ack it on an empty heartbeat."""
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 3
        # entries 1-2 consistent with the leader; 3-4 are stale term-1
        # leftovers the leader knows nothing about
        n.log = [
            self._entry(2, 1),
            self._entry(2, 2),
            self._entry(1, 3),
            self._entry(1, 4),
        ]
        req = rpb.AppendEntriesRequest(
            term=3,
            leader_id="127.0.0.1:19334",
            prev_log_index=2,
            prev_log_term=2,
            leader_commit=0,
        )
        resp = n.AppendEntries(req)
        assert resp.success
        assert resp.match_index == 2  # NOT 4

    def test_stale_term_rejected_with_current_term(self, tmp_path):
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 5
        resp = n.AppendEntries(
            rpb.AppendEntriesRequest(term=3, leader_id="x", prev_log_index=0)
        )
        assert not resp.success and resp.term == 5

    def test_vote_denied_to_stale_log(self, tmp_path):
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 2
        n.log = [self._entry(2, 1)]
        resp = n.RequestVote(
            rpb.RequestVoteRequest(
                term=3,
                candidate_id="127.0.0.1:19334",
                last_log_index=5,
                last_log_term=1,  # older last term than ours
            )
        )
        assert not resp.vote_granted
        # but an up-to-date candidate gets the vote in the same term
        resp = n.RequestVote(
            rpb.RequestVoteRequest(
                term=3,
                candidate_id="127.0.0.1:19334",
                last_log_index=1,
                last_log_term=2,
            )
        )
        assert resp.vote_granted


class TestStaleLeaderStepsDown:
    """Partition-heal at the handler level: a leader isolated during a
    new election must step down the moment it hears a higher term —
    from either RPC — and a stale candidate must not split the new
    leader's cluster."""

    def _node(self, tmp_path):
        return RaftNode(
            "127.0.0.1:19333",
            ["127.0.0.1:19333", "127.0.0.1:19334", "127.0.0.1:19335"],
            lambda cmd: None,
            data_dir=str(tmp_path),
        )

    def test_leader_steps_down_on_higher_term_append(self, tmp_path):
        from seaweedfs_tpu.cluster.raft import FOLLOWER, LEADER
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 2
        n.role = LEADER
        resp = n.AppendEntries(
            rpb.AppendEntriesRequest(
                term=3, leader_id="127.0.0.1:19334",
                prev_log_index=0, prev_log_term=0,
            )
        )
        assert resp.success
        assert n.role == FOLLOWER
        assert n.current_term == 3
        assert n.leader_id == "127.0.0.1:19334"

    def test_leader_steps_down_on_higher_term_vote(self, tmp_path):
        from seaweedfs_tpu.cluster.raft import FOLLOWER, LEADER
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 2
        n.role = LEADER
        resp = n.RequestVote(
            rpb.RequestVoteRequest(
                term=3, candidate_id="127.0.0.1:19335",
                last_log_index=0, last_log_term=0,
            )
        )
        assert n.role == FOLLOWER
        assert n.current_term == 3
        assert resp.vote_granted  # our log is empty too: candidate is current

    def test_stale_candidate_cannot_disrupt_newer_term(self, tmp_path):
        """A node returning from a partition with an old term must get
        term=current back and no vote (it then becomes a follower of
        the real leader instead of forcing a re-election)."""
        from seaweedfs_tpu.pb import raft_pb2 as rpb

        n = self._node(tmp_path)
        n.current_term = 5
        resp = n.RequestVote(
            rpb.RequestVoteRequest(
                term=3, candidate_id="127.0.0.1:19334",
                last_log_index=9, last_log_term=3,
            )
        )
        assert not resp.vote_granted
        assert resp.term == 5
