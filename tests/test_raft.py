"""HA master tests: compact raft election + replicated MaxVolumeId.

Reference role: weed/server/raft_server.go + topology/cluster_commands.go.
The failover test is the VERDICT's acceptance bar: 3 in-process
masters, kill the leader, assigns keep working, no volume-id reuse.
"""

import socket
import time

import pytest

from seaweedfs_tpu.cluster.raft import NotLeader, RaftNode


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_for(cond, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestRaftCore:
    """RaftNode alone, with gRPC servers bound per node."""

    def _mk_cluster(self, n, tmp_path):
        import grpc as grpc_mod
        from concurrent import futures

        from seaweedfs_tpu.pb import rpc

        addrs = [f"127.0.0.1:{free_port()}" for _ in range(n)]
        nodes, servers, applied = [], [], []
        for addr in addrs:
            log: list = []
            applied.append(log)
            node = RaftNode(
                addr,
                addrs,
                (lambda lg: (lambda cmd: lg.append(cmd)))(log),
                data_dir=str(tmp_path),
            )
            server = grpc_mod.server(futures.ThreadPoolExecutor(max_workers=8))
            server.add_generic_rpc_handlers(
                (rpc.servicer_handler(rpc.RAFT_SERVICE, rpc.RAFT_METHODS, node),)
            )
            server.add_insecure_port(rpc.grpc_address(addr))
            server.start()
            nodes.append(node)
            servers.append(server)
        for node in nodes:
            node.start()
        return addrs, nodes, servers

    def _teardown(self, nodes, servers):
        for node in nodes:
            node.stop()
        for server in servers:
            server.stop(grace=0)

    def test_elects_single_leader_and_replicates(self, tmp_path):
        addrs, nodes, servers = self._mk_cluster(3, tmp_path)
        try:
            assert wait_for(
                lambda: sum(1 for n in nodes if n.is_leader) == 1
            ), "no single leader elected"
            leader = next(n for n in nodes if n.is_leader)
            leader.propose({"name": "MaxVolumeId", "maxVolumeId": 7})
            assert wait_for(
                lambda: all(
                    any(c.get("maxVolumeId") == 7 for c in n_applied)
                    for n_applied in self._applied_lists(nodes)
                )
            )
            # followers reject proposals with the leader hint
            follower = next(n for n in nodes if not n.is_leader)
            with pytest.raises(NotLeader):
                follower.propose({"name": "MaxVolumeId", "maxVolumeId": 8})
        finally:
            self._teardown(nodes, servers)

    def _applied_lists(self, nodes):
        # apply_fn closures append into per-node lists; recover them by
        # proposing through the leader and watching last_applied instead
        out = []
        for n in nodes:
            lst = []
            for i in range(1, n.last_applied + 1):
                e = n._entry_at(i)
                if e is not None and e.command:
                    import json

                    lst.append(json.loads(e.command))
            out.append(lst)
        return out

    def test_leader_failover(self, tmp_path):
        addrs, nodes, servers = self._mk_cluster(3, tmp_path)
        try:
            assert wait_for(lambda: sum(1 for n in nodes if n.is_leader) == 1)
            leader = next(n for n in nodes if n.is_leader)
            leader.propose({"name": "MaxVolumeId", "maxVolumeId": 3})
            # kill the leader (node + its grpc endpoint)
            idx = nodes.index(leader)
            leader.stop()
            servers[idx].stop(grace=0)
            rest = [n for i, n in enumerate(nodes) if i != idx]
            assert wait_for(
                lambda: sum(1 for n in rest if n.is_leader) == 1, timeout=15
            ), "no new leader after failover"
            new_leader = next(n for n in rest if n.is_leader)
            # the committed entry survived, and new proposals commit
            assert new_leader.last_applied >= 1
            new_leader.propose({"name": "MaxVolumeId", "maxVolumeId": 4})
        finally:
            self._teardown(nodes, servers)


class TestHaMasters:
    """3 MasterServer instances with raft + a volume server."""

    @pytest.fixture()
    def ha_cluster(self, tmp_path_factory):
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        ports = [free_port() for _ in range(3)]
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        masters = [
            MasterServer(
                port=p,
                volume_size_limit_mb=64,
                peers=peers,
                raft_dir=str(tmp_path_factory.mktemp(f"raft{p}")),
            )
            for p in ports
        ]
        for m in masters:
            m.start()
        assert wait_for(
            lambda: sum(1 for m in masters if m.is_leader) == 1, timeout=15
        ), "no leader among masters"
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("havs"))],
            port=free_port(),
            master=peers,  # all seeds; follows leader hints
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        vs.start()
        leader = next(m for m in masters if m.is_leader)
        assert wait_for(
            lambda: len(leader.topology.data_nodes()) == 1, timeout=15
        ), "volume server did not register with the leader"
        yield masters, vs
        vs.stop()
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass

    def test_assign_via_any_master_and_failover(self, ha_cluster):
        from seaweedfs_tpu.client import operation as op

        masters, vs = ha_cluster
        leader = next(m for m in masters if m.is_leader)
        followers = [m for m in masters if not m.is_leader]

        # assign through a FOLLOWER: proxied to the leader
        ar1 = op.assign(f"127.0.0.1:{followers[0].port}")
        assert ar1.fid
        vid1 = int(ar1.fid.split(",")[0])
        ur = op.upload(f"{ar1.url}/{ar1.fid}", b"ha payload")
        assert not ur.error

        # kill the leader
        leader.stop()
        rest = [m for m in masters if m is not leader]
        assert wait_for(
            lambda: sum(1 for m in rest if m.is_leader) == 1, timeout=20
        ), "no failover leader"
        new_leader = next(m for m in rest if m.is_leader)

        # the volume server re-registers with the new leader
        assert wait_for(
            lambda: len(new_leader.topology.data_nodes()) == 1, timeout=20
        ), "volume server did not follow the new leader"

        # assigns keep working via the new leader, and if growth
        # allocates new volumes their ids are NOT reused (replicated
        # max-vid survived the failover)
        ar2 = op.assign(f"127.0.0.1:{new_leader.port}")
        assert ar2.fid
        vid2 = int(ar2.fid.split(",")[0])
        max_before = max(
            vid1, new_leader.topology.id_gen.peek()
        )
        # force growth of a fresh volume in a new collection: its vid
        # must be strictly greater than anything allocated pre-failover
        ar3 = op.assign(f"127.0.0.1:{new_leader.port}", collection="post_failover")
        vid3 = int(ar3.fid.split(",")[0])
        assert vid3 > 0
        assert new_leader.topology.id_gen.peek() >= max_before
        assert vid3 != vid1 or vid2 == vid1  # fresh collection => fresh vid
        ur2 = op.upload(f"{ar3.url}/{ar3.fid}", b"post failover")
        assert not ur2.error


class TestFilerHaFailover:
    def test_filer_writes_survive_leader_loss(self, tmp_path_factory):
        """A filer configured with all three masters keeps serving
        writes after the leader dies (rotation + leader proxy)."""
        import urllib.request

        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        ports = [free_port() for _ in range(3)]
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        masters = [
            MasterServer(
                port=p,
                volume_size_limit_mb=64,
                peers=peers,
                raft_dir=str(tmp_path_factory.mktemp(f"fha{p}")),
            )
            for p in ports
        ]
        for m in masters:
            m.start()
        vs = filer = None
        try:
            assert wait_for(
                lambda: sum(1 for m in masters if m.is_leader) == 1, timeout=15
            )
            vs = VolumeServer(
                [str(tmp_path_factory.mktemp("fhavs"))],
                port=free_port(),
                master=peers,
                heartbeat_interval=0.2,
                max_volume_counts=[100],
            )
            vs.start()
            leader = next(m for m in masters if m.is_leader)
            assert wait_for(
                lambda: len(leader.topology.data_nodes()) == 1, timeout=15
            )
            filer = FilerServer(
                [f"127.0.0.1:{p}" for p in ports],
                port=free_port(),
                store="memory",
            )
            filer.start()

            def put(path, data):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{filer.port}{path}",
                    data=data,
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=15).close()

            put("/a/pre.txt", b"before failover")

            leader.stop()
            rest = [m for m in masters if m is not leader]
            assert wait_for(
                lambda: sum(1 for m in rest if m.is_leader) == 1, timeout=20
            )
            new_leader = next(m for m in rest if m.is_leader)
            assert wait_for(
                lambda: len(new_leader.topology.data_nodes()) == 1, timeout=20
            )

            put("/a/post.txt", b"after failover")
            with urllib.request.urlopen(
                f"http://127.0.0.1:{filer.port}/a/post.txt", timeout=15
            ) as r:
                assert r.read() == b"after failover"
        finally:
            if filer:
                filer.stop()
            if vs:
                vs.stop()
            for m in masters:
                try:
                    m.stop()
                except Exception:
                    pass
