"""HTTP Range reads (volume + filer) and on-read image resizing.

Reference roles: volume_server_handlers_read.go:30-128 (ranged reads
via http.ServeContent), images/resizing.go:15 (?width=&height=&mode=),
images/orientation.go:14 (EXIF fix on .jpg upload)."""

import io
import socket
import time
import urllib.error
import urllib.request

import pytest


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read(), dict(r.headers)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    master = MasterServer(port=free_port(), volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("imgvs"))],
        port=free_port(),
        master=f"127.0.0.1:{master.port}",
        heartbeat_interval=0.2,
        max_volume_counts=[100],
    )
    vs.start()
    deadline = time.time() + 45
    while time.time() < deadline and len(master.topology.data_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer([f"127.0.0.1:{master.port}"], port=free_port(), store="memory")
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


class TestVolumeRange:
    @pytest.fixture(scope="class")
    def blob(self, stack):
        from seaweedfs_tpu.client import operation as op

        master, vs, _ = stack
        payload = bytes(range(256)) * 64  # 16 KiB
        ar = op.assign(f"127.0.0.1:{master.port}")
        assert not op.upload(f"{ar.url}/{ar.fid}", payload, jwt=ar.auth).error
        return f"http://{ar.url}/{ar.fid}", payload

    def test_full_read_advertises_ranges(self, blob):
        url, payload = blob
        status, body, headers = _get(url)
        assert status == 200 and body == payload
        assert headers.get("Accept-Ranges") == "bytes"

    def test_closed_range(self, blob):
        url, payload = blob
        status, body, headers = _get(url, {"Range": "bytes=100-299"})
        assert status == 206
        assert body == payload[100:300]
        assert headers["Content-Range"] == f"bytes 100-299/{len(payload)}"

    def test_open_and_suffix_ranges(self, blob):
        url, payload = blob
        _, body, _ = _get(url, {"Range": f"bytes={len(payload) - 50}-"})
        assert body == payload[-50:]
        _, body, _ = _get(url, {"Range": "bytes=-77"})
        assert body == payload[-77:]

    def test_unsatisfiable_range(self, blob):
        url, payload = blob
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url, {"Range": f"bytes={len(payload) + 10}-"})
        assert e.value.code == 416


class TestFilerRange:
    @pytest.fixture(scope="class")
    def filer_file(self, stack):
        _, _, filer = stack
        payload = bytes(range(256)) * 32
        req = urllib.request.Request(
            f"http://127.0.0.1:{filer.port}/r/data.bin", data=payload, method="POST"
        )
        urllib.request.urlopen(req, timeout=10).close()
        return f"http://127.0.0.1:{filer.port}/r/data.bin", payload

    def test_closed_range(self, filer_file):
        url, payload = filer_file
        status, body, headers = _get(url, {"Range": "bytes=10-19"})
        assert status == 206 and body == payload[10:20]
        assert headers["Content-Range"] == f"bytes 10-19/{len(payload)}"

    def test_suffix_range(self, filer_file):
        url, payload = filer_file
        status, body, _ = _get(url, {"Range": "bytes=-100"})
        assert status == 206 and body == payload[-100:]

    def test_unsatisfiable(self, filer_file):
        url, payload = filer_file
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url, {"Range": f"bytes={len(payload)}-"})
        assert e.value.code == 416


def _png_bytes(w, h, color=(255, 0, 0)):
    from PIL import Image

    img = Image.new("RGB", (w, h), color)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


class TestImageResize:
    def test_resized_downscales(self):
        from PIL import Image

        from seaweedfs_tpu import images

        data = _png_bytes(200, 100)
        out, w, h = images.resized(".png", data, 100, 0)
        assert (w, h) == (100, 50)
        img = Image.open(io.BytesIO(out))
        assert img.size == (100, 50)

    def test_resized_passthrough_when_smaller(self):
        from seaweedfs_tpu import images

        data = _png_bytes(50, 50)
        out, w, h = images.resized(".png", data, 100, 100)
        assert out == data and (w, h) == (50, 50)

    def test_fit_and_fill_modes(self):
        from PIL import Image

        from seaweedfs_tpu import images

        data = _png_bytes(400, 200)
        out, _, _ = images.resized(".png", data, 100, 100, "fit")
        assert Image.open(io.BytesIO(out)).size == (100, 50)
        out, _, _ = images.resized(".png", data, 100, 100, "fill")
        assert Image.open(io.BytesIO(out)).size == (100, 100)

    def test_served_resize_on_volume_get(self, stack):
        from PIL import Image

        from seaweedfs_tpu.client import operation as op

        master, vs, _ = stack
        ar = op.assign(f"127.0.0.1:{master.port}")
        data = _png_bytes(300, 150)
        assert not op.upload(
            f"{ar.url}/{ar.fid}",
            data,
            filename="pic.png",
            mime="image/png",
            jwt=ar.auth,
        ).error
        _, body, _ = _get(f"http://{ar.url}/{ar.fid}?width=60")
        assert Image.open(io.BytesIO(body)).size == (60, 30)
        # mode=fit via query
        _, body, _ = _get(f"http://{ar.url}/{ar.fid}?width=50&height=50&mode=fit")
        assert Image.open(io.BytesIO(body)).size == (50, 25)

    def test_jpg_orientation_fixed_on_upload(self, stack):
        from PIL import Image

        from seaweedfs_tpu.client import operation as op

        master, vs, _ = stack
        # a 40x20 image marked EXIF orientation 6 (rotate 90 CW to view):
        # after the write-path fix it must come back 20x40 upright with
        # no orientation tag
        img = Image.new("RGB", (40, 20), (0, 128, 255))
        exif = Image.Exif()
        exif[0x0112] = 6
        buf = io.BytesIO()
        img.save(buf, format="JPEG", exif=exif.tobytes())

        ar = op.assign(f"127.0.0.1:{master.port}")
        assert not op.upload(
            f"{ar.url}/{ar.fid}",
            buf.getvalue(),
            filename="rot.jpg",
            mime="image/jpeg",
            jwt=ar.auth,
        ).error
        _, body, _ = _get(f"http://{ar.url}/{ar.fid}")
        served = Image.open(io.BytesIO(body))
        assert served.size == (20, 40)
        assert served.getexif().get(0x0112, 1) == 1


class TestAllOrientations:
    @pytest.mark.parametrize("orient", [2, 3, 4, 5, 6, 7, 8])
    def test_orientation_matches_pillow_ground_truth(self, orient):
        """Every EXIF orientation bakes to the same pixels Pillow's
        canonical exif_transpose produces, with the tag cleared.
        Block colors + corner means keep JPEG chroma subsampling out
        of the comparison (a tiny test image would smear)."""
        from PIL import Image, ImageOps

        from seaweedfs_tpu import images

        img = Image.new("RGB", (64, 32), (0, 0, 255))
        for x in range(16):
            for y in range(16):
                img.putpixel((x, y), (255, 0, 0))
                img.putpixel((63 - x, 31 - y), (0, 255, 0))
        exif = Image.Exif()
        exif[0x0112] = orient
        buf = io.BytesIO()
        img.save(buf, format="JPEG", exif=exif.tobytes(), quality=100)
        data = buf.getvalue()

        ours = Image.open(io.BytesIO(images.fix_jpg_orientation(data)))
        truth = ImageOps.exif_transpose(Image.open(io.BytesIO(data)))
        assert ours.getexif().get(0x0112, 1) == 1
        assert ours.size == truth.size

        def corner_mean(im, cx, cy):
            px = [
                im.getpixel((cx + dx, cy + dy))
                for dx in range(6)
                for dy in range(6)
            ]
            return tuple(sum(c[i] for c in px) // len(px) for i in range(3))

        w, h = truth.size
        for cx, cy in ((2, 2), (w - 8, 2), (2, h - 8), (w - 8, h - 8)):
            a, b = corner_mean(ours, cx, cy), corner_mean(truth, cx, cy)
            assert sum(abs(x - y) for x, y in zip(a, b)) < 90, (orient, a, b)

    def test_orientation_1_passthrough(self):
        from PIL import Image

        from seaweedfs_tpu import images

        img = Image.new("RGB", (4, 2), (1, 2, 3))
        buf = io.BytesIO()
        img.save(buf, format="JPEG")
        data = buf.getvalue()
        assert images.fix_jpg_orientation(data) == data


class TestPillowDegradeObservability:
    """When Pillow is missing, resizing silently degrading to
    pass-through must be observable: one wlog warning at first degrade
    (VERDICT r4 weak #5; reference images/resizing.go:15 always has its
    imaging dep, so it never degrades)."""

    def test_warns_once_and_passes_through(self, monkeypatch):
        import sys

        from seaweedfs_tpu import images
        from seaweedfs_tpu.util import wlog

        calls = []
        monkeypatch.setattr(wlog, "warning", lambda msg, *a: calls.append(msg))
        # Blocking the PIL entry in sys.modules makes `from PIL import
        # Image` raise ImportError without uninstalling Pillow.
        monkeypatch.setitem(sys.modules, "PIL", None)
        monkeypatch.setattr(images, "_degrade_warned", False)
        monkeypatch.setattr(images, "_resizing_enabled", None)  # re-probe

        data = b"not-an-image"
        out, w, h = images.resized(".png", data, 100, 0)
        assert out == data and (w, h) == (0, 0)
        assert images.fix_jpg_orientation(data) == data
        out, _, _ = images.resized(".png", data, 50, 50)
        assert out == data
        # three degraded calls -> exactly one warning
        assert len(calls) == 1 and "Pillow" in calls[0]
        assert images.resizing_enabled() is False

    def test_status_reports_resizing_state(self, stack):
        import json as _json

        master, vs, _ = stack
        _, body, _ = _get(f"http://127.0.0.1:{vs.port}/status")
        st = _json.loads(body)
        assert st["Resizing"] == "enabled"  # Pillow present in this image
