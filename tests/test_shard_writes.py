"""`volume -workers N -shardWrites`: volume-ownership write sharding.

The single-writer-per-volume invariant (reference
volume_read_write.go:66, enforced in-process there) partitions cleanly
across processes: writer k of N owns vids with vid % N == k (lead is
writer 0) and is the only process appending those volumes' .dat/.idx.
Everything else routes: the lead forwards worker-owned writes to the
owner's internal listener, workers forward lead-owned (or released)
writes to the lead, reads are served anywhere via .idx tail replay.
Admin ops that rewrite files (vacuum, EC encode via readonly, delete)
take ownership back first through the release handshake
(VolumeServer._ensure_owned ↔ the worker's /__shard/release).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import grpc
import pytest

from seaweedfs_tpu.pb import rpc, volume_pb2
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.server.volume_workers import VolumeReadWorker


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def _post(url, data):
    with urllib.request.urlopen(
        urllib.request.Request(url, data=data, method="POST"), timeout=10
    ) as r:
        return r.status, r.read()


@pytest.fixture(scope="module")
def shard_stack(tmp_path_factory):
    """Master + sharded lead (writer 0 of 2) + one write worker
    (writer 1 of 2). The worker gets a private worker_port so tests can
    aim requests at a specific process (no SO_REUSEPORT lottery)."""
    mport = free_port()
    master = MasterServer(port=mport, volume_size_limit_mb=64)
    master.start()
    vdir = str(tmp_path_factory.mktemp("shardv"))
    vport, wport = free_port(), free_port()
    iport = free_port()
    winternal = free_port()
    lead = VolumeServer(
        [vdir],
        port=vport,
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        max_volume_counts=[100],
        internal_port=iport,
        shard_writes=True,
        n_writers=2,
    )
    # worker 1's internal listener must be where the lead expects it
    lead._writer_internal_addr = lambda k: (
        f"127.0.0.1:{winternal}" if k == 1 else f"127.0.0.1:{iport}"
    )
    lead.start()
    deadline = time.time() + 20
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    worker = VolumeReadWorker(
        [vdir],
        host="127.0.0.1",
        port=free_port(),
        lead=f"127.0.0.1:{iport}",
        worker_port=wport,
        shard_writes=True,
        writer_index=1,
        n_writers=2,
        master=f"127.0.0.1:{mport}",
        internal_port=winternal,
    )
    worker.start()
    yield master, lead, worker, mport, vport, wport
    worker.stop()
    lead.stop()
    master.stop()


def assign_vid_parity(mport, parity, collection="", n=40):
    """Assign until we get a fid on a vid with vid % 2 == parity."""
    for _ in range(n):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/dir/assign"
            + (f"?collection={collection}" if collection else "")
        ) as r:
            a = json.load(r)
        if int(a["fid"].split(",")[0]) % 2 == parity:
            return a
    raise AssertionError(f"no vid with parity {parity} in {n} assigns")


class TestShardWriteRouting:
    def test_worker_owned_write_lands_and_reads_everywhere(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)  # worker-owned vid
        vid = int(a["fid"].split(",")[0])
        payload = b"worker-owned write " * 100

        # write through the LEAD's public port: it must route to the
        # worker, whose append the lead then serves via tail replay
        status, body = _post(f"http://127.0.0.1:{vport}/{a['fid']}", payload)
        assert status == 201
        assert json.loads(body)["size"] > 0
        # the WORKER really wrote it: its SharedReadVolume holds the key
        assert worker._find_volume(vid) is not None
        # read via lead
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == payload
        # read via worker
        status, body = _get(f"http://127.0.0.1:{wport}/{a['fid']}")
        assert status == 200 and body == payload

    def test_worker_port_write_handled_locally(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)
        payload = b"direct worker write"
        status, _ = _post(f"http://127.0.0.1:{wport}/{a['fid']}", payload)
        assert status == 201
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == payload

    def test_lead_owned_write_from_worker_port_proxies(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 0)  # lead-owned vid
        payload = b"lead-owned via worker"
        status, _ = _post(f"http://127.0.0.1:{wport}/{a['fid']}", payload)
        assert status == 201
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == payload

    def test_overwrite_wrong_cookie_409_on_worker_path(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)
        _post(f"http://127.0.0.1:{vport}/{a['fid']}", b"v1")
        vid_str, key_cookie = a["fid"].split(",")
        forged = f"{vid_str},{key_cookie[:-8]}{'f' * 8}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{vport}/{forged}", b"evil")
        assert ei.value.code == 409

    def test_delete_routes_to_owner(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)
        _post(f"http://127.0.0.1:{vport}/{a['fid']}", b"to be deleted")
        req = urllib.request.Request(
            f"http://127.0.0.1:{vport}/{a['fid']}", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            # 202 Accepted like the lead's do_DELETE: the cluster must
            # answer the same whichever process takes the first hop
            assert r.status == 202
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert ei.value.code == 404
        # tombstone visible through the worker too
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{wport}/{a['fid']}")
        assert ei.value.code == 404

    def test_client_supplied_hop_header_does_not_seize(self, shard_stack):
        """x-shard-hop is trusted only from the loopback internal
        listener: an anonymous client setting it on the PUBLIC port
        must not strip write ownership from a healthy worker."""
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)  # worker-owned vid
        vid = int(a["fid"].split(",")[0])
        req = urllib.request.Request(
            f"http://127.0.0.1:{vport}/{a['fid']}",
            data=b"hop forgery",
            method="POST",
            headers={"x-shard-hop": "1"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        assert vid not in lead._shard_taken
        with worker._release_lock:
            assert vid not in worker.released
        # the write still landed through the owner and reads back
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == b"hop forgery"

    def test_owned_delete_fans_out_to_replicas(self, shard_stack, monkeypatch):
        """A first-hop DELETE on a worker-owned vid must run the same
        replica fan-out as the lead's do_DELETE (store_replicate.go's
        ReplicatedDelete) — an acknowledged delete that skipped its
        replicas would resurrect there."""
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)  # worker-owned vid
        vid = int(a["fid"].split(",")[0])
        _post(f"http://127.0.0.1:{vport}/{a['fid']}", b"replicated doomed")

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.server import write_path
        from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement

        v = worker._find_volume(vid)
        assert v is not None
        monkeypatch.setattr(
            v.volume.super_block,
            "replica_placement",
            ReplicaPlacement.parse("001"),
        )
        me = f"{worker.host}:{worker.port}"

        class FakeLookup:
            error = ""
            locations = [{"url": me}, {"url": "127.0.0.1:59999"}]

        calls = []

        def fake_replicate(fid, q, method, body, headers, locations):
            calls.append((method, tuple(locations)))
            return None

        monkeypatch.setattr(op, "lookup", lambda m, vs, collection="": FakeLookup())
        monkeypatch.setattr(write_path, "replicate_to_peers", fake_replicate)

        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 202
        assert calls == [("DELETE", ("127.0.0.1:59999",))]

    def test_owned_delete_replica_error_fails_request(
        self, shard_stack, monkeypatch
    ):
        """All-or-error like the reference: a replica that refuses the
        delete fails the client's request (500), it is not silently
        acknowledged."""
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1)
        vid = int(a["fid"].split(",")[0])
        _post(f"http://127.0.0.1:{vport}/{a['fid']}", b"replica refuses")

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.server import write_path
        from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement

        v = worker._find_volume(vid)
        monkeypatch.setattr(
            v.volume.super_block,
            "replica_placement",
            ReplicaPlacement.parse("001"),
        )

        class FakeLookup:
            error = ""
            locations = [{"url": "127.0.0.1:59999"}]

        monkeypatch.setattr(op, "lookup", lambda m, vs, collection="": FakeLookup())
        monkeypatch.setattr(
            write_path,
            "replicate_to_peers",
            lambda *args: "replica 127.0.0.1:59999 failed",
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}", method="DELETE"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 500


class TestShardHandback:
    def test_readonly_takes_ownership_back(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1, collection="hb")
        vid = int(a["fid"].split(",")[0])
        payload = b"written by worker before handback " * 50
        status, _ = _post(f"http://127.0.0.1:{vport}/{a['fid']}", payload)
        assert status == 201

        with grpc.insecure_channel(f"127.0.0.1:{lead.grpc_port}") as ch:
            rpc.volume_stub(ch).VolumeMarkReadonly(
                volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
            )
        assert vid in lead._shard_taken
        assert vid in worker.released
        # the lead's own map caught up with the worker's append: the
        # blob reads through the lead's REGULAR volume path
        v = lead.store.find_volume(vid)
        got = v.read_needle(int(a["fid"].split(",")[1][:-8], 16))
        raw = bytes(got.data)
        if got.is_gzipped():  # transparent write-path compression
            import gzip

            raw = gzip.decompress(raw)
        assert raw == payload
        # writes now 409 at the LEAD (read-only), not lost at the worker
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{vport}/{a['fid']}", b"rejected")
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{wport}/{a['fid']}", b"rejected")
        assert ei.value.code == 409

    def test_vacuum_handback_preserves_worker_writes(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1, collection="vac")
        vid = int(a["fid"].split(",")[0])
        payload = b"survives vacuum handback"
        _post(f"http://127.0.0.1:{vport}/{a['fid']}", payload)

        with grpc.insecure_channel(f"127.0.0.1:{lead.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VacuumVolumeCompact(
                volume_pb2.VacuumVolumeCompactRequest(volume_id=vid)
            )
            stub.VacuumVolumeCommit(
                volume_pb2.VacuumVolumeCommitRequest(volume_id=vid)
            )
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == payload
        # post-handback writes are lead-local
        a2_fid = None
        for _ in range(40):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/dir/assign?collection=vac"
            ) as r:
                cand = json.load(r)
            if int(cand["fid"].split(",")[0]) == vid:
                a2_fid = cand["fid"]
                break
        if a2_fid:
            status, _ = _post(f"http://127.0.0.1:{vport}/{a2_fid}", b"post-vac")
            assert status == 201
            status, body = _get(f"http://127.0.0.1:{wport}/{a2_fid}")
            assert status == 200 and body == b"post-vac"


class TestShardConcurrency:
    def test_concurrent_writes_across_owners_all_land(self, shard_stack):
        """16 threads × mixed-parity fids through both entry ports:
        every blob must read back exactly from both processes."""
        master, lead, worker, mport, vport, wport = shard_stack
        written: dict[str, bytes] = {}
        lock = threading.Lock()
        errors: list[str] = []

        def one(i):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign?collection=conc"
                ) as r:
                    a = json.load(r)
                payload = (f"concurrent blob {i} ".encode()) * 37
                port = vport if i % 2 == 0 else wport
                status, _ = _post(f"http://127.0.0.1:{port}/{a['fid']}", payload)
                if status != 201:
                    raise RuntimeError(f"status {status}")
                with lock:
                    written[a["fid"]] = payload
            except Exception as e:  # noqa: BLE001
                errors.append(f"{i}: {e!r}")

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(48)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:5]
        assert len(written) == 48
        for fid, want in written.items():
            for port in (vport, wport):
                status, body = _get(f"http://127.0.0.1:{port}/{fid}")
                assert status == 200 and body == want, (fid, port)


class TestShardWritesCli:
    """Real multiprocess write scaling: `volume -workers 2 -shardWrites`
    spawns an actual write-worker subprocess; writes for both vid
    parities must land through the shared SO_REUSEPORT port and read
    back exactly — the multi-core write-scaling deployment shape."""

    def test_cli_shard_writes_both_parities(self, tmp_path):
        import os
        import subprocess
        import sys

        mport, vport = free_port(), free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu", WEED_EC_CODEC="cpu")

        def spawn(*args):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "import jax; jax.config.update('jax_platforms', 'cpu');"
                    "from seaweedfs_tpu.__main__ import main; main()",
                    *args,
                ],
                env=env,
                cwd="/root/repo",
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )

        procs = [spawn("master", "-port", str(mport))]
        try:
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/stats/health", timeout=2
                    ).read()
                    break
                except OSError:
                    time.sleep(0.2)
            procs.append(
                spawn(
                    "volume",
                    "-port", str(vport),
                    "-mserver", f"127.0.0.1:{mport}",
                    "-dir", str(tmp_path),
                    "-max", "16",
                    "-workers", "2",
                    "-shardWrites",
                )
            )

            def assign():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign", timeout=2
                ) as r:
                    return json.load(r)

            deadline = time.time() + 60
            ready = False
            while time.time() < deadline:
                try:
                    if "fid" in assign():
                        ready = True
                        break
                except OSError:
                    pass
                time.sleep(0.3)
            assert ready, "volume lead never registered"
            # the worker subprocess needs to come up before its vids
            # accept writes without lead-takeover; writes to its parity
            # would otherwise still succeed (fallback) but the test
            # wants the sharded path — wait for the worker's internal
            # listener via a parity-1 write retry loop
            written = {}
            deadline = time.time() + 60
            while len(written) < 12 and time.time() < deadline:
                a = assign()
                payload = f"shard cli {a['fid']} ".encode() * 19
                try:
                    urllib.request.urlopen(
                        urllib.request.Request(
                            f"http://127.0.0.1:{vport}/{a['fid']}",
                            data=payload,
                            method="POST",
                        ),
                        timeout=10,
                    ).read()
                    written[a["fid"]] = payload
                except OSError:
                    time.sleep(0.3)
            assert len(written) >= 12
            parities = {int(f.split(",")[0]) % 2 for f in written}
            assert parities == {0, 1}, "writes must cover both owners"
            for fid, want in written.items():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{vport}/{fid}", timeout=10
                ) as r:
                    assert r.read() == want, fid
        finally:
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pr.kill()


class TestThreeWriterRouting:
    """-workers 3: a write landing on a NON-owner worker must reach the
    true owner via the lead WITHOUT the lead seizing the vid — the hop
    marker is owner-decline-only (a non-owner's proxy setting it would
    collapse sharding for every N>=3 deployment under load)."""

    @pytest.fixture(scope="class")
    def three_stack(self, tmp_path_factory):
        mport = free_port()
        master = MasterServer(port=mport, volume_size_limit_mb=64)
        master.start()
        vdir = str(tmp_path_factory.mktemp("shard3"))
        vport = free_port()
        iport = free_port()
        winternals = {1: free_port(), 2: free_port()}
        lead = VolumeServer(
            [vdir],
            port=vport,
            master=f"127.0.0.1:{mport}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            internal_port=iport,
            shard_writes=True,
            n_writers=3,
        )
        lead._writer_internal_addr = lambda k: (
            f"127.0.0.1:{winternals[k]}" if k else f"127.0.0.1:{iport}"
        )
        lead.start()
        deadline = time.time() + 20
        while time.time() < deadline and not master.topology.data_nodes():
            time.sleep(0.05)
        workers = []
        wports = {}
        for k in (1, 2):
            wports[k] = free_port()
            w = VolumeReadWorker(
                [vdir],
                host="127.0.0.1",
                port=free_port(),
                lead=f"127.0.0.1:{iport}",
                worker_port=wports[k],
                shard_writes=True,
                writer_index=k,
                n_writers=3,
                master=f"127.0.0.1:{mport}",
                internal_port=winternals[k],
            )
            w.start()
            workers.append(w)
        yield master, lead, workers, mport, vport, wports
        for w in workers:
            w.stop()
        lead.stop()
        master.stop()

    def test_nonowner_worker_routes_without_seizure(self, three_stack):
        master, lead, workers, mport, vport, wports = three_stack
        # find a fid on a vid owned by worker 2 (vid % 3 == 2)
        a = None
        for _ in range(60):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/dir/assign"
            ) as r:
                cand = json.load(r)
            if int(cand["fid"].split(",")[0]) % 3 == 2:
                a = cand
                break
        assert a, "no worker-2-owned vid assigned"
        vid = int(a["fid"].split(",")[0])
        payload = b"three-writer routed payload " * 40

        # write through WORKER 1's port (non-owner): worker1 -> lead ->
        # worker2
        status, _ = _post(f"http://127.0.0.1:{wports[1]}/{a['fid']}", payload)
        assert status == 201
        # the lead must NOT have seized the vid: worker 2 still owns it
        assert vid not in lead._shard_taken
        assert vid not in workers[1].released and vid not in workers[0].released
        # and worker 2 genuinely holds the volume (it wrote it)
        assert workers[1]._find_volume(vid) is not None  # writer_index 2
        # readable from every process
        for port in (vport, wports[1], wports[2]):
            status, body = _get(f"http://127.0.0.1:{port}/{a['fid']}")
            assert status == 200 and body == payload


class TestShardWritesWithJwt:
    """Sharded local writes enforce the same JWT gate as the lead
    (write_path.check_write_auth): an unsigned write to a worker-owned
    vid 401s at the WORKER, a signed one lands."""

    @pytest.fixture(scope="class")
    def jwt_shard_stack(self, tmp_path_factory):
        from seaweedfs_tpu.security.guard import Guard

        key = "shard-signing-key"
        mport = free_port()
        master = MasterServer(
            port=mport,
            volume_size_limit_mb=64,
            guard=Guard(signing_key=key, expires_after_sec=30),
        )
        master.start()
        vdir = str(tmp_path_factory.mktemp("jwtshard"))
        vport, wport, iport, winternal = (
            free_port(), free_port(), free_port(), free_port(),
        )
        lead = VolumeServer(
            [vdir],
            port=vport,
            master=f"127.0.0.1:{mport}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            internal_port=iport,
            shard_writes=True,
            n_writers=2,
            guard=Guard(signing_key=key, expires_after_sec=30),
        )
        lead._writer_internal_addr = lambda k: (
            f"127.0.0.1:{winternal}" if k == 1 else f"127.0.0.1:{iport}"
        )
        lead.start()
        deadline = time.time() + 20
        while time.time() < deadline and not master.topology.data_nodes():
            time.sleep(0.05)
        worker = VolumeReadWorker(
            [vdir],
            host="127.0.0.1",
            port=free_port(),
            lead=f"127.0.0.1:{iport}",
            worker_port=wport,
            shard_writes=True,
            writer_index=1,
            n_writers=2,
            master=f"127.0.0.1:{mport}",
            internal_port=winternal,
            guard=Guard(signing_key=key, expires_after_sec=30),
        )
        worker.start()
        yield master, lead, worker, mport, vport, wport
        worker.stop()
        lead.stop()
        master.stop()

    def test_signed_write_lands_unsigned_401s(self, jwt_shard_stack):
        master, lead, worker, mport, vport, wport = jwt_shard_stack
        # worker-owned fid WITH its assign-issued token
        a = None
        for _ in range(40):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/dir/assign"
            ) as r:
                cand = json.load(r)
            if int(cand["fid"].split(",")[0]) % 2 == 1:
                a = cand
                break
        assert a and a.get("auth"), "assign must mint a write token"
        payload = b"signed sharded write"

        # unsigned: 401 straight from the worker's local-write path
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{wport}/{a['fid']}", payload)
        assert ei.value.code == 401

        # signed: lands through the worker
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}",
            data=payload,
            method="POST",
            headers={"Authorization": f"BEARER {a['auth']}"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        status, body = _get(f"http://127.0.0.1:{vport}/{a['fid']}")
        assert status == 200 and body == payload
        # the WORKER wrote it (not a proxy-to-lead fallback)
        assert worker._find_volume(int(a["fid"].split(",")[0])) is not None


class TestHandbackUnderWriteLoad:
    """The release/write race end-to-end: writers hammer a worker-owned
    vid WHILE the lead takes ownership back for vacuum. Every write
    acknowledged with 201 must be readable afterwards — the
    VolumeReleased abort in the worker re-routes in-flight writes to
    the lead instead of appending past the lead's catch-up refresh."""

    def test_no_acknowledged_write_lost_across_handback(self, shard_stack):
        master, lead, worker, mport, vport, wport = shard_stack
        a = assign_vid_parity(mport, 1, collection="race")
        vid = int(a["fid"].split(",")[0])

        acked: dict[str, bytes] = {}
        lock = threading.Lock()
        stop = threading.Event()
        errors: list[str] = []

        def writer(tid):
            i = 0
            while not stop.is_set():
                i += 1
                # same-vid fids via ?count= delta sub-fids would pin the
                # vid, but plain assigns work: filter to our vid
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign?collection=race"
                ) as r:
                    cand = json.load(r)
                if int(cand["fid"].split(",")[0]) != vid:
                    continue
                payload = f"race {tid}-{i} ".encode() * 23
                try:
                    status, _ = _post(
                        f"http://127.0.0.1:{vport}/{cand['fid']}", payload
                    )
                except urllib.error.HTTPError as e:
                    if e.code == 409:
                        continue  # readonly during compact: acceptable reject
                    errors.append(f"{tid}-{i}: HTTP {e.code}")
                    continue
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{tid}-{i}: {e!r}")
                    continue
                if status == 201:
                    with lock:
                        acked[cand["fid"]] = payload

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # let writes flow through the worker first
        import grpc

        from seaweedfs_tpu.pb import rpc, volume_pb2

        with grpc.insecure_channel(f"127.0.0.1:{lead.grpc_port}") as ch:
            stub = rpc.volume_stub(ch)
            stub.VacuumVolumeCompact(
                volume_pb2.VacuumVolumeCompactRequest(volume_id=vid)
            )
            stub.VacuumVolumeCommit(
                volume_pb2.VacuumVolumeCommitRequest(volume_id=vid)
            )
        time.sleep(0.4)  # post-handback writes flow through the lead
        stop.set()
        for t in threads:
            t.join(timeout=30)

        assert not errors, errors[:5]
        assert vid in lead._shard_taken  # the handback really happened
        assert len(acked) > 5, "no writes crossed the handback window"
        # EVERY acknowledged write reads back exactly, from both procs
        for fid, want in acked.items():
            for port in (vport, wport):
                status, body = _get(f"http://127.0.0.1:{port}/{fid}")
                assert status == 200 and body == want, (fid, port)
