"""Scrub & self-healing plane (seaweedfs_tpu/scrub/, docs/SCRUB.md).

Covers the full loop the subsystem exists for: fault injection
(tests/faults.py) → background detection (ScrubEngine) → quarantine
(unmount + .bad rename + forced delta heartbeat) → automatic repair
(master RepairScheduler driving VolumeEcShardsRebuild /
re-replication) → byte-identical reads — plus the unit tiers: token
bucket pacing, parity-verify localization, plain-volume CRC walk,
cursor persistence/resume.
"""

import io
import json
import os
import random
import threading
import time
import urllib.request

import numpy as np
import pytest

from tests.faults import (
    corrupt_needle_data,
    find_ec_shard_path,
    flip_byte,
    restore_byte,
    truncate_by,
)

from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.scrub.engine import ScrubEngine
from seaweedfs_tpu.scrub.ratelimit import TokenBucket
from seaweedfs_tpu.scrub.state import ScrubState
from seaweedfs_tpu.scrub.verify import (
    localize_corrupt_shards,
    scan_plain_volume,
    verify_parity_stream,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume


def make_needle(nid, data=None, cookie=0x12345678):
    return Needle(
        cookie=cookie,
        id=nid,
        data=data if data is not None else f"data-{nid}".encode(),
    )


def wait_for(predicate, timeout=30.0, step=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return False


# ---------------------------------------------------------------------------
class TestTokenBucket:
    def test_paces_after_burst(self):
        tb = TokenBucket(100_000, burst_bytes=50_000)
        t0 = time.perf_counter()
        assert tb.take(50_000)  # burst: instant
        assert tb.take(50_000)  # must wait ~0.5s of refill
        took = time.perf_counter() - t0
        assert 0.3 < took < 3.0, took

    def test_zero_rate_is_unlimited(self):
        tb = TokenBucket(0)
        t0 = time.perf_counter()
        for _ in range(1000):
            assert tb.take(10**9)
        assert time.perf_counter() - t0 < 0.5

    def test_stop_event_aborts(self):
        tb = TokenBucket(1.0, burst_bytes=1)  # ~glacial
        assert tb.take(1)  # drain the burst
        stop = threading.Event()
        stop.set()
        t0 = time.perf_counter()
        assert tb.take(10**6, stop) is False
        assert time.perf_counter() - t0 < 1.0

    def test_oversized_request_admits_then_charges_debt(self):
        tb = TokenBucket(10**6, burst_bytes=1000)
        assert tb.take(10**5)  # admitted (no deadlock on n > burst)...
        # ...but the FULL charge landed: the next take must wait out
        # the ~0.1 s debt, keeping the long-run rate exact
        t0 = time.perf_counter()
        assert tb.take(1)
        assert time.perf_counter() - t0 > 0.05


# ---------------------------------------------------------------------------
def _synthetic_tiles(nbytes=8192, seed=0):
    rs = new_encoder(backend="cpu")
    rng = np.random.default_rng(seed)
    shards = [
        rng.integers(0, 256, nbytes, dtype=np.uint8) for _ in range(10)
    ] + [None] * 4
    rs.encode(shards)
    return rs, [s.tobytes() for s in shards]


def _readers(tiles):
    return [lambda off, size, _t=t: _t[off : off + size] for t in tiles]


class TestVerifyCore:
    def test_clean(self):
        rs, tiles = _synthetic_tiles()
        res = verify_parity_stream(_readers(tiles), rs=rs, tile_bytes=4096)
        assert res.mismatch == [0, 0, 0, 0] and res.complete
        assert not res.corrupt and res.bytes_per_shard == 8192

    def test_data_corruption_hits_all_rows_and_localizes(self):
        rs, tiles = _synthetic_tiles()
        bad = bytearray(tiles[3])
        bad[100] ^= 0x55
        tiles[3] = bytes(bad)
        res = verify_parity_stream(_readers(tiles), rs=rs, tile_bytes=4096)
        assert all(m > 0 for m in res.mismatch)
        assert sorted(res.culprits) == [3]
        assert res.bad_tiles == [(0, 4096)]

    def test_parity_corruption_hits_own_row_only(self):
        rs, tiles = _synthetic_tiles()
        bad = bytearray(tiles[12])
        bad[5000] ^= 0xAA
        tiles[12] = bytes(bad)
        res = verify_parity_stream(_readers(tiles), rs=rs, tile_bytes=4096)
        assert res.mismatch[2] > 0
        assert res.mismatch[0] == res.mismatch[1] == res.mismatch[3] == 0
        assert sorted(res.culprits) == [12]

    def test_two_shard_localization(self):
        rs, tiles = _synthetic_tiles()
        for sid, off in ((1, 50), (7, 60)):
            b = bytearray(tiles[sid])
            b[off] ^= 0x01
            tiles[sid] = bytes(b)
        assert sorted(
            localize_corrupt_shards(tiles, rs)
        ) == [1, 7]

    def test_resume_from_cursor_matches_full_scan(self):
        rs, tiles = _synthetic_tiles()
        full = verify_parity_stream(_readers(tiles), rs=rs, tile_bytes=2048)
        part1 = verify_parity_stream(
            _readers(tiles), rs=rs, tile_bytes=2048, max_bytes=4096
        )
        assert not part1.complete and part1.end_offset == 4096
        part2 = verify_parity_stream(
            _readers(tiles), rs=rs, tile_bytes=2048, start=part1.end_offset
        )
        assert part2.complete
        assert (
            part1.bytes_per_shard + part2.bytes_per_shard
            == full.bytes_per_shard
        )


# ---------------------------------------------------------------------------
class TestPlainScan:
    def _volume(self, tmp_path, n=20):
        v = Volume(str(tmp_path), 7)
        rng = random.Random(3)
        payload = {}
        for k in range(1, n + 1):
            data = bytes(rng.randbytes(rng.randint(200, 2000)))
            payload[k] = data
            v.write_needle(make_needle(k, data))
        return v, payload

    def test_clean_scan(self, tmp_path):
        v, payload = self._volume(tmp_path)
        res = scan_plain_volume(v)
        assert res.complete and not res.corruptions
        assert res.scanned_bytes > sum(len(d) for d in payload.values())
        v.close()

    def test_detects_flipped_data_byte(self, tmp_path):
        v, _ = self._volume(tmp_path)
        corrupt_needle_data(v, 11)
        res = scan_plain_volume(v)
        assert [nid for nid, _ in res.corruptions] == [11]
        # cursor semantics: resuming past the bad needle sees nothing
        res2 = scan_plain_volume(v, after_key=11)
        assert not res2.corruptions and res2.complete
        v.close()

    def test_budget_partial_then_resume(self, tmp_path):
        v, _ = self._volume(tmp_path)
        part = scan_plain_volume(v, max_bytes=2000)
        assert not part.complete and part.last_key > 0
        rest = scan_plain_volume(v, after_key=part.last_key)
        assert rest.complete
        v.close()


# ---------------------------------------------------------------------------
def _local_ec_store(tmp_path, n_needles=40, vid=9):
    """A Store holding one plain volume EC-encoded in place with all
    14 shards mounted (the post-ec.encode single-holder shape)."""
    d = str(tmp_path)
    v = Volume(d, vid)
    rng = random.Random(5)
    payload = {}
    for k in range(1, n_needles + 1):
        data = bytes(rng.randbytes(rng.randint(500, 4000)))
        payload[k] = data
        v.write_needle(make_needle(k, data))
    v.close()
    base = os.path.join(d, str(vid))
    ec_files.write_ec_files(base, rs=new_encoder(backend="cpu"))
    ec_files.write_sorted_file_from_idx(base)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    store = Store([d], ec_backend="cpu")
    assert store.find_ec_volume(vid) is not None
    return store, payload


class TestScrubEngine:
    def test_clean_sweep_and_state_persistence(self, tmp_path):
        store, _ = _local_ec_store(tmp_path)
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        summary = eng.sweep_once()
        assert summary["ec_volumes"] == 1
        assert summary["corruptions"] == 0
        assert summary["scanned_bytes"] > 0
        state_file = os.path.join(str(tmp_path), "scrub_state.json")
        assert os.path.exists(state_file)
        # a fresh engine resumes from persisted health
        eng2 = ScrubEngine(store, interval=3600, rate_mb_s=0)
        rows = eng2.health_rows()
        assert rows and rows[0].sweeps == 1
        store.close()

    def test_detects_quarantines_and_renames(self, tmp_path):
        store, _ = _local_ec_store(tmp_path)
        events = []
        eng = ScrubEngine(
            store, interval=3600, rate_mb_s=0, on_event=lambda: events.append(1)
        )
        shard_path = os.path.join(str(tmp_path), "9.ec02")
        flip_byte(shard_path, 300, 0x40)
        summary = eng.sweep_once()
        assert summary["corruptions"] >= 1
        assert summary["quarantined"] == 1
        ev = store.find_ec_volume(9)
        assert 2 not in ev.shards  # unmounted
        assert 2 in ev.quarantined
        assert store.quarantined[9][2].startswith("scrub:")
        assert store.quarantined_shard_bits(9) == 1 << 2
        assert os.path.exists(shard_path + ".bad")  # renamed for rebuild
        assert not os.path.exists(shard_path)
        assert events  # forced-heartbeat hook fired
        store.close()

    def test_truncated_shard_quarantined_by_sweep(self, tmp_path):
        store, _ = _local_ec_store(tmp_path)
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        shard_path = os.path.join(str(tmp_path), "9.ec05")
        truncate_by(shard_path, os.path.getsize(shard_path) - 64)
        eng.sweep_once()
        ev = store.find_ec_volume(9)
        assert 5 not in ev.shards and 5 in ev.quarantined
        store.close()

    def test_shard_truncated_before_mount_quarantined_not_stalled(
        self, tmp_path
    ):
        """Truncation while the server was DOWN: the shard mounts with
        a stale short .size, so reads clamp instead of raising and the
        parity stream sees a permanent length skew — the sweep must
        quarantine the short shard (via the sibling-length check), not
        retry the same skew forever."""
        store, _ = _local_ec_store(tmp_path)
        store.close()
        shard_path = os.path.join(str(tmp_path), "9.ec05")
        truncate_by(shard_path, os.path.getsize(shard_path) - 64)
        store = Store([str(tmp_path)], ec_backend="cpu")  # mounts short
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        summary = eng.sweep_once()
        ev = store.find_ec_volume(9)
        assert 5 not in ev.shards and 5 in ev.quarantined
        assert summary["quarantined"] >= 1
        h = next(r for r in eng.health_rows() if r.is_ec)
        assert "skew" not in h.last_error  # not stalled on the skew
        store.close()

    def test_rebuild_after_quarantine_restores_reads(self, tmp_path):
        """Quarantine renames the corrupt file away, so a local
        rebuild regenerates it and remounting clears the record —
        the repair scheduler drives exactly this via gRPC."""
        store, payload = _local_ec_store(tmp_path)
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        shard_path = os.path.join(str(tmp_path), "9.ec02")
        flip_byte(shard_path, 300, 0x40)
        eng.sweep_once()
        assert 2 in store.find_ec_volume(9).quarantined
        rebuilt = ec_files.rebuild_ec_files(
            os.path.join(str(tmp_path), "9"), rs=new_encoder(backend="cpu")
        )
        assert rebuilt == [2]
        store.mount_ec_shards(9, "", [2])
        ev = store.find_ec_volume(9)
        assert 2 in ev.shards and 2 not in ev.quarantined
        assert store.quarantined.get(9) is None
        for k, data in payload.items():
            assert bytes(ev.read_needle(k).data) == data
        # the next full sweep runs clean
        summary = eng.sweep_once()
        assert summary["corruptions"] == 0
        store.close()

    def test_plain_volume_corruption_reported_not_quarantined(self, tmp_path):
        d = str(tmp_path)
        v = Volume(d, 4)
        for k in range(1, 15):
            v.write_needle(make_needle(k, bytes([k]) * 1200))
        v.close()
        store = Store([d], ec_backend="cpu")
        corrupt_needle_data(store.find_volume(4), 7)
        eng = ScrubEngine(store, interval=3600, rate_mb_s=0)
        summary = eng.sweep_once()
        assert summary["corruptions"] == 1
        h = next(r for r in eng.health_rows() if r.volume_id == 4)
        assert h.sweep_corruptions == 1 and "needle 7" in h.last_error
        store.close()


# ---------------------------------------------------------------------------
# live mini-cluster: the acceptance loop, no manual shell command
@pytest.fixture(scope="module")
def healing_cluster(tmp_path_factory):
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.util.availability import free_port

    master = MasterServer(
        port=free_port(),
        volume_size_limit_mb=64,
        vacuum_interval=0,
        repair_interval=0.5,
        repair_grace=0.5,
    )
    # fast repair convergence for the test: short cool-down
    master.repair.cooldown = 3.0
    master.start()
    servers = []
    for i in range(3):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"heal{i}"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            rack=f"rack{i % 2}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            ec_codec="cpu",
            scrub_interval=1.0,
            scrub_rate_mb_s=0,
        )
        vs.start()
        servers.append(vs)
    assert wait_for(lambda: len(master.topology.data_nodes()) == 3, 45)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _registered_shards(master, vid):
    locs = master.topology.lookup_ec_shards(vid)
    if locs is None:
        return 0
    return sum(1 for nodes in locs.locations if nodes)


class TestSelfHealingEndToEnd:
    def test_corrupt_shard_detected_quarantined_rebuilt(
        self, healing_cluster
    ):
        """The PR's acceptance scenario: inject shard corruption on a
        live cluster; the background scrubber detects + quarantines,
        the master scheduler rebuilds — no shell command — and reads
        stay byte-identical."""
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import do_ec_encode, do_ec_verify
        from seaweedfs_tpu.util.availability import write_keyset

        master, servers = healing_cluster
        vid, keys, _src = write_keyset(
            master.port,
            "heal",
            n=10,
            payload_fn=lambda i: (f"heal {i} ".encode() * 2500)[: 16000 + i],
        )
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        do_ec_encode(env, vid, "heal", io.StringIO())
        assert wait_for(lambda: _registered_shards(master, vid) == 14, 20)
        # let the scheduler drain boot-time transients (the test's
        # 0.5 s grace is far below the production 30 s, so freshly
        # grown replicas can flag as under-replicated for a beat) —
        # the corruption below must be the only tracked damage
        wait_for(lambda: not master.repair.tasks, 30)

        shard_path, holder = find_ec_shard_path(servers, "heal", vid, 3)
        assert shard_path is not None
        flip_byte(shard_path, 500, 0x77)

        # kick the sweep hook (prioritizing this vid) rather than
        # waiting out the interval timer — detection becomes an event
        # the engine schedules now, not a tick rig load can starve
        holder.scrub.trigger(vid)
        assert wait_for(
            lambda: 3 in holder.store.quarantined.get(vid, {}), 30
        ), "background scrubber never quarantined the corrupt shard"
        assert os.path.exists(shard_path + ".bad")

        # the scheduler repairs — completion lands in history BEFORE
        # the topology necessarily reflects the rebuilt mount
        assert wait_for(
            lambda: any(
                h["Kind"] == "ec_rebuild" and h["VolumeId"] == vid
                for h in master.repair.history
            ),
            90,
        ), f"no ec_rebuild recorded: {master.repair.queue_snapshot()}"
        # ...and the cluster converges back to 14 registered shards
        # with the rebuilt shard actually mounted somewhere
        assert wait_for(
            lambda: _registered_shards(master, vid) == 14
            and any(
                (ev := s.store.find_ec_volume(vid)) is not None
                and 3 in ev.shards
                for s in servers
            ),
            30,
        ), "cluster never converged to 14 mounted+registered shards"

        # byte-identical reads for every key, via the master redirect
        for fid, want in keys.items():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.port}/{fid}?collection=heal",
                timeout=10,
            ) as r:
                assert r.read() == want

        # ec.verify (now routed through the scrub core) agrees, and
        # its machine-readable mode parses
        out = io.StringIO()
        assert do_ec_verify(env, vid, out, as_json=True) == [0, 0, 0, 0]
        doc = json.loads(out.getvalue())
        assert doc["corrupt"] is False and doc["volumeId"] == vid

    def test_quarantine_reaches_master_and_status_json(
        self, healing_cluster
    ):
        """Satellite: quarantine is not silent — a foreground-read
        truncation quarantine lands in the volume server's /status
        JSON and (via forced delta beat) in the master's topology
        within a couple of heartbeats."""
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import do_ec_encode
        from seaweedfs_tpu.util.availability import write_keyset

        master, servers = healing_cluster
        vid, keys, _src = write_keyset(
            master.port,
            "quiet",
            n=8,
            payload_fn=lambda i: (f"quiet {i} ".encode() * 2000)[: 12000 + i],
        )
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        do_ec_encode(env, vid, "quiet", io.StringIO())
        assert wait_for(lambda: _registered_shards(master, vid) == 14, 20)

        shard_path, holder = find_ec_shard_path(servers, "quiet", vid, 1)
        truncate_by(shard_path, os.path.getsize(shard_path) - 100)

        # a foreground degraded read trips the truncation quarantine
        fid = next(iter(keys))
        with urllib.request.urlopen(
            f"http://{holder.host}:{holder.port}/{fid}", timeout=10
        ) as r:
            assert r.read() == keys[fid]

        assert wait_for(
            lambda: vid in holder.store.quarantined
            or _registered_shards(master, vid) == 14,
            30,
        )
        # /status JSON names the quarantined shards (while quarantined)
        with urllib.request.urlopen(
            f"http://{holder.host}:{holder.port}/status", timeout=5
        ) as r:
            st = json.loads(r.read())
        assert "QuarantinedShards" in st and "Scrub" in st

        # master hears about it on a forced beat and the scheduler
        # eventually re-registers all 14
        assert wait_for(
            lambda: any(
                s.quarantined_shard_bits
                for dn in master.topology.data_nodes()
                for s in dn.scrub_stats.values()
            )
            or _registered_shards(master, vid) == 14,
            30,
        )
        assert wait_for(lambda: _registered_shards(master, vid) == 14, 60)

    def test_repair_queue_and_scrub_shell_surfaces(self, healing_cluster):
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import run_command

        master, _servers = healing_cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        out = run_command(env, "repair.queue -json")
        snap = json.loads(out)
        assert "Config" in snap and snap["Config"]["Concurrency"] == 2
        out = run_command(env, "scrub.status")
        assert "sweeps" in out
        out = run_command(env, "scrub.trigger")
        assert "sweep triggered" in out


# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestPlainReplicaReplace:
    def test_corrupt_replica_replaced_from_clean_peer(
        self, tmp_path_factory
    ):
        """Plain-volume self-healing: scrub flags a CRC-corrupt
        replica; the scheduler deletes it and re-copies from the clean
        peer; reads on the repaired node are byte-identical."""
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.util.availability import free_port, write_keyset

        master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            vacuum_interval=0,
            repair_interval=0.5,
            repair_grace=0.5,
        )
        master.repair.cooldown = 3.0
        master.start()
        servers = [
            VolumeServer(
                [str(tmp_path_factory.mktemp(f"rep{i}"))],
                port=free_port(),
                master=f"127.0.0.1:{master.port}",
                # replication=001 places the second copy on a DIFFERENT
                # server in the SAME rack: both nodes share one rack
                rack="rack0",
                heartbeat_interval=0.2,
                max_volume_counts=[100],
                ec_codec="cpu",
                scrub_interval=1.0,
                scrub_rate_mb_s=0,
            )
            for i in range(2)
        ]
        for vs in servers:
            vs.start()
        try:
            assert wait_for(
                lambda: len(master.topology.data_nodes()) == 2, 45
            )
            vid, keys, _src = write_keyset(
                master.port,
                "repl",
                n=10,
                payload_fn=lambda i: (f"repl {i} ".encode() * 800)[: 5000 + i],
            )
            holders = [
                vs for vs in servers if vs.store.find_volume(vid) is not None
            ]
            assert len(holders) == 2, "replication=001 should place 2 copies"
            bad = holders[0]
            v = bad.store.find_volume(vid)
            # corrupt the first live needle on one replica
            live = sorted(nv.key for nv in v.nm.items())
            corrupt_needle_data(v, live[0])

            # event-driven detection: kick the engine's sweep hook and
            # barrier on sweep completion instead of waiting out the
            # interval timer. Beyond speed this STAGES the wait — the
            # old single 90 s poll covered sweep + heartbeat + repair
            # and a rig-load stall anywhere reported as the same
            # opaque timeout (the PR-18 flake); now a detection stall
            # and a repair stall fail with different messages
            swept = bad.scrub.sweeps_completed
            bad.scrub.trigger(vid)
            assert wait_for(
                lambda: bad.scrub.sweeps_completed > swept, 30
            ), "triggered scrub sweep never completed (detection stage)"

            # the flag rides the next 0.2 s beat, the master's repair
            # scheduler is heartbeat-triggered from there on: replace
            # lands and the volume returns clean (fresh copy reads)
            assert wait_for(
                lambda: (
                    (v2 := bad.store.find_volume(vid)) is not None
                    and v2 is not v
                ),
                90,
            ), "replace repair never recreated the corrupt replica"
            assert wait_for(
                lambda: any(
                    h["Kind"] == "replace" for h in master.repair.history
                ),
                30,
            )
            v2 = bad.store.find_volume(vid)
            got = v2.read_needle(live[0])
            assert got is not None  # CRC-clean read on the fresh copy
        finally:
            for vs in servers:
                vs.stop()
            master.stop()
