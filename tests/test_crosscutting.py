"""Cross-cutting subsystem tests: wlog, security (JWT + guard),
metrics, duration counters, config loader.

Models the reference's unit-test style for these packages (the
reference has no dedicated tests for glog/stats; jwt behavior is pinned
by weed/security/jwt.go semantics)."""

import os
import time

import pytest

from seaweedfs_tpu.security import (
    Guard,
    UnauthorizedError,
    decode_jwt,
    gen_jwt,
    jwt_from_headers,
    JwtError,
)
from seaweedfs_tpu.stats import DurationCounter, Registry
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.config import Configuration, load_config, SCAFFOLD_TEMPLATES


class TestJwt:
    def test_round_trip(self):
        token = gen_jwt("secret", 60, "3,0144b2cookie")
        claims = decode_jwt("secret", token)
        assert claims["fid"] == "3,0144b2cookie"
        assert claims["exp"] > time.time()

    def test_empty_key_disables(self):
        assert gen_jwt("", 60, "3,01") == ""

    def test_no_expiry_when_zero(self):
        token = gen_jwt("secret", 0, "3,01")
        assert "exp" not in decode_jwt("secret", token)

    def test_bad_signature(self):
        token = gen_jwt("secret", 60, "3,01")
        with pytest.raises(JwtError):
            decode_jwt("other", token)

    def test_expired(self):
        # hand-roll a token whose exp is in the past (gen_jwt only sets
        # exp for positive expiry, matching jwt.go:30-32)
        import base64, hashlib, hmac, json

        def b64(b):
            return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

        h = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        p = b64(json.dumps({"fid": "3,01", "exp": int(time.time()) - 10}).encode())
        sig = b64(hmac.new(b"secret", f"{h}.{p}".encode(), hashlib.sha256).digest())
        with pytest.raises(JwtError, match="expired"):
            decode_jwt("secret", f"{h}.{p}.{sig}")

    def test_tampered_payload(self):
        token = gen_jwt("secret", 60, "3,01")
        h, p, s = token.split(".")
        with pytest.raises(JwtError):
            decode_jwt("secret", f"{h}.{p}x.{s}")

    def test_malformed(self):
        with pytest.raises(JwtError):
            decode_jwt("secret", "garbage")

    def test_extraction_query_then_bearer(self):
        # ?jwt= wins; otherwise Authorization: BEARER (jwt.go:43-57)
        assert jwt_from_headers({"jwt": ["tok1"]}, {}) == "tok1"
        assert (
            jwt_from_headers({}, {"Authorization": "BEARER tok2"}) == "tok2"
        )
        assert jwt_from_headers({}, {}) == ""


class TestGuard:
    def test_inactive_passes_everything(self):
        g = Guard()
        assert not g.is_write_active
        g.check_write("8.8.8.8", "", "3,01")  # no raise

    def test_white_list(self):
        g = Guard(white_list=["127.0.0.1", "10.0.0.0/8"])
        g.check_write("127.0.0.1", "", "")
        g.check_write("10.1.2.3", "", "")
        with pytest.raises(UnauthorizedError):
            g.check_write("8.8.8.8", "", "")

    def test_jwt_write_path(self):
        g = Guard(signing_key="k1", expires_after_sec=30)
        token = g.sign_write("3,01ab")
        g.check_write("8.8.8.8", token, "3,01ab")
        with pytest.raises(UnauthorizedError):
            g.check_write("8.8.8.8", token, "4,99zz")  # fid mismatch
        with pytest.raises(UnauthorizedError):
            g.check_write("8.8.8.8", "", "3,01ab")  # missing token

    def test_read_key_separate(self):
        g = Guard(signing_key="w", read_signing_key="r")
        rt = g.sign_read("3,01")
        g.check_read("8.8.8.8", rt, "3,01")
        with pytest.raises(UnauthorizedError):
            g.check_read("8.8.8.8", g.sign_write("3,01"), "3,01")

    def test_wildcard(self):
        g = Guard(white_list=["*"])
        g.check_write("8.8.8.8", "", "")


class TestMetrics:
    def test_counter_and_labels(self):
        reg = Registry()
        c = reg.counter("reqs_total", "requests", ("server", "type"))
        c.labels("volume", "GET").inc()
        c.labels("volume", "GET").inc(2)
        assert c.value("volume", "GET") == 3
        text = reg.render_text()
        assert '# TYPE reqs_total counter' in text
        assert 'reqs_total{server="volume",type="GET"} 3.0' in text

    def test_gauge(self):
        reg = Registry()
        g = reg.gauge("vols", "volumes", ("collection",))
        g.set(5, "default")
        g.add(2, "default")
        assert g.value("default") == 7

    def test_histogram_buckets_cumulative(self):
        reg = Registry()
        h = reg.histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.render_text()
        assert 'lat_bucket{le="0.01"} 1' in text
        assert 'lat_bucket{le="0.1"} 2' in text
        assert 'lat_bucket{le="1.0"} 3' in text
        assert 'lat_bucket{le="+Inf"} 4' in text
        assert "lat_count 4" in text

    def test_histogram_timer(self):
        reg = Registry()
        h = reg.histogram("t", "t")
        with h.time():
            pass
        assert h.count() == 1

    def test_duration_counter(self):
        dc = DurationCounter()
        now = 1000000.0
        for i in range(10):
            dc.add(1, now=now + i)
        snap = dc.snapshot(now=now + 9)
        assert snap["total"] == 10
        assert snap["last_minute"] == 10
        assert snap["last_hour"] == 10
        # events older than the minute ring fall out
        snap2 = dc.snapshot(now=now + 120)
        assert snap2["last_minute"] == 0
        assert snap2["total"] == 10


class TestConfig:
    def test_dotted_get_and_types(self):
        cfg = Configuration(
            {"jwt": {"signing": {"key": "abc", "expires_after_seconds": 10}},
             "access": {"ui": True}},
            env={},
        )
        assert cfg.get_string("jwt.signing.key") == "abc"
        assert cfg.get_int("jwt.signing.expires_after_seconds") == 10
        assert cfg.get_bool("access.ui") is True
        assert cfg.get("missing.key") is None

    def test_env_override(self):
        # WEED_* env wins over file values (util/config.go:45-50)
        cfg = Configuration(
            {"jwt": {"signing": {"key": "abc"}}},
            env={"WEED_JWT_SIGNING_KEY": "fromenv"},
        )
        assert cfg.get_string("jwt.signing.key") == "fromenv"

    def test_load_search_path(self, tmp_path):
        (tmp_path / "security.toml").write_text('[jwt.signing]\nkey = "xyz"\n')
        cfg = load_config("security", search_dirs=(str(tmp_path),), env={})
        assert cfg.get_string("jwt.signing.key") == "xyz"

    def test_missing_optional_and_required(self, tmp_path):
        cfg = load_config("nosuch", search_dirs=(str(tmp_path),), env={})
        assert cfg.get("anything") is None
        with pytest.raises(FileNotFoundError):
            load_config("nosuch", required=True, search_dirs=(str(tmp_path),))

    def test_scaffold_templates_parse(self, tmp_path):
        import io

        # the stdlib parser where the image has one, else the
        # util/config fallback reader the daemons actually run on
        from seaweedfs_tpu.util.config import tomllib

        for name, text in SCAFFOLD_TEMPLATES.items():
            # all templates must be valid TOML for whichever parser
            # load_config will use on this image
            tree = tomllib.load(io.BytesIO(text.encode()))
            assert isinstance(tree, dict) and tree, name

    def test_sub_tree(self):
        cfg = Configuration({"sink": {"filer": {"enabled": True}}}, env={})
        assert cfg.sub("sink.filer") == {"enabled": True}
        assert cfg.sub("sink.nope") == {}


class TestSecuredCluster:
    """assign → jwt-gated write end-to-end: master signs the fid, the
    volume server enforces it (guard wiring on both servers)."""

    def test_write_requires_jwt(self, tmp_path):
        import socket
        import urllib.error
        import urllib.request

        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        from seaweedfs_tpu.util.availability import free_port

        guard = Guard(signing_key="cluster-secret", expires_after_sec=30)
        mport = free_port()
        master = MasterServer(port=mport, volume_size_limit_mb=64, guard=guard)
        master.start()
        vs = VolumeServer(
            [str(tmp_path)],
            port=free_port(),
            master=f"127.0.0.1:{mport}",
            heartbeat_interval=0.2,
            max_volume_counts=[20],
            guard=guard,
        )
        vs.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not master.topology.data_nodes():
                time.sleep(0.05)
            import json as _json

            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/dir/assign", timeout=10
            ) as r:
                assign = _json.loads(r.read())
            assert assign.get("auth"), "master must hand out a write jwt"
            url = f"http://{assign['url']}/{assign['fid']}"
            # no token → 401
            req = urllib.request.Request(url, data=b"x", method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 401
            # with the assigned token → accepted
            req = urllib.request.Request(url, data=b"payload", method="POST")
            req.add_header("Authorization", f"BEARER {assign['auth']}")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 201
            # token for a different fid → 401
            other = guard.sign_write("9,deadbeef00000000")
            req = urllib.request.Request(url, data=b"x", method="POST")
            req.add_header("Authorization", f"BEARER {other}")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 401
            # reads stay open (no read key configured)
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.read() == b"payload"
        finally:
            vs.stop()
            master.stop()


class TestWlog:
    def test_v_levels(self, capsys):
        wlog.set_verbosity(1)
        assert bool(wlog.V(0))
        assert bool(wlog.V(1))
        assert not bool(wlog.V(2))
        wlog.set_verbosity(0)

    def test_vmodule_match(self):
        wlog.set_verbosity(0)
        wlog.set_vmodule("test_crosscutting=3")
        assert bool(wlog.V(3))
        wlog.set_vmodule("other_module=3")
        assert not bool(wlog.V(3))
        wlog.set_vmodule("")

    def test_log_file(self, tmp_path):
        wlog.set_log_dir(str(tmp_path), program="testweed")
        wlog.info("hello %s", "world")
        content = (tmp_path / "testweed.log").read_text()
        assert "hello world" in content


class TestNativeCrc:
    """The native CRC tier (reference vendored klauspost/crc32 SSE4.2,
    needle/crc.go:8) must agree byte-for-byte with the pure-Python
    slicing-by-8 fallback."""

    def test_native_matches_python(self):
        try:
            from seaweedfs_tpu.native import crc32c as native_crc
        except ImportError:
            pytest.skip("no compiler for the native shim in this env")
        from seaweedfs_tpu.util.crc import _crc32c_py

        rng_data = os.urandom(257 * 1024 + 3)
        assert native_crc(rng_data) == _crc32c_py(rng_data)
        assert native_crc(b"") == _crc32c_py(b"")
        # streaming continuation across an arbitrary split
        mid = native_crc(rng_data[:12345])
        assert native_crc(rng_data[12345:], mid) == _crc32c_py(rng_data)

    def test_known_vector(self):
        # RFC 3720 iSCSI test vector: crc32c of 32 zero bytes
        from seaweedfs_tpu.util.crc import crc32c

        assert crc32c(b"\x00" * 32) == 0x8A9136AA


class TestMetricsPushPlumbing:
    """The master ships pushgateway config in heartbeat responses and
    the volume server starts pushing (master_grpc_server.go:80-84 +
    LoopPushingMetric)."""

    def test_volume_server_pushes_after_heartbeat_hint(self, tmp_path_factory):
        import socket
        import threading
        import time as _time
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        from seaweedfs_tpu.util.availability import free_port

        received = []

        class Gateway(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path, self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        gw_port = free_port()
        gw = ThreadingHTTPServer(("127.0.0.1", gw_port), Gateway)
        threading.Thread(target=gw.serve_forever, daemon=True).start()

        master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            metrics_address=f"127.0.0.1:{gw_port}",
            metrics_interval_sec=1,
        )
        master.start()
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("metricsvs"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
        )
        vs.start()
        try:
            deadline = _time.time() + 15
            while _time.time() < deadline and not received:
                _time.sleep(0.1)
            assert received, "no metrics push arrived at the gateway"
            path, body = received[0]
            assert path.startswith("/metrics/job/volume_")
            assert b"# TYPE" in body
        finally:
            vs.stop()
            master.stop()
            gw.shutdown()
            gw.server_close()


class TestMultipartParser:
    """From-scratch multipart/form-data parser (util/multipart.py) —
    the ParseUpload role (needle.go:85): first file part wins, raw
    bodies pass through, boundary bytes inside payloads stay intact."""

    CT = "multipart/form-data; boundary=bndX"

    @staticmethod
    def _mp(*parts):
        out = b""
        for headers, payload in parts:
            out += b"--bndX\r\n" + headers + b"\r\n\r\n" + payload + b"\r\n"
        return out + b"--bndX--\r\n"

    def test_file_part_with_mime(self):
        from seaweedfs_tpu.util.multipart import parse_upload

        body = self._mp(
            (
                b'Content-Disposition: form-data; name="file"; '
                b'filename="a.txt"\r\nContent-Type: text/plain',
                b"hello",
            )
        )
        p = parse_upload(body, self.CT)
        assert (p.data, p.filename, p.mime) == (b"hello", "a.txt", "text/plain")

    def test_file_part_preferred_over_fields(self):
        from seaweedfs_tpu.util.multipart import parse_upload

        body = self._mp(
            (b'Content-Disposition: form-data; name="k"', b"v"),
            (
                b'Content-Disposition: form-data; name="file"; filename="b.bin"',
                b"\x00\x01\r\n\x02",
            ),
        )
        p = parse_upload(body, self.CT)
        assert (p.data, p.filename) == (b"\x00\x01\r\n\x02", "b.bin")

    def test_first_field_when_no_file(self):
        from seaweedfs_tpu.util.multipart import parse_upload

        body = self._mp(
            (b'Content-Disposition: form-data; name="k"', b"value1"),
            (b'Content-Disposition: form-data; name="j"', b"value2"),
        )
        assert parse_upload(body, self.CT).data == b"value1"
        # quoted boundary spelling
        q = 'multipart/form-data; boundary="bndX"'
        assert parse_upload(body, q).data == b"value1"

    def test_raw_body_passthrough(self):
        from seaweedfs_tpu.util.multipart import parse_upload

        p = parse_upload(b"raw", "application/octet-stream")
        assert p.data == b"raw" and p.mime == "application/octet-stream"

    def test_base64_transfer_encoding(self):
        import base64

        from seaweedfs_tpu.util.multipart import parse_upload

        body = self._mp(
            (
                b'Content-Disposition: form-data; name="file"; filename="c"'
                b"\r\nContent-Transfer-Encoding: base64",
                base64.b64encode(b"decoded!"),
            )
        )
        assert parse_upload(body, self.CT).data == b"decoded!"

    def test_boundary_bytes_inside_payload_survive(self):
        from seaweedfs_tpu.util.multipart import parse_upload

        tricky = b"data --bndX mid-line and\r\n --bndX with space"
        body = self._mp(
            (b'Content-Disposition: form-data; name="file"; filename="t"', tricky)
        )
        assert parse_upload(body, self.CT).data == tricky
        # preamble before the first delimiter is skipped (RFC 2046)
        assert parse_upload(b"preamble\r\n" + body, self.CT).data == tricky
        # line-anchored but trailing-garbage boundary runs are DATA: a
        # delimiter line must end in padding+CRLF (or "--" + padding)
        for inner in (
            b"A\r\n--bndXtra not a delimiter\r\nB",
            b"A\r\n--bndX--data after\r\nB",
        ):
            body = self._mp(
                (
                    b'Content-Disposition: form-data; name="file"; filename="t"',
                    inner,
                )
            )
            assert parse_upload(body, self.CT).data == inner
        # transport padding after the boundary is still a delimiter
        body = (
            b"--bndX  \t\r\n"
            b'Content-Disposition: form-data; name="file"; filename="p"'
            b"\r\n\r\npadded\r\n--bndX--\r\n"
        )
        assert parse_upload(body, self.CT).data == b"padded"

    def test_malformed_raises(self):
        import pytest as _pytest

        from seaweedfs_tpu.util.multipart import MalformedUpload, parse_upload

        with _pytest.raises(MalformedUpload):
            parse_upload(b"no boundary in here", self.CT)
        with _pytest.raises(MalformedUpload):
            parse_upload(b"x", "multipart/form-data")
