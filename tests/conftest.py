"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run against 8 XLA host devices. Must run before jax is imported
anywhere.
"""

import os

# The host environment exports JAX_PLATFORMS=axon (the tunneled TPU)
# and a sitecustomize imports jax at interpreter start, so the env var
# is already baked into jax.config before this file runs. Funneling
# test kernels through the tunnel is slow and wedges when two processes
# race for the single chip — force the virtual CPU mesh via
# jax.config.update, which is still honored before first backend use.
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Dynamic lock-order witness (analysis/witness.py): wraps Lock/RLock
# allocation for locks created in repo files and fails the run on any
# runtime acquisition-order inversion — the `-race`-style complement
# to the static weedlint pass, ON by default in tier-1. Installed here,
# before any seaweedfs_tpu module import can allocate its locks.
# WEED_LOCK_WITNESS=0 disables (e.g. when bisecting a perf number).
_WITNESS_ON = os.environ.get("WEED_LOCK_WITNESS", "1") != "0"
if _WITNESS_ON:
    from seaweedfs_tpu.analysis import witness as _witness

    _witness.install()

# Unit tests default to the cpu codec (fast, no per-shape jit compiles);
# the TPU serving path is covered explicitly by tests that pass
# ec_codec="tpu" / backend="tpu" (e.g. test_ec_tpu_serving.py), which
# overrides this env default.
os.environ.setdefault("WEED_EC_CODEC", "cpu")

import pathlib

import pytest

REFERENCE_ROOT = pathlib.Path("/root/reference")


def pytest_configure(config):
    # tier-1 deselects with `-m 'not slow'`; registering the marker
    # keeps the run warning-clean (unknown-mark warnings drown real
    # ones in the tail summary)
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 sweep"
    )


@pytest.fixture(scope="session")
def reference_root() -> pathlib.Path:
    """Path to the read-only reference checkout; tests that golden-check
    against its binary fixtures skip when it is absent (e.g. on the
    bench host)."""
    if not REFERENCE_ROOT.exists():
        pytest.skip("reference checkout not available")
    return REFERENCE_ROOT


@pytest.fixture(autouse=_WITNESS_ON)
def _lock_order_witness():
    """Fails the test during which a lock-order inversion completed.
    The order graph is cumulative across the whole session (an
    inversion needs one test to establish A→B and possibly a later one
    to demonstrate B→A), so the failing test is the one that CLOSED
    the cycle — its stack is in the report."""
    from seaweedfs_tpu.analysis import witness as _w

    before = len(_w.inversions())
    yield
    found = _w.inversions()[before:]
    if found:
        pytest.fail(
            "dynamic lock-order witness detected inversion(s):\n"
            + _w.format_inversions(found),
            pytrace=False,
        )


@pytest.fixture(scope="session")
def native_post_toolchain():
    """C-path guard: tests that exercise the native write hot loop
    (native/post.c via needle_ext.post) SKIP — never error — on hosts
    without a working C toolchain, where the loader returns None and
    production falls back to the pure-Python path those same tests
    compare against."""
    from seaweedfs_tpu.server import write_path

    if write_path._needle_ext is None or not hasattr(
        write_path._needle_ext, "post"
    ):
        pytest.skip("no C toolchain: native needle_ext.post unavailable")
