"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run against 8 XLA host devices. Must run before jax is imported
anywhere.
"""

import os

# The host environment exports JAX_PLATFORMS=axon (the tunneled TPU)
# and a sitecustomize imports jax at interpreter start, so the env var
# is already baked into jax.config before this file runs. Funneling
# test kernels through the tunnel is slow and wedges when two processes
# race for the single chip — force the virtual CPU mesh via
# jax.config.update, which is still honored before first backend use.
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Unit tests default to the cpu codec (fast, no per-shape jit compiles);
# the TPU serving path is covered explicitly by tests that pass
# ec_codec="tpu" / backend="tpu" (e.g. test_ec_tpu_serving.py), which
# overrides this env default.
os.environ.setdefault("WEED_EC_CODEC", "cpu")

import pathlib

import pytest

REFERENCE_ROOT = pathlib.Path("/root/reference")


@pytest.fixture(scope="session")
def reference_root() -> pathlib.Path:
    """Path to the read-only reference checkout; tests that golden-check
    against its binary fixtures skip when it is absent (e.g. on the
    bench host)."""
    if not REFERENCE_ROOT.exists():
        pytest.skip("reference checkout not available")
    return REFERENCE_ROOT


@pytest.fixture(scope="session")
def native_post_toolchain():
    """C-path guard: tests that exercise the native write hot loop
    (native/post.c via needle_ext.post) SKIP — never error — on hosts
    without a working C toolchain, where the loader returns None and
    production falls back to the pure-Python path those same tests
    compare against."""
    from seaweedfs_tpu.server import write_path

    if write_path._needle_ext is None or not hasattr(
        write_path._needle_ext, "post"
    ):
        pytest.skip("no C toolchain: native needle_ext.post unavailable")
