"""EC codec tests: field math, matrix construction, encode/reconstruct
properties, CPU↔TPU-backend equivalence.

Models the reference's ec_test.go strategy: encode, drop random shard
subsets, verify reconstruction equals the original bytes.
"""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf256
from seaweedfs_tpu.ec.codec import ReedSolomon, cpu_apply_matrix, new_encoder


class TestGf256:
    def test_exp_table_basics(self):
        # generator 2, poly 0x11D: 2^0=1, 2^1=2, ..., 2^8 = 0x1d
        assert gf256.EXP_TABLE[0] == 1
        assert gf256.EXP_TABLE[1] == 2
        assert gf256.EXP_TABLE[7] == 0x80
        assert gf256.EXP_TABLE[8] == 0x1D

    def test_mul_matches_carryless_reference(self):
        def slow_mul(a, b):
            r = 0
            while b:
                if b & 1:
                    r ^= a
                a <<= 1
                if a & 0x100:
                    a ^= 0x11D
                b >>= 1
            return r

        rng = np.random.default_rng(0)
        for _ in range(500):
            a, b = int(rng.integers(256)), int(rng.integers(256))
            assert gf256.gf_mul(a, b) == slow_mul(a, b)

    def test_mul_table_symmetry_and_identity(self):
        assert np.array_equal(gf256.MUL_TABLE, gf256.MUL_TABLE.T)
        assert np.array_equal(gf256.MUL_TABLE[1], np.arange(256, dtype=np.uint8))
        assert np.all(gf256.MUL_TABLE[0] == 0)

    def test_div_inverts_mul(self):
        rng = np.random.default_rng(1)
        for _ in range(200):
            a, b = int(rng.integers(256)), int(rng.integers(1, 256))
            assert gf256.gf_div(gf256.gf_mul(a, b), b) == a

    def test_gf_exp_matches_reference_semantics(self):
        # galExp: n==0 → 1 even for a==0; a==0 → 0 otherwise
        assert gf256.gf_exp(0, 0) == 1
        assert gf256.gf_exp(0, 5) == 0
        assert gf256.gf_exp(3, 1) == 3
        v = 1
        for _ in range(7):
            v = gf256.gf_mul(v, 5)
        assert gf256.gf_exp(5, 7) == v

    def test_mat_inv(self):
        rng = np.random.default_rng(2)
        for n in [1, 2, 5, 10, 14]:
            # random invertible matrix: retry until non-singular
            while True:
                m = rng.integers(0, 256, (n, n)).astype(np.uint8)
                try:
                    inv = gf256.mat_inv(m)
                    break
                except np.linalg.LinAlgError:
                    continue
            assert np.array_equal(gf256.mat_mul(m, inv), gf256.identity(n))
            assert np.array_equal(gf256.mat_mul(inv, m), gf256.identity(n))

    def test_singular_raises(self):
        m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
        with pytest.raises(np.linalg.LinAlgError):
            gf256.mat_inv(m)

    def test_code_matrix_systematic(self):
        a = gf256.build_code_matrix(10, 14)
        assert a.shape == (14, 10)
        assert np.array_equal(a[:10], gf256.identity(10))
        # parity rows must have no zero coefficients (MDS property side
        # effect of the Vandermonde construction)
        assert np.all(a[10:] != 0)

    def test_code_matrix_mds_property(self):
        # every k-row submatrix must be invertible (this is what makes
        # any-10-of-14 reconstruction work)
        a = gf256.build_code_matrix(4, 6)
        for rows in itertools.combinations(range(6), 4):
            inv = gf256.mat_inv(a[np.array(rows)])  # must not raise
            assert inv.shape == (4, 4)


def _random_shards(rng, k, n):
    return [rng.integers(0, 256, n).astype(np.uint8) for _ in range(k)]


class TestReedSolomonCpu:
    def setup_method(self):
        self.rs = new_encoder(10, 4, backend="cpu")
        self.rng = np.random.default_rng(42)

    def _encoded(self, n=1000):
        shards = _random_shards(self.rng, 10, n) + [None] * 4
        return self.rs.encode(shards)

    def test_encode_verify(self):
        shards = self._encoded()
        assert all(s is not None for s in shards)
        assert self.rs.verify(shards)

    def test_verify_detects_corruption(self):
        shards = self._encoded()
        shards[3] = shards[3].copy()
        shards[3][17] ^= 0xFF
        assert not self.rs.verify(shards)

    @pytest.mark.parametrize("n_missing", [1, 2, 3, 4])
    def test_reconstruct_any_missing(self, n_missing):
        original = self._encoded()
        for missing in itertools.islice(
            itertools.combinations(range(14), n_missing), 30
        ):
            shards = [s.copy() if i not in missing else None for i, s in enumerate(original)]
            self.rs.reconstruct(shards)
            for i in range(14):
                np.testing.assert_array_equal(shards[i], original[i], err_msg=f"shard {i}")

    def test_reconstruct_data_leaves_parity_missing(self):
        original = self._encoded()
        shards = [s.copy() for s in original]
        shards[2] = None
        shards[12] = None
        self.rs.reconstruct_data(shards)
        np.testing.assert_array_equal(shards[2], original[2])
        assert shards[12] is None

    def test_too_few_shards_raises(self):
        original = self._encoded()
        shards = [s.copy() for s in original]
        for i in [0, 1, 2, 3, 13]:
            shards[i] = None
        with pytest.raises(ValueError, match="too few"):
            self.rs.reconstruct(shards)

    def test_identity_passthrough(self):
        # encode must not modify data shards (systematic code)
        shards = self._encoded()
        data_copy = [s.copy() for s in shards[:10]]
        self.rs.encode(shards)
        for a, b in zip(shards[:10], data_copy):
            np.testing.assert_array_equal(a, b)

    def test_parity_linear_in_data(self):
        # RS is linear: parity(a ^ b) = parity(a) ^ parity(b)
        a = self._encoded(256)
        b = self._encoded(256)
        xored = [x ^ y for x, y in zip(a[:10], b[:10])] + [None] * 4
        self.rs.encode(xored)
        for i in range(10, 14):
            np.testing.assert_array_equal(xored[i], a[i] ^ b[i])


class TestTpuBackendEquivalence:
    """The TPU (bitsliced XOR-matmul) backend must be byte-identical to
    the CPU reference backend — the analogue of ec_test.go's
    read-vs-reconstruct cross-check."""

    def setup_method(self):
        self.rng = np.random.default_rng(7)

    def test_apply_matrix_equivalence(self):
        from seaweedfs_tpu.ec.codec_tpu import tpu_apply_matrix

        for r, c, n in [(4, 10, 512), (10, 10, 100), (1, 14, 63), (14, 14, 257)]:
            m = self.rng.integers(0, 256, (r, c)).astype(np.uint8)
            x = self.rng.integers(0, 256, (c, n)).astype(np.uint8)
            np.testing.assert_array_equal(
                tpu_apply_matrix(m, x), cpu_apply_matrix(m, x)
            )

    def test_encode_equivalence(self):
        cpu = new_encoder(10, 4, backend="cpu")
        tpu = new_encoder(10, 4, backend="tpu")
        data = _random_shards(self.rng, 10, 4096)
        s_cpu = cpu.encode([d.copy() for d in data] + [None] * 4)
        s_tpu = tpu.encode([d.copy() for d in data] + [None] * 4)
        for a, b in zip(s_cpu, s_tpu):
            np.testing.assert_array_equal(a, b)

    def test_reconstruct_equivalence(self):
        cpu = new_encoder(10, 4, backend="cpu")
        tpu = new_encoder(10, 4, backend="tpu")
        data = _random_shards(self.rng, 10, 1024)
        original = cpu.encode([d.copy() for d in data] + [None] * 4)
        for missing in [(0,), (0, 5, 10, 13), (10, 11, 12, 13), (6, 7, 8, 9)]:
            shards = [
                s.copy() if i not in missing else None for i, s in enumerate(original)
            ]
            tpu.reconstruct(shards)
            for i in range(14):
                np.testing.assert_array_equal(shards[i], original[i])

    def test_device_kernels(self):
        import jax.numpy as jnp

        from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

        kern = TpuCodecKernels(10, 4)
        data = np.stack(_random_shards(self.rng, 10, 2048))
        parity = np.asarray(kern.encode(jnp.asarray(data)))
        cpu = new_encoder(10, 4, backend="cpu")
        expect = cpu.encode([d.copy() for d in data] + [None] * 4)
        for i in range(4):
            np.testing.assert_array_equal(parity[i], expect[10 + i])

        # degraded read: lose shards 2 and 11, rebuild from 10 survivors
        all_shards = np.concatenate([data, parity], axis=0)
        survivors = tuple(i for i in range(14) if i not in (2, 11))[:10]
        rebuilt = np.asarray(
            kern.reconstruct(survivors, (2, 11), jnp.asarray(all_shards[list(survivors)]))
        )
        np.testing.assert_array_equal(rebuilt[0], data[2])
        np.testing.assert_array_equal(rebuilt[1], expect[11])

    def test_batched_encode(self):
        import jax.numpy as jnp

        from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels

        kern = TpuCodecKernels(10, 4)
        batch = self.rng.integers(0, 256, (3, 10, 512)).astype(np.uint8)
        parity = np.asarray(kern.encode_batch(jnp.asarray(batch)))
        cpu = new_encoder(10, 4, backend="cpu")
        for b in range(3):
            expect = cpu.encode([batch[b, i].copy() for i in range(10)] + [None] * 4)
            for i in range(4):
                np.testing.assert_array_equal(parity[b, i], expect[10 + i])


class TestSmallConfigs:
    @pytest.mark.parametrize("k,p", [(1, 1), (2, 2), (4, 2), (10, 4), (17, 3)])
    def test_roundtrip(self, k, p):
        rng = np.random.default_rng(k * 31 + p)
        rs = ReedSolomon(k, p, backend="cpu")
        shards = [rng.integers(0, 256, 128).astype(np.uint8) for _ in range(k)] + [
            None
        ] * p
        rs.encode(shards)
        original = [s.copy() for s in shards]
        drop = list(range(min(p, k)))
        for i in drop:
            shards[i] = None
        rs.reconstruct(shards)
        for a, b in zip(shards, original):
            np.testing.assert_array_equal(a, b)


class TestSwarKernel:
    """The SWAR Horner Pallas kernel — the default serving path for
    streams >= 64 KiB on TPU hosts — via the Pallas interpreter, byte-
    compared against the CPU LUT backend (codec_tpu.py fast path)."""

    def test_encode_rows_interpret(self):
        from seaweedfs_tpu.ec.codec import cpu_apply_matrix
        from seaweedfs_tpu.ec.codec_tpu import swar_apply_matrix_host
        from seaweedfs_tpu.ec import gf256

        rng = np.random.default_rng(99)
        n = 128 * 1024  # above _SWAR_MIN_BYTES, multiple of 1024
        data = rng.integers(0, 256, (10, n), dtype=np.uint8)
        matrix = gf256.build_code_matrix(10, 14)
        parity_rows = matrix[10:]
        out = swar_apply_matrix_host(parity_rows, data, interpret=True)
        np.testing.assert_array_equal(out, cpu_apply_matrix(parity_rows, data))

    def test_decode_rows_interpret(self):
        import jax.numpy as jnp

        from seaweedfs_tpu.ec.codec import cpu_apply_matrix
        from seaweedfs_tpu.ec.codec_tpu import TpuCodecKernels, swar_apply_matrix_host

        rng = np.random.default_rng(100)
        n = 64 * 1024
        kern = TpuCodecKernels(10, 4)
        data = rng.integers(0, 256, (10, n), dtype=np.uint8)
        parity = cpu_apply_matrix(kern.matrix[10:], data)
        shards = np.concatenate([data, parity], axis=0)

        survivors = tuple(i for i in range(14) if i not in (0, 5, 12, 13))
        targets = (0, 5, 12, 13)
        rows = kern.decode_rows_for(survivors, targets)
        out = swar_apply_matrix_host(rows, shards[list(survivors)], interpret=True)
        np.testing.assert_array_equal(out[0], shards[0])
        np.testing.assert_array_equal(out[1], shards[5])
        np.testing.assert_array_equal(out[2], shards[12])
        np.testing.assert_array_equal(out[3], shards[13])


class TestNativeBackend:
    """The SIMD C shim (native/gf256.c) — the "native" codec backend
    serving plain hosts (the reference's klauspost/reedsolomon-AVX2
    role) — byte-compared against the numpy "cpu" backend. Skipped
    only when no system compiler exists."""

    @pytest.fixture(scope="class")
    def nat(self):
        try:
            from seaweedfs_tpu.native.gf import apply_matrix
        except ImportError:
            pytest.skip("native gf256 shim unavailable (no compiler)")
        return apply_matrix

    def test_apply_matrix_equivalence(self, nat):
        from seaweedfs_tpu.ec.codec import cpu_apply_matrix

        rng = np.random.default_rng(7)
        # sizes straddling the SIMD widths and the 256 KiB block size
        for n in (0, 1, 31, 32, 33, 63, 64, 65, 4096, 262144 + 17):
            matrix = rng.integers(0, 256, (4, 10), dtype=np.uint8)
            data = rng.integers(0, 256, (10, n), dtype=np.uint8)
            np.testing.assert_array_equal(
                nat(matrix, data), cpu_apply_matrix(matrix, data)
            )

    def test_zero_and_identity_coefficients(self, nat):
        from seaweedfs_tpu.ec.codec import cpu_apply_matrix

        rng = np.random.default_rng(8)
        data = rng.integers(0, 256, (3, 1000), dtype=np.uint8)
        matrix = np.array([[0, 1, 2], [1, 0, 0], [0, 0, 0]], dtype=np.uint8)
        np.testing.assert_array_equal(
            nat(matrix, data), cpu_apply_matrix(matrix, data)
        )

    def test_full_encoder_roundtrip(self, nat):
        from seaweedfs_tpu.ec.codec import new_encoder

        rng = np.random.default_rng(9)
        rs_nat = new_encoder(backend="native")
        rs_cpu = new_encoder(backend="cpu")
        data = [
            rng.integers(0, 256, 100_001, dtype=np.uint8) for _ in range(10)
        ]
        got = rs_nat.encode([d.copy() for d in data] + [None] * 4)
        want = rs_cpu.encode([d.copy() for d in data] + [None] * 4)
        for i in range(14):
            np.testing.assert_array_equal(got[i], want[i])

        # worst case: all four losses are data shards
        shards = [s.copy() for s in got]
        for i in (0, 3, 5, 9):
            shards[i] = None
        rs_nat.reconstruct(shards)
        for i in range(14):
            np.testing.assert_array_equal(shards[i], want[i])

    def test_default_backend_prefers_native_on_plain_hosts(
        self, nat, monkeypatch
    ):
        from seaweedfs_tpu.ec import codec

        # conftest pins WEED_EC_CODEC=cpu for determinism and forces a
        # cpu-only jax backend; with the pin lifted, auto-detect on
        # this no-accelerator host must land on the native shim
        monkeypatch.delenv("WEED_EC_CODEC", raising=False)
        monkeypatch.setattr(codec, "_default_backend", "")
        assert codec.default_backend() == "native"

    def test_thread_safety_parallel_calls(self, nat):
        """Server handler threads run EC ops concurrently; the shim's
        tables are read-only after dlopen and every call writes only
        its own output — N threads hammering apply_matrix must all get
        byte-identical results (ctypes releases the GIL, so the C code
        really runs in parallel)."""
        import threading

        from seaweedfs_tpu.ec.codec import cpu_apply_matrix

        rng = np.random.default_rng(11)
        matrix = rng.integers(0, 256, (4, 10), dtype=np.uint8)
        data = rng.integers(0, 256, (10, 1 << 18), dtype=np.uint8)
        want = cpu_apply_matrix(matrix, data)
        errors = []

        def worker():
            try:
                for _ in range(8):
                    np.testing.assert_array_equal(nat(matrix, data), want)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:1]
