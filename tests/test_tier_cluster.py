"""Live-cluster tiering acceptance (ISSUE 17 tentpole): a real
master + 3 volume servers + filer + S3 gateway, EC-encoded keysets
tiered out to the local-dir backend fake and recalled — degraded and
range GETs served from the backend in between, every holder streaming
its OWN shards, cross-holder fetches riding VolumeEcShardRead's
remote fallback. Plus the WEED_TIER=0 kill switch, the master-side
TierScheduler driving moves from rules, and the operator shell verbs.
"""

from __future__ import annotations

import io
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.s3api import S3ApiServer
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import do_ec_encode, run_command
from seaweedfs_tpu.tier import TierRules, TierScheduler
from seaweedfs_tpu.util.availability import free_port, write_keyset

from tests.chaos import wait_for

BACKEND = "dir.clu"


@pytest.fixture(scope="module")
def tier_cluster(tmp_path_factory):
    backend_dir = str(tmp_path_factory.mktemp("tierbk"))
    storage_cfg = {"dir": {"clu": {"enabled": True, "dir": backend_dir}}}
    master = MasterServer(
        port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
    )
    master.start()
    maddr = f"127.0.0.1:{master.port}"
    servers = []
    for i in range(3):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"tiervol{i}"))],
            port=free_port(),
            master=maddr,
            rack=f"rack{i % 2}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            ec_codec="cpu",
            storage_backends=storage_cfg,
        )
        vs.start()
        servers.append(vs)
    fport = free_port()
    filer = FilerServer([maddr], port=fport, store="memory", max_mb=1)
    filer.start()
    s3 = S3ApiServer(filer=f"127.0.0.1:{fport}", port=free_port())
    s3.start()
    assert wait_for(lambda: len(master.topology.data_nodes()) == 3, 45)
    yield master, servers, s3, backend_dir
    s3.stop()
    filer.stop()
    for vs in servers:
        vs.stop()
    master.stop()


def _post_json(url: str, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(url, method="POST", data=b"")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _holders(servers, vid):
    return [s for s in servers if s.store.find_ec_volume(vid) is not None]


def _registered_shards(master, vid):
    locs = master.topology.lookup_ec_shards(vid)
    if locs is None:
        return 0
    return sum(1 for nodes in locs.locations if nodes)


def _encode(master, collection, n=8):
    vid, keys, _src = write_keyset(
        master.port,
        collection,
        n=n,
        payload_fn=lambda i: (f"{collection} {i} ".encode() * 2500)[: 15000 + i],
    )
    env = CommandEnv([f"127.0.0.1:{master.port}"])
    do_ec_encode(env, vid, collection, io.StringIO())
    assert wait_for(lambda: _registered_shards(master, vid) == 14, 30)
    return vid, keys, env


def _tier_out_everywhere(servers, vid):
    moved = 0
    for vs in _holders(servers, vid):
        ev = vs.store.find_ec_volume(vid)
        if not ev.shards:
            continue
        res = _post_json(
            f"http://{vs.host}:{vs.port}/tier/move"
            f"?volumeId={vid}&direction=out&destination={BACKEND}"
        )
        assert res.get("Backend") == BACKEND, res
        moved += len(res.get("Shards") or [])
    return moved


def _read_all(master, collection, keys):
    for fid, want in keys.items():
        with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/{fid}?collection={collection}",
            timeout=15,
        ) as r:
            assert r.read() == want, f"fid {fid} corrupt"


class TestManualTierMoves:
    def test_out_degraded_reads_then_in(self, tier_cluster):
        master, servers, _s3, backend_dir = tier_cluster
        vid, keys, env = _encode(master, "tiered")

        assert _tier_out_everywhere(servers, vid) == 14
        for vs in _holders(servers, vid):
            ev = vs.store.find_ec_volume(vid)
            assert ev.shards == {} and ev.remote is not None
            st = _get_json(f"http://{vs.host}:{vs.port}/tier/status")
            assert st[str(vid)]["Tiered"]
        assert len(os.listdir(backend_dir)) >= 14
        # the master still routes every shard (serving_shard_ids rides
        # the heartbeat) — no repair stampede for a tiered volume
        assert wait_for(lambda: _registered_shards(master, vid) == 14, 15)

        # every GET is now a degraded read spliced out of backend
        # sub-range fetches — local AND cross-holder (gRPC fallback)
        _read_all(master, "tiered", keys)

        # operator surface agrees
        out = io.StringIO()
        run_command(env, "tier.status", out)
        assert "TIERED" in out.getvalue()
        assert BACKEND in out.getvalue()

        # recall through the shell verb; bytes identical, keys reclaimed
        out = io.StringIO()
        run_command(env, f"tier.move -volumeId {vid} -in", out)
        assert "FAILED" not in out.getvalue()
        for vs in _holders(servers, vid):
            ev = vs.store.find_ec_volume(vid)
            assert ev.remote is None and ev.shards
        _read_all(master, "tiered", keys)

    def test_kill_switch_forbids_moves(self, tier_cluster, monkeypatch):
        master, servers, _s3, _bd = tier_cluster
        vid, keys, _env = _encode(master, "killsw")
        monkeypatch.setenv("WEED_TIER", "0")
        vs = _holders(servers, vid)[0]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(
                f"http://{vs.host}:{vs.port}/tier/move"
                f"?volumeId={vid}&direction=out&destination={BACKEND}"
            )
        assert e.value.code == 403
        # the scheduler is inert too
        sched = TierScheduler(
            master,
            interval=3600,
            rules=TierRules(backend=BACKEND, min_age_s=0.0,
                            cold_reads_per_s=1e9),
        )
        assert sched.scan_once() == 0
        monkeypatch.delenv("WEED_TIER")
        # pre-tier behavior wholesale: plain local reads, nothing moved
        for vs in _holders(servers, vid):
            assert vs.store.find_ec_volume(vid).remote is None
        _read_all(master, "killsw", keys)

    def test_bad_requests_are_typed(self, tier_cluster):
        master, servers, _s3, _bd = tier_cluster
        vs = servers[0]
        base = f"http://{vs.host}:{vs.port}/tier/move"
        for qs, code in (
            ("volumeId=abc&direction=out&destination=d", 400),
            ("volumeId=123456&direction=sideways", 400),
            ("volumeId=123456&direction=out", 400),  # no destination
            ("volumeId=123456&direction=in", 404),  # unknown volume
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post_json(f"{base}?{qs}")
            assert e.value.code == code, qs


class TestTierScheduler:
    def test_scan_tiers_cold_volume_and_reports(self, tier_cluster):
        master, servers, _s3, _bd = tier_cluster
        vid, keys, _env = _encode(master, "coldsched")
        # every volume is "cold" under these rules (no telemetry →
        # rate 0.0; min age 0) — the scheduler has no collection
        # filter, so it sweeps EVERY volume in the shared cluster; the
        # concurrency cap must cover all (holder, vid) pairs or the
        # target vid's moves get deferred to a later scan
        sched = TierScheduler(
            master,
            interval=3600,
            rules=TierRules(
                backend=BACKEND,
                min_age_s=0.0,
                cold_reads_per_s=1e9,
                hot_reads_per_s=1e12,
            ),
            concurrency=32,
            cooldown_s=0.0,
        )
        master.tier = sched
        try:
            launched = sched.scan_once()
            assert launched >= 1
            assert wait_for(
                lambda: all(
                    vs.store.find_ec_volume(vid).remote is not None
                    and not vs.store.find_ec_volume(vid).shards
                    for vs in _holders(servers, vid)
                ),
                60,
            ), sched.status_snapshot()
            assert wait_for(lambda: sched.status_snapshot()["Active"] == 0, 30)
            snap = _get_json(
                f"http://127.0.0.1:{master.port}/cluster/tier"
            )
            assert snap["MovesStarted"] >= 1
            assert snap["Rules"]["Backend"] == BACKEND
            assert any(h["Direction"] == "out" for h in snap["History"])
            assert not any(h["Error"] for h in snap["History"]), snap
            # reads still serve, now from the backend
            _read_all(master, "coldsched", keys)
            # scans converge: once everything cold is tiered, a fresh
            # scan is a no-op (hysteresis holds tiered volumes put)
            time.sleep(0.1)
            assert wait_for(
                lambda: sched.scan_once() == 0
                and sched.status_snapshot()["Active"] == 0,
                60,
            ), sched.status_snapshot()
        finally:
            master.tier = None

    def test_cluster_tier_endpoint_disabled_by_default(self, tier_cluster):
        master, _servers, _s3, _bd = tier_cluster
        snap = _get_json(f"http://127.0.0.1:{master.port}/cluster/tier")
        assert snap.get("Disabled") is True


class TestS3RangeOnTieredVolume:
    def _req(self, url, method="GET", data=None, headers=None):
        r = urllib.request.Request(url, data=data, method=method)
        for k, v in (headers or {}).items():
            r.add_header(k, v)
        return urllib.request.urlopen(r, timeout=20)

    def test_range_reads_206_through_tier_cycle(self, tier_cluster):
        master, servers, s3, _bd = tier_cluster
        base = f"http://127.0.0.1:{s3.port}"
        body = bytes(
            (i * 131 + (i >> 8)) & 0xFF for i in range(300_000)
        )  # 300 KB → several filer chunks at max_mb=1? no — but >1 needle span
        self._req(f"{base}/tierbkt", "PUT").close()
        self._req(f"{base}/tierbkt/blob.bin", "PUT", data=body).close()

        entry = s3._lookup(f"{s3.buckets_path}/tierbkt", "blob.bin")
        assert entry is not None and entry.chunks
        vids = {int(c.fid.split(",")[0]) for c in entry.chunks}
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        for vid in vids:
            do_ec_encode(env, vid, "", io.StringIO())
            assert wait_for(lambda: _registered_shards(master, vid) == 14, 30)
            assert _tier_out_everywhere(servers, vid) == 14

        def check_ranges():
            with self._req(
                f"{base}/tierbkt/blob.bin",
                headers={"Range": "bytes=1000-2999"},
            ) as r:
                assert r.status == 206
                assert r.read() == body[1000:3000]
                assert r.headers["Content-Range"] == (
                    f"bytes 1000-2999/{len(body)}"
                )
            # a tail range crossing needle-chunk boundaries
            with self._req(
                f"{base}/tierbkt/blob.bin",
                headers={"Range": f"bytes={len(body) - 5000}-"},
            ) as r:
                assert r.status == 206
                assert r.read() == body[-5000:]
            with self._req(f"{base}/tierbkt/blob.bin") as r:
                assert r.status == 200
                assert r.read() == body

        check_ranges()  # served degraded, from the tier backend

        for vid in vids:
            for vs in _holders(servers, vid):
                if vs.store.find_ec_volume(vid).remote is None:
                    continue
                _post_json(
                    f"http://{vs.host}:{vs.port}/tier/move"
                    f"?volumeId={vid}&direction=in"
                )
        check_ranges()  # byte-identical again after recall
