"""Striping/shard-file tests, modeled on the reference's ec_test.go:
encode the reference's checked-in volume fixture with small block sizes,
then (a) byte-compare striped shard reads against the original .dat for
every needle, and (b) drop shard subsets and verify rebuild equality.
"""

import os
import random
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.ec import ec_files, locate
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t

# ec_test.go:15-18 — tiny block sizes so the fixture exercises both tiers
LARGE = 10000
SMALL = 100


class TestLocateData:
    def test_pinned_single_interval(self):
        # ec_test.go:187 TestLocateData
        intervals = locate.locate_data(LARGE, SMALL, 10 * LARGE + 1, 10 * LARGE, 1)
        assert len(intervals) == 1
        iv = intervals[0]
        assert (iv.block_index, iv.inner_block_offset, iv.size, iv.is_large_block) == (
            0,
            0,
            1,
            False,
        )

    def test_spanning_intervals_cover_range(self):
        dat_size = 10 * LARGE + 1
        offset = 10 * LARGE // 2 + 100
        size = dat_size - offset
        intervals = locate.locate_data(LARGE, SMALL, dat_size, offset, size)
        assert sum(iv.size for iv in intervals) == size
        # intervals must be contiguous in .dat space: re-derive offsets
        cursor = offset
        for iv in intervals:
            again = locate.locate_data(LARGE, SMALL, dat_size, cursor, iv.size)
            assert again[0] == iv
            cursor += iv.size

    def test_shard_id_and_offset_roundtrip(self):
        dat_size = 3 * 10 * LARGE + 2345
        rng = random.Random(5)
        for _ in range(100):
            offset = rng.randrange(dat_size)
            size = rng.randrange(1, min(5 * SMALL, dat_size - offset) + 1)
            for iv in locate.locate_data(LARGE, SMALL, dat_size, offset, size):
                shard_id, shard_off = iv.to_shard_id_and_offset(LARGE, SMALL)
                assert 0 <= shard_id < 10
                assert 0 <= shard_off


class TestRowCounts:
    def test_strict_greater_quirk(self):
        # exactly one full large row goes through the small tier
        assert ec_files.shard_row_counts(10 * LARGE, LARGE, SMALL) == (0, 100)
        assert ec_files.shard_row_counts(10 * LARGE + 1, LARGE, SMALL) == (1, 1)
        assert ec_files.shard_row_counts(0, LARGE, SMALL) == (0, 0)
        assert ec_files.shard_row_counts(1, LARGE, SMALL) == (0, 1)

    def test_shard_file_size(self):
        assert ec_files.shard_file_size(10 * LARGE + 1, LARGE, SMALL) == LARGE + SMALL


@pytest.fixture(scope="session")
def encoded_fixture(tmp_path_factory, reference_root):
    """The reference's binary volume fixture (1.dat/1.idx — real
    artifacts written by the reference implementation) encoded ONCE with
    the CPU backend; tests copy the results instead of re-encoding."""
    root = tmp_path_factory.mktemp("encoded")
    for ext in (".dat", ".idx"):
        shutil.copyfile(
            reference_root / f"weed/storage/erasure_coding/1{ext}",
            root / f"1{ext}",
        )
    base = str(root / "1")
    _encode_fixture(base)
    return base


@pytest.fixture()
def fixture_volume(tmp_path, encoded_fixture):
    """Per-test scratch copy of the pre-encoded fixture volume."""
    src = os.path.dirname(encoded_fixture)
    for name in os.listdir(src):
        shutil.copyfile(os.path.join(src, name), tmp_path / name)
    return str(tmp_path / "1")


def _encode_fixture(base, backend="cpu", buffer_size=2000):
    rs = new_encoder(backend=backend)
    ec_files.write_ec_files(
        base,
        rs=rs,
        buffer_size=buffer_size,
        large_block_size=LARGE,
        small_block_size=SMALL,
    )


class TestEncodeFixture:
    def test_striped_reads_match_dat(self, fixture_volume):
        # validateFiles (ec_test.go:63-121): every needle's bytes read
        # through the striping must equal the .dat bytes.
        dat = open(fixture_volume + ".dat", "rb").read()
        idx_data = open(fixture_volume + ".idx", "rb").read()
        checked = 0
        for key, offset_units, size in idx_codec.iter_entries(idx_data):
            if size == t.TOMBSTONE_FILE_SIZE or offset_units == 0:
                continue
            offset = t.units_to_offset(offset_units)
            from seaweedfs_tpu.storage.needle import get_actual_size

            span = get_actual_size(size, 3)
            got = ec_files.read_shard_intervals(
                fixture_volume, offset, span, len(dat), LARGE, SMALL
            )
            assert got == dat[offset : offset + span], f"needle {key} mismatch"
            checked += 1
        assert checked > 200

    def test_shard_sizes(self, fixture_volume):
        dat_size = os.path.getsize(fixture_volume + ".dat")
        expect = ec_files.shard_file_size(dat_size, LARGE, SMALL)
        for i in range(14):
            assert os.path.getsize(fixture_volume + ec_files.to_ext(i)) == expect

    def test_tpu_backend_identical_files(self, fixture_volume, tmp_path):
        cpu_shards = [
            open(fixture_volume + ec_files.to_ext(i), "rb").read() for i in range(14)
        ]
        # re-encode with the TPU backend and a different buffer size
        _encode_fixture(fixture_volume, backend="tpu", buffer_size=500)
        for i in range(14):
            tpu_bytes = open(fixture_volume + ec_files.to_ext(i), "rb").read()
            assert tpu_bytes == cpu_shards[i], f"shard {i} differs"

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_rebuild_missing_shards(self, fixture_volume, seed):
        # ec_test.go:141-172: drop a random subset (≤4), rebuild, compare.
        originals = {
            i: open(fixture_volume + ec_files.to_ext(i), "rb").read()
            for i in range(14)
        }
        rng = random.Random(seed)
        missing = rng.sample(range(14), rng.randint(1, 4))
        for i in missing:
            os.remove(fixture_volume + ec_files.to_ext(i))
        rebuilt = ec_files.rebuild_ec_files(fixture_volume)
        assert sorted(rebuilt) == sorted(missing)
        for i in range(14):
            got = open(fixture_volume + ec_files.to_ext(i), "rb").read()
            assert got == originals[i], f"shard {i} not restored"

    def test_rebuild_too_few_raises(self, fixture_volume):
        for i in range(5):
            os.remove(fixture_volume + ec_files.to_ext(i))
        with pytest.raises(ValueError, match="too few"):
            ec_files.rebuild_ec_files(fixture_volume)

    def test_rebuild_noop_when_complete(self, fixture_volume):
        assert ec_files.rebuild_ec_files(fixture_volume) == []


class TestEcx:
    def test_sorted_and_complete(self, fixture_volume):
        ec_files.write_sorted_file_from_idx(fixture_volume)
        ecx = open(fixture_volume + ".ecx", "rb").read()
        keys, offsets, sizes = idx_codec.entries_as_arrays(ecx)
        assert np.all(np.diff(keys.astype(np.int64)) > 0), "keys must ascend strictly"
        idx_data = open(fixture_volume + ".idx", "rb").read()
        live = {}
        for key, off, size in idx_codec.iter_entries(idx_data):
            if off != 0 and size != t.TOMBSTONE_FILE_SIZE:
                live[key] = (off, size)
        assert set(int(k) for k in keys) == set(live)

    def test_delete_of_out_of_order_insert_removed(self, tmp_path):
        # reference CompactMap: out-of-order inserts land in `overflow`,
        # and Delete removes overflow entries entirely
        base = str(tmp_path / "oo")
        entries = (
            idx_codec.pack_entry(10, 1, 100)
            + idx_codec.pack_entry(4, 2, 200)  # out of order -> overflow
            + idx_codec.pack_entry(4, 0, t.TOMBSTONE_FILE_SIZE)
            + idx_codec.pack_entry(10, 0, t.TOMBSTONE_FILE_SIZE)
        )
        with open(base + ".idx", "wb") as f:
            f.write(entries)
        ec_files.write_sorted_file_from_idx(base)
        got = list(idx_codec.iter_entries(open(base + ".ecx", "rb").read()))
        assert got == [(10, 1, t.TOMBSTONE_FILE_SIZE)]

    def test_delete_of_zero_size_entry_is_noop(self, tmp_path):
        base = str(tmp_path / "zz")
        entries = (
            idx_codec.pack_entry(3, 5, 0)  # live zero-size needle
            + idx_codec.pack_entry(3, 0, t.TOMBSTONE_FILE_SIZE)
        )
        with open(base + ".idx", "wb") as f:
            f.write(entries)
        ec_files.write_sorted_file_from_idx(base)
        got = list(idx_codec.iter_entries(open(base + ".ecx", "rb").read()))
        assert got == [(3, 5, 0)]

    def test_delete_entries_tombstone(self, tmp_path):
        base = str(tmp_path / "2")
        entries = (
            idx_codec.pack_entry(5, 10, 100)
            + idx_codec.pack_entry(3, 20, 200)
            + idx_codec.pack_entry(5, 0, t.TOMBSTONE_FILE_SIZE)  # delete 5
            + idx_codec.pack_entry(9, 0, t.TOMBSTONE_FILE_SIZE)  # delete unknown
        )
        with open(base + ".idx", "wb") as f:
            f.write(entries)
        ec_files.write_sorted_file_from_idx(base)
        ecx = open(base + ".ecx", "rb").read()
        got = list(idx_codec.iter_entries(ecx))
        assert got == [(3, 20, 200), (5, 10, t.TOMBSTONE_FILE_SIZE)]

    def test_idx_from_ecx_roundtrip(self, tmp_path):
        base = str(tmp_path / "3")
        with open(base + ".idx", "wb") as f:
            f.write(idx_codec.pack_entry(1, 5, 50) + idx_codec.pack_entry(2, 9, 90))
        ec_files.write_sorted_file_from_idx(base)
        # simulate a journaled delete of needle 2
        with open(base + ".ecj", "wb") as f:
            f.write(t.needle_id_to_bytes(2))
        ec_files.write_idx_file_from_ec_index(base)
        got = list(idx_codec.iter_entries(open(base + ".idx", "rb").read()))
        assert got == [
            (1, 5, 50),
            (2, 9, 90),
            (2, 0, t.TOMBSTONE_FILE_SIZE),
        ]


class TestSyntheticVolume:
    def test_large_tier_roundtrip(self, tmp_path):
        # big enough for 2 large rows + small tail (tiny block sizes)
        base = str(tmp_path / "synth")
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, 2 * 10 * LARGE + 12345, dtype=np.uint8).tobytes()
        with open(base + ".dat", "wb") as f:
            f.write(data)
        rs = new_encoder()
        ec_files.write_ec_files(
            base, rs=rs, buffer_size=2500, large_block_size=LARGE, small_block_size=SMALL
        )
        # spot-check random spans through the striping
        pyrng = random.Random(0)
        for _ in range(50):
            off = pyrng.randrange(len(data))
            size = pyrng.randrange(1, min(3 * SMALL, len(data) - off) + 1)
            got = ec_files.read_shard_intervals(base, off, size, len(data), LARGE, SMALL)
            assert got == data[off : off + size]


class TestStreamDrivers:
    """Pipelined ec_stream drivers must be byte-identical to the
    classic synchronous loops. Kernel stages are injected as numpy
    functions so the pipeline (tiling, in-flight ordering, writes)
    is exercised on CPU hosts; kernel correctness is pinned in
    test_ec_codec.py."""

    def _cpu_stages(self):
        from seaweedfs_tpu.ec.codec import ReedSolomon

        rs = ReedSolomon(backend="cpu")

        def parity_fn(tile):
            return rs._apply(rs.parity_rows, tile)

        def rebuild_fn(survivors, targets, tile):
            from seaweedfs_tpu.ec import gf256

            rows = gf256.decode_rows(rs.matrix, survivors, targets)
            return rs._apply(rows, tile)

        return parity_fn, rebuild_fn, (lambda h: h)

    def test_stream_write_matches_classic(self, tmp_path):
        import numpy as np

        from seaweedfs_tpu.ec import ec_files, ec_stream

        rng = np.random.default_rng(17)
        payload = rng.integers(0, 256, 987_654, dtype=np.uint8).tobytes()
        LARGE, SMALL = 40_000, 4_000

        classic = tmp_path / "classic"
        stream = tmp_path / "stream"
        for d in (classic, stream):
            d.mkdir()
            (d / "1.dat").write_bytes(payload)

        ec_files.write_ec_files(
            str(classic / "1"),
            buffer_size=2_000,
            large_block_size=LARGE,
            small_block_size=SMALL,
        )
        parity_fn, _, fetch = self._cpu_stages()
        ec_stream.stream_write_ec_files(
            str(stream / "1"),
            tile_bytes=16_000,
            large_block_size=LARGE,
            small_block_size=SMALL,
            parity_fn=parity_fn,
            fetch_fn=fetch,
        )
        for i in range(14):
            ext = ec_files.to_ext(i)
            assert (stream / f"1{ext}").read_bytes() == (
                classic / f"1{ext}"
            ).read_bytes(), ext

    def test_stream_rebuild_matches_original(self, tmp_path):
        import os

        import numpy as np

        from seaweedfs_tpu.ec import ec_files, ec_stream

        rng = np.random.default_rng(18)
        payload = rng.integers(0, 256, 500_000, dtype=np.uint8).tobytes()
        LARGE, SMALL = 40_000, 4_000
        base = str(tmp_path / "1")
        (tmp_path / "1.dat").write_bytes(payload)
        ec_files.write_ec_files(
            base, buffer_size=2_000, large_block_size=LARGE, small_block_size=SMALL
        )
        originals = {
            i: open(base + ec_files.to_ext(i), "rb").read() for i in range(14)
        }
        for sid in (1, 7, 10, 13):
            os.remove(base + ec_files.to_ext(sid))

        _, rebuild_fn, fetch = self._cpu_stages()
        rebuilt = ec_stream.stream_rebuild_ec_files(
            base, tile_bytes=12_000, rebuild_fn=rebuild_fn, fetch_fn=fetch
        )
        assert rebuilt == [1, 7, 10, 13]
        for i in range(14):
            assert (
                open(base + ec_files.to_ext(i), "rb").read() == originals[i]
            ), i


    def test_stream_write_stage_error_propagates(self, tmp_path):
        """A kernel-stage failure mid-stream must raise on the caller
        (not hang the reader/writer threads and not leave them alive)."""
        import threading

        import numpy as np
        import pytest as _pytest

        from seaweedfs_tpu.ec import ec_stream

        rng = np.random.default_rng(19)
        (tmp_path / "1.dat").write_bytes(
            rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
        )
        calls = {"n": 0}

        def parity_fn(tile):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("kernel died")
            return np.zeros((4, tile.shape[1]), dtype=np.uint8)

        # warm the lazy trace-drainer thread before the leak baseline
        from seaweedfs_tpu import trace

        with trace.span("warmup"):
            pass
        before = threading.active_count()
        with _pytest.raises(RuntimeError, match="kernel died"):
            ec_stream.stream_write_ec_files(
                str(tmp_path / "1"),
                tile_bytes=16_000,
                large_block_size=40_000,
                small_block_size=4_000,
                parity_fn=parity_fn,
                fetch_fn=lambda h: h,
            )
        assert threading.active_count() <= before  # stage threads joined

    def test_stream_write_pool_identical_odd_sizes(self, tmp_path):
        """The pwritev writer POOL lands tiles in completion order —
        positioned writes must keep the bytes identical to the classic
        serial driver on awkward sizes (tail zero-padding, one-tile
        rows, sub-tile remainders)."""
        import numpy as np

        from seaweedfs_tpu.ec import ec_files, ec_stream

        LARGE, SMALL = 40_000, 4_000
        rng = np.random.default_rng(23)
        parity_fn, _, fetch = self._cpu_stages()
        for size in (1, 3_999, 123_457, 1_000_001):
            classic = tmp_path / f"c{size}"
            stream = tmp_path / f"s{size}"
            payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
            for d in (classic, stream):
                d.mkdir()
                (d / "1.dat").write_bytes(payload)
            ec_files.write_ec_files(
                str(classic / "1"),
                rs=new_encoder(backend="cpu"),
                buffer_size=2_000,
                large_block_size=LARGE,
                small_block_size=SMALL,
            )
            ec_stream.stream_write_ec_files(
                str(stream / "1"),
                tile_bytes=7_000,
                large_block_size=LARGE,
                small_block_size=SMALL,
                parity_fn=parity_fn,
                fetch_fn=fetch,
                writer_threads=3,
                reader_threads=2,
            )
            for i in range(14):
                ext = ec_files.to_ext(i)
                assert (stream / f"1{ext}").read_bytes() == (
                    classic / f"1{ext}"
                ).read_bytes(), (size, ext)

    def test_stream_write_enospc_abort_no_leaks(self, tmp_path, monkeypatch):
        """A short-write/ENOSPC surfacing in the writer POOL mid-stream
        must raise on the caller, join every pool thread, and leak no
        fd (the .dat readers and all 14 preallocated shard fds)."""
        import errno
        import os
        import threading

        import numpy as np
        import pytest as _pytest

        from seaweedfs_tpu.ec import ec_stream

        rng = np.random.default_rng(29)
        (tmp_path / "1.dat").write_bytes(
            rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
        )
        calls = {"n": 0}
        real_pwritev = ec_stream._pwritev_full

        def flaky_pwritev(fd, bufs, offset):
            calls["n"] += 1
            if calls["n"] == 20:
                raise OSError(errno.ENOSPC, "No space left on device")
            return real_pwritev(fd, bufs, offset)

        monkeypatch.setattr(ec_stream, "_pwritev_full", flaky_pwritev)
        # the first completed span in a process starts the trace
        # drainer thread lazily — warm it so the leak check below
        # counts only pool threads
        from seaweedfs_tpu import trace

        with trace.span("warmup"):
            pass
        fds_before = len(os.listdir("/proc/self/fd"))
        threads_before = threading.active_count()
        with _pytest.raises(OSError, match="No space left"):
            ec_stream.stream_write_ec_files(
                str(tmp_path / "1"),
                tile_bytes=4_000,
                large_block_size=40_000,
                small_block_size=4_000,
                parity_fn=lambda t: np.zeros((4, t.shape[1]), dtype=np.uint8),
                fetch_fn=lambda h: h,
                writer_threads=3,
                reader_threads=2,
            )
        assert threading.active_count() <= threads_before
        assert len(os.listdir("/proc/self/fd")) == fds_before
        # the trace span must record the failure: an aborted encode
        # that looks clean in /debug/traces would hide exactly the
        # repair-path behavior the tracing plane exists to attribute
        from seaweedfs_tpu import trace

        encode_spans = [
            s
            for s in trace.debug_payload(n=64)["recent"]
            if s["name"] == "ec_stream.encode"
        ]
        assert encode_spans, "no ec_stream.encode span recorded"
        assert "No space left" in encode_spans[0].get("error", "")
        # no half-written shard files survive the abort: shard_presence
        # would otherwise count the garbage as a complete valid set
        from seaweedfs_tpu.ec import ec_files

        for i in range(14):
            assert not os.path.exists(
                str(tmp_path / "1") + ec_files.to_ext(i)
            ), i

    def test_stream_rebuild_enospc_abort_no_leaks(self, tmp_path, monkeypatch):
        import errno
        import os
        import threading

        import numpy as np
        import pytest as _pytest

        from seaweedfs_tpu.ec import ec_files, ec_stream

        rng = np.random.default_rng(31)
        (tmp_path / "1.dat").write_bytes(
            rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
        )
        base = str(tmp_path / "1")
        ec_files.write_ec_files(
            base,
            rs=new_encoder(backend="cpu"),
            buffer_size=2_000,
            large_block_size=40_000,
            small_block_size=4_000,
        )
        os.remove(base + ec_files.to_ext(2))
        _, rebuild_fn, fetch = self._cpu_stages()

        def broken_pwrite(fd, buf, offset):
            raise OSError(errno.ENOSPC, "No space left on device")

        monkeypatch.setattr(ec_stream, "_pwrite_full", broken_pwrite)
        # warm the lazy trace-drainer thread before the leak baseline
        from seaweedfs_tpu import trace

        with trace.span("warmup"):
            pass
        fds_before = len(os.listdir("/proc/self/fd"))
        threads_before = threading.active_count()
        with _pytest.raises(OSError, match="No space left"):
            ec_stream.stream_rebuild_ec_files(
                base,
                tile_bytes=3_000,
                rebuild_fn=rebuild_fn,
                fetch_fn=fetch,
                writer_threads=2,
                reader_threads=2,
            )
        assert threading.active_count() <= threads_before
        assert len(os.listdir("/proc/self/fd")) == fds_before
        # the half-written target was removed (a retry must see it as
        # still missing), the survivors untouched
        assert not os.path.exists(base + ec_files.to_ext(2))
        assert os.path.exists(base + ec_files.to_ext(3))

    def test_stream_rebuild_remote_readers_identical(self, tmp_path):
        """The rack-gather path: survivors held only by OTHER nodes
        arrive through injected remote readers; shards readable
        remotely are treated as present (not rebuilt) and the rebuilt
        bytes match the originals exactly."""
        import os

        import numpy as np

        from seaweedfs_tpu.ec import ec_files, ec_stream

        rng = np.random.default_rng(37)
        (tmp_path / "1.dat").write_bytes(
            rng.integers(0, 256, 500_000, dtype=np.uint8).tobytes()
        )
        base = str(tmp_path / "1")
        ec_files.write_ec_files(
            base, buffer_size=2_000, large_block_size=40_000, small_block_size=4_000
        )
        originals = {
            i: open(base + ec_files.to_ext(i), "rb").read() for i in range(14)
        }
        # shards 4..9 live only on the "remote holder" (moved away);
        # shards 2 and 12 are lost cluster-wide
        remote_dir = tmp_path / "remote"
        remote_dir.mkdir()
        remote_held = (4, 5, 6, 7, 8, 9)
        for sid in remote_held:
            os.rename(
                base + ec_files.to_ext(sid),
                str(remote_dir / f"1{ec_files.to_ext(sid)}"),
            )
        for sid in (2, 12):
            os.remove(base + ec_files.to_ext(sid))

        def make_reader(sid):
            path = str(remote_dir / f"1{ec_files.to_ext(sid)}")

            def read(offset, size):
                with open(path, "rb") as f:
                    f.seek(offset)
                    return f.read(size)

            return read

        _, rebuild_fn, fetch = self._cpu_stages()
        rebuilt = ec_stream.stream_rebuild_ec_files(
            base,
            tile_bytes=12_000,
            rebuild_fn=rebuild_fn,
            fetch_fn=fetch,
            remote_readers={sid: make_reader(sid) for sid in remote_held},
            writer_threads=2,
            reader_threads=2,
        )
        assert rebuilt == [2, 12]
        for sid in (2, 12):
            assert (
                open(base + ec_files.to_ext(sid), "rb").read()
                == originals[sid]
            ), sid
        # remote-held shards were NOT recreated locally
        for sid in remote_held:
            assert not os.path.exists(base + ec_files.to_ext(sid)), sid

    def test_stream_rebuild_read_error_propagates(self, tmp_path):
        """A truncated survivor detected by the reader THREAD must
        surface as the caller's exception."""
        import os

        import numpy as np
        import pytest as _pytest

        from seaweedfs_tpu.ec import ec_files, ec_stream

        rng = np.random.default_rng(20)
        (tmp_path / "1.dat").write_bytes(
            rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()
        )
        base = str(tmp_path / "1")
        ec_files.write_ec_files(
            base, buffer_size=2_000, large_block_size=40_000, small_block_size=4_000
        )
        os.remove(base + ec_files.to_ext(12))
        # truncate a survivor below one tile so the reader's pread fails
        surv = base + ec_files.to_ext(3)
        with open(surv, "r+b") as f:
            f.truncate(1_000)

        _, rebuild_fn, fetch = self._cpu_stages()
        with _pytest.raises(ValueError, match="truncated"):
            ec_stream.stream_rebuild_ec_files(
                base, tile_bytes=12_000, rebuild_fn=rebuild_fn, fetch_fn=fetch
            )


class TestLocateProperty:
    """Randomized cross-check of the striping math against the actual
    encoder: encode random .dat sizes with tiny block sizes, then for
    random spans gather bytes via locate_data +
    to_shard_id_and_offset from the shard FILES and compare with the
    .dat bytes. Covers multi-row large-tier layouts the fixture tests
    (production block sizes, tiny volumes) never reach."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_spans_roundtrip(self, seed, tmp_path):
        import random as _r

        rng = _r.Random(seed)
        large, small = 1000, 100  # tiny two-tier layout
        large_row = large * locate.DATA_SHARDS
        # avoid the documented exact-large-row-multiple reference quirk
        while True:
            dat_size = rng.randint(1, 4 * large_row)
            if dat_size % large_row:
                break
        base = str(tmp_path / f"p{seed}")
        data = bytes(rng.randbytes(dat_size))
        with open(base + ".dat", "wb") as f:
            f.write(data)
        ec_files.write_ec_files(
            base,
            rs=new_encoder(backend="cpu"),
            buffer_size=small,
            large_block_size=large,
            small_block_size=small,
        )
        shards = [
            open(base + ec_files.to_ext(i), "rb").read()
            for i in range(locate.DATA_SHARDS)
        ]
        for _ in range(25):
            off = rng.randint(0, dat_size - 1)
            size = rng.randint(1, min(dat_size - off, 3 * large))
            got = bytearray()
            for iv in locate.locate_data(large, small, dat_size, off, size):
                sid, soff = iv.to_shard_id_and_offset(large, small)
                got += shards[sid][soff : soff + iv.size]
            assert bytes(got) == data[off : off + size], (
                f"dat_size={dat_size} span=({off},{size})"
            )
