"""Mesh-parallel codec tests over the 8-virtual-device CPU mesh.

The conftest forces 8 XLA host devices; these tests build real
(vol × stripe) Meshes, run the shard_map'd batched encode / rebuild /
verify programs, and pin byte-equality against the CPU LUT backend —
the multi-device story of SURVEY §2.6/§2.7 exercised for real
(the driver separately dry-runs __graft_entry__.dryrun_multichip).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


@pytest.fixture(scope="module")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices (conftest sets XLA_FLAGS)")
    return devs[:8]


def _host_batch(rng, b, k, n):
    return rng.integers(0, 256, (b, k, n), dtype=np.uint8)


def _cpu_parity(batch):
    from seaweedfs_tpu.ec.codec import new_encoder

    rs = new_encoder(backend="cpu")
    out = []
    for vol in batch:
        shards = [vol[i].copy() for i in range(10)] + [None] * 4
        rs.encode(shards)
        out.append(np.stack(shards[10:]))
    return np.stack(out)


class TestMakeMesh:
    def test_shapes(self, eight_devices):
        from seaweedfs_tpu.parallel import make_mesh

        mesh = make_mesh(eight_devices)
        assert mesh.devices.shape == (4, 2)
        assert mesh.axis_names == ("vol", "stripe")
        mesh1 = make_mesh(eight_devices, stripe=1)
        assert mesh1.devices.shape == (8, 1)
        with pytest.raises(ValueError):
            make_mesh(eight_devices, stripe=3)


class TestMeshCodec:
    @pytest.fixture(scope="class")
    def codec(self, eight_devices):
        from seaweedfs_tpu.parallel import MeshCodec, make_mesh

        return MeshCodec(make_mesh(eight_devices))

    def test_encode_batch_matches_cpu(self, codec):
        rng = np.random.default_rng(41)
        host = _host_batch(rng, 8, 10, 512)  # B=8 over vol=4, N=512 over stripe=2
        parity = np.asarray(codec.encode_batch(codec.shard_volumes(host)))
        np.testing.assert_array_equal(parity, _cpu_parity(host))

    def test_encode_is_sharded(self, codec):
        rng = np.random.default_rng(42)
        host = _host_batch(rng, 4, 10, 256)
        vols = codec.shard_volumes(host)
        parity = codec.encode_batch(vols)
        # output keeps the (vol, -, stripe) layout: each device holds a
        # [B/4, 4, N/2] tile
        shard_shapes = {s.data.shape for s in parity.addressable_shards}
        assert shard_shapes == {(1, 4, 128)}
        assert len(parity.addressable_shards) == 8

    def test_reconstruct_batch(self, codec):
        rng = np.random.default_rng(43)
        host = _host_batch(rng, 4, 10, 256)
        parity = _cpu_parity(host)
        all_shards = np.concatenate([host, parity], axis=1)  # [B, 14, N]

        lost = (0, 5, 11, 13)  # worst case: 4 missing, mixed data/parity
        survivors = tuple(i for i in range(14) if i not in lost)
        surv_blocks = codec.shard_volumes(all_shards[:, list(survivors), :])
        rebuilt = np.asarray(
            codec.reconstruct_batch(survivors, lost, surv_blocks)
        )
        for j, t in enumerate(lost):
            np.testing.assert_array_equal(rebuilt[:, j], all_shards[:, t])

    def test_verify_batch_psum(self, codec):
        rng = np.random.default_rng(44)
        host = _host_batch(rng, 4, 10, 256)
        parity = _cpu_parity(host)
        good = np.asarray(
            codec.verify_batch(
                codec.shard_volumes(host), codec.shard_volumes(parity)
            )
        )
        np.testing.assert_array_equal(good, np.zeros(4, dtype=np.int32))

        # corrupt one byte of volume 2's parity: only that volume's
        # residual fires, and the psum sees it from whichever stripe
        # device owns the byte
        parity_bad = parity.copy()
        parity_bad[2, 1, 250] ^= 0xFF
        bad = np.asarray(
            codec.verify_batch(
                codec.shard_volumes(host), codec.shard_volumes(parity_bad)
            )
        )
        assert bad[2] > 0
        assert bad[0] == bad[1] == bad[3] == 0

    def test_encode_u32_matmul_fallback_matches_cpu(self, codec):
        """The u32-lane mesh API on a CPU mesh (matmul per device) is
        byte-identical to the CPU LUT backend."""
        rng = np.random.default_rng(46)
        host = _host_batch(rng, 8, 10, 4096)
        host_u32 = host.view(np.uint32)  # [8, 10, 1024] lanes
        parity_u32 = np.asarray(
            codec.encode_batch_u32(codec.shard_volumes(host_u32))
        )
        np.testing.assert_array_equal(
            parity_u32.view(np.uint8), _cpu_parity(host)
        )

    def test_reconstruct_u32_matches_cpu(self, codec):
        rng = np.random.default_rng(47)
        host = _host_batch(rng, 4, 10, 4096)
        parity = _cpu_parity(host)
        all_shards = np.concatenate([host, parity], axis=1)
        lost = (0, 1, 2, 3)  # worst case: all-data losses
        survivors = tuple(i for i in range(14) if i not in lost)
        surv_u32 = all_shards[:, list(survivors), :].view(np.uint32)
        rebuilt = np.asarray(
            codec.reconstruct_batch_u32(
                survivors, lost, codec.shard_volumes(surv_u32)
            )
        )
        for j, t in enumerate(lost):
            np.testing.assert_array_equal(
                rebuilt[:, j].view(np.uint8), all_shards[:, t]
            )

    def test_swar_interpret_equals_matmul_on_mesh(self, eight_devices):
        """The per-device SWAR kernel (Pallas interpreter) and the
        matmul fallback produce identical bytes through the SAME
        shard_map program shape — the pin that the TPU-mesh fast path
        computes what the CPU-mesh fallback does (VERDICT r2 weak #2:
        nothing exercised the 'SWAR usable under shard_map' claim)."""
        from seaweedfs_tpu.parallel import MeshCodec, make_mesh

        mesh = make_mesh(eight_devices)
        rng = np.random.default_rng(48)
        host = _host_batch(rng, 4, 10, 2048)  # per device: [1, 10, 256] lanes
        host_u32 = host.view(np.uint32)

        fallback = MeshCodec(mesh)
        swar = MeshCodec(mesh)
        swar._swar_interpret = True

        p_fallback = np.asarray(
            fallback.encode_batch_u32(fallback.shard_volumes(host_u32))
        )
        p_swar = np.asarray(swar.encode_batch_u32(swar.shard_volumes(host_u32)))
        np.testing.assert_array_equal(p_swar, p_fallback)
        np.testing.assert_array_equal(p_swar.view(np.uint8), _cpu_parity(host))

        lost = (2, 7)
        survivors = tuple(i for i in range(14) if i not in lost)[:10]
        all_shards = np.concatenate([host, p_fallback.view(np.uint8)], axis=1)
        surv_u32 = all_shards[:, list(survivors), :].view(np.uint32)
        r_fallback = np.asarray(
            fallback.reconstruct_batch_u32(
                survivors, lost, fallback.shard_volumes(surv_u32)
            )
        )
        r_swar = np.asarray(
            swar.reconstruct_batch_u32(
                survivors, lost, swar.shard_volumes(surv_u32)
            )
        )
        np.testing.assert_array_equal(r_swar, r_fallback)
        for j, t in enumerate(lost):
            np.testing.assert_array_equal(
                r_swar[:, j].view(np.uint8), all_shards[:, t]
            )

    def test_stripe_only_mesh_long_stream(self, eight_devices):
        """SP analogue: one volume's stream split across all 8 devices."""
        from seaweedfs_tpu.parallel import MeshCodec, make_mesh

        codec = MeshCodec(make_mesh(eight_devices, stripe=8))
        rng = np.random.default_rng(45)
        host = _host_batch(rng, 1, 10, 8 * 512)
        parity = np.asarray(codec.encode_batch(codec.shard_volumes(host)))
        np.testing.assert_array_equal(parity, _cpu_parity(host))


class TestByteApiSwarUnification:
    """The byte-layout APIs route through the SWAR u32 kernel under
    interpret mode, pinning byte-identity against the matmul tier on a
    CPU mesh (VERDICT r3 weak #3). On REAL TPU meshes byte layouts keep
    the matmul tier — device-side u8<->u32 views cost a 12.8x tiled
    relayout (docs/EC_KERNEL.md); the fast tier is the *_u32 APIs."""

    def _codecs(self, eight_devices):
        from seaweedfs_tpu.parallel import MeshCodec, make_mesh

        mesh = make_mesh(eight_devices)
        fallback = MeshCodec(mesh)
        swar = MeshCodec(mesh)
        swar._swar_interpret = True
        return fallback, swar

    def test_gate_picks_swar_only_when_aligned(self, eight_devices):
        fallback, swar = self._codecs(eight_devices)
        # stripe=2: per-device bytes must be a multiple of 4*256
        assert swar._swar_ok(2048)
        assert not swar._swar_ok(512)
        assert not swar._swar_ok(2048 + 8)
        assert not fallback._swar_ok(2048)  # CPU mesh, no interpret

    def test_encode_and_verify_bytes_match(self, eight_devices):
        fallback, swar = self._codecs(eight_devices)
        rng = np.random.default_rng(51)
        host = _host_batch(rng, 4, 10, 2048)  # per device 1024 B = 256 lanes
        assert swar._swar_ok(host.shape[-1])
        p_fb = np.asarray(fallback.encode_batch(fallback.shard_volumes(host)))
        p_sw = np.asarray(swar.encode_batch(swar.shard_volumes(host)))
        np.testing.assert_array_equal(p_sw, p_fb)
        np.testing.assert_array_equal(p_sw, _cpu_parity(host))
        # verify: zero residual on good parity, fires on corruption,
        # with the SAME byte-sum residual as the matmul tier
        good = np.asarray(
            swar.verify_batch(
                swar.shard_volumes(host), swar.shard_volumes(p_sw)
            )
        )
        np.testing.assert_array_equal(good, np.zeros(4, dtype=np.int32))
        bad_parity = p_sw.copy()
        bad_parity[1, 0, 2000] ^= 0x5A
        bad_sw = np.asarray(
            swar.verify_batch(
                swar.shard_volumes(host), swar.shard_volumes(bad_parity)
            )
        )
        bad_fb = np.asarray(
            fallback.verify_batch(
                fallback.shard_volumes(host),
                fallback.shard_volumes(bad_parity),
            )
        )
        np.testing.assert_array_equal(bad_sw, bad_fb)
        assert bad_sw[1] > 0 and bad_sw[0] == bad_sw[2] == bad_sw[3] == 0

    def test_verify_u32_matches_byte_tier(self, eight_devices):
        """verify_batch_u32 (the TPU production tier: SWAR recompute +
        mismatched-lane psum) agrees with the byte tiers on the
        0-iff-verified contract, via interpret mode on a CPU mesh."""
        fallback, swar = self._codecs(eight_devices)
        rng = np.random.default_rng(53)
        host = _host_batch(rng, 4, 10, 2048)
        parity = _cpu_parity(host)
        h32, p32 = host.view(np.uint32), parity.view(np.uint32)
        for codec in (fallback, swar):
            good = np.asarray(
                codec.verify_batch_u32(
                    codec.shard_volumes(h32), codec.shard_volumes(p32)
                )
            )
            np.testing.assert_array_equal(good, np.zeros(4, dtype=np.int32))
            bad = p32.copy()
            bad[2, 1, 100] ^= 0xFF00
            res = np.asarray(
                codec.verify_batch_u32(
                    codec.shard_volumes(h32), codec.shard_volumes(bad)
                )
            )
            assert res[2] == 1 and res[0] == res[1] == res[3] == 0

    def test_reconstruct_bytes_match(self, eight_devices):
        fallback, swar = self._codecs(eight_devices)
        rng = np.random.default_rng(52)
        host = _host_batch(rng, 4, 10, 2048)
        parity = _cpu_parity(host)
        all_shards = np.concatenate([host, parity], axis=1)
        lost = (0, 5, 11, 13)
        survivors = tuple(i for i in range(14) if i not in lost)
        surv = all_shards[:, list(survivors), :]
        r_fb = np.asarray(
            fallback.reconstruct_batch(
                survivors, lost, fallback.shard_volumes(surv)
            )
        )
        r_sw = np.asarray(
            swar.reconstruct_batch(survivors, lost, swar.shard_volumes(surv))
        )
        np.testing.assert_array_equal(r_sw, r_fb)
        for j, t in enumerate(lost):
            np.testing.assert_array_equal(r_sw[:, j], all_shards[:, t])
