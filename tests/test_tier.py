"""Tiered storage: backend SPI + S3 tier against our own S3 gateway.

Reference role: weed/storage/backend/ + volume_grpc_tier_upload.go /
tier_download.go + shell command_volume_tier_*.go. The remote tier in
these tests is this repo's own S3 gateway (filer + volume + master
underneath), so the whole loop runs in-process with zero external
dependencies — upload a sealed volume's .dat, read needles through
ranged GETs, download it back.
"""

import socket
import time

import pytest

ACCESS, SECRET = "tier_access", "tier_secret"


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


@pytest.fixture(scope="module")
def tier_env(tmp_path_factory):
    """A full stack: cluster A (data) + cluster B (S3 remote tier)."""
    from seaweedfs_tpu.s3api import S3ApiServer
    from seaweedfs_tpu.s3api.auth import Identity, IdentityAccessManagement
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage import backend as bk

    servers = []

    def up(srv):
        srv.start()
        servers.append(srv)
        return srv

    # remote-tier stack: master + volume + filer + s3 gateway
    m2 = up(MasterServer(port=free_port(), volume_size_limit_mb=64))
    v2 = up(
        VolumeServer(
            [str(tmp_path_factory.mktemp("tier_remote_vs"))],
            port=free_port(),
            master=f"127.0.0.1:{m2.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
    )
    deadline = time.time() + 45
    while time.time() < deadline and len(m2.topology.data_nodes()) < 1:
        time.sleep(0.05)
    f2 = up(FilerServer([f"127.0.0.1:{m2.port}"], port=free_port(), store="memory"))
    iam = IdentityAccessManagement([Identity("tier", ACCESS, SECRET)])
    s3 = up(
        S3ApiServer(
            filer=f"127.0.0.1:{f2.port}",
            port=free_port(),
            iam=iam,
        )
    )

    # data stack: master + volume with the s3 backend configured
    backends = {
        "s3": {
            "default": {
                "enabled": True,
                "endpoint": f"127.0.0.1:{s3.port}",
                "bucket": "volume-tier",
                "access_key": ACCESS,
                "secret_key": SECRET,
            }
        }
    }
    m1 = up(MasterServer(port=free_port(), volume_size_limit_mb=64))
    v1 = up(
        VolumeServer(
            [str(tmp_path_factory.mktemp("tier_data_vs"))],
            port=free_port(),
            master=f"127.0.0.1:{m1.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            storage_backends=backends,
        )
    )
    deadline = time.time() + 45
    while time.time() < deadline and len(m1.topology.data_nodes()) < 1:
        time.sleep(0.05)

    # the tier bucket must exist
    from seaweedfs_tpu.s3api.client import S3Client

    S3Client(f"127.0.0.1:{s3.port}", ACCESS, SECRET).create_bucket("volume-tier")

    yield m1, v1, s3
    for srv in reversed(servers):
        srv.stop()
    bk.BACKEND_STORAGES.clear()


class TestS3Client:
    def test_put_get_range_delete(self, tier_env):
        from seaweedfs_tpu.s3api.client import S3Client, S3ClientError

        _, _, s3 = tier_env
        c = S3Client(f"127.0.0.1:{s3.port}", ACCESS, SECRET)
        payload = bytes(range(256)) * 8
        c.put_object("volume-tier", "probe.bin", payload)
        assert c.get_object("volume-tier", "probe.bin") == payload
        assert c.get_object("volume-tier", "probe.bin", 10, 16) == payload[10:26]
        assert c.get_object("volume-tier", "probe.bin", 2040) == payload[2040:]
        c.delete_object("volume-tier", "probe.bin")
        with pytest.raises(S3ClientError):
            c.get_object("volume-tier", "probe.bin")


class TestTierLifecycle:
    def test_upload_read_download(self, tier_env):
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2

        m1, v1, s3 = tier_env
        master = f"127.0.0.1:{m1.port}"

        # write a few needles
        fids = []
        for i in range(5):
            ar = op.assign(master)
            payload = f"tiered needle {i}".encode() * 50
            ur = op.upload(f"{ar.url}/{ar.fid}", payload, jwt=ar.auth)
            assert not ur.error
            fids.append((ar.fid, payload))
        vid = int(fids[0][0].split(",")[0])

        # move the volume's .dat to the s3 tier
        with grpc.insecure_channel(f"127.0.0.1:{v1.grpc_port}") as ch:
            list(
                rpc.volume_stub(ch).VolumeTierMoveDatToRemote(
                    volume_pb2.VolumeTierMoveDatToRemoteRequest(
                        volume_id=vid,
                        collection="",
                        destination_backend_name="s3.default",
                    )
                )
            )

        vol = v1.store.find_volume(vid)
        assert vol.has_remote_file()
        assert vol.read_only
        import os

        assert not os.path.exists(vol.base_name + ".dat")
        assert os.path.exists(vol.base_name + ".vif")

        # reads now ride ranged GETs against the s3 gateway
        for fid, payload in fids:
            if int(fid.split(",")[0]) != vid:
                continue
            data, _ = op.download(f"{v1.host}:{v1.port}/{fid}")
            assert data == payload

        # bring it back down
        with grpc.insecure_channel(f"127.0.0.1:{v1.grpc_port}") as ch:
            list(
                rpc.volume_stub(ch).VolumeTierMoveDatFromRemote(
                    volume_pb2.VolumeTierMoveDatFromRemoteRequest(
                        volume_id=vid, collection=""
                    )
                )
            )
        assert not vol.has_remote_file()
        assert os.path.exists(vol.base_name + ".dat")
        for fid, payload in fids:
            if int(fid.split(",")[0]) != vid:
                continue
            data, _ = op.download(f"{v1.host}:{v1.port}/{fid}")
            assert data == payload

    def test_volume_reload_from_vif(self, tier_env, tmp_path):
        """A restarted server loads a tiered volume from .vif + .idx."""
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2
        from seaweedfs_tpu.storage.disk_location import DiskLocation

        m1, v1, s3 = tier_env
        master = f"127.0.0.1:{m1.port}"
        ar = op.assign(master, collection="reload")
        # incompressible: raw-needle asserts below (see tail test note)
        payload = bytes(range(256)) * 4
        assert not op.upload(f"{ar.url}/{ar.fid}", payload, jwt=ar.auth).error
        vid = int(ar.fid.split(",")[0])

        with grpc.insecure_channel(f"127.0.0.1:{v1.grpc_port}") as ch:
            list(
                rpc.volume_stub(ch).VolumeTierMoveDatToRemote(
                    volume_pb2.VolumeTierMoveDatToRemoteRequest(
                        volume_id=vid,
                        collection="reload",
                        destination_backend_name="s3.default",
                    )
                )
            )
        directory = v1.store.locations[0].directory
        fresh = DiskLocation(directory, max_volume_count=100)
        fresh.load_existing_volumes()
        vol = fresh.volumes[vid]
        assert vol.has_remote_file() and vol.read_only
        from seaweedfs_tpu.storage.file_id import FileId

        fid = FileId.parse(ar.fid)
        n = vol.read_needle(fid.key, fid.cookie)
        assert bytes(n.data) == payload
