"""weedlint self-tests: the analysis plane must catch what it claims.

A checker that silently goes blind is worse than no checker — every
rule here gets a positive control (a synthetic tree with a planted
bug the rule MUST flag) and the real tree gets the negative control
(`python -m seaweedfs_tpu.analysis` exits 0, which is also the
acceptance gate bench.py --check drives).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
import threading
import time

import pytest

from seaweedfs_tpu.analysis import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)


def _write_pkg(tmp_path, files: dict[str, str]) -> str:
    root = tmp_path / "fakepkg"
    root.mkdir()
    (root / "__init__.py").write_text("")
    for name, src in files.items():
        (root / name).write_text(textwrap.dedent(src))
    return str(root)


# ---------------------------------------------------------------------------
# suppression policy


class TestSuppressions:
    def test_reason_required(self):
        sup = scan_suppressions(
            "x = 1  # weedlint: ignore[hot-loop-sleep]\n"
            "y = 2  # weedlint: ignore[lock-order] — held across tx\n"
        )
        assert sup.bare == [(1, "hot-loop-sleep")]
        assert "lock-order" in sup.by_line[2]

    def test_bare_ignore_becomes_finding(self):
        kept, _ = apply_suppressions(
            [], {"mod.py": "a = 1  # weedlint: ignore[x]\n"}
        )
        assert [f.rule for f in kept] == ["bare-ignore"]

    def test_comment_above_silences_next_line(self):
        findings = [Finding("hot-loop-sleep", "mod.py", 2, "m")]
        kept, suppressed = apply_suppressions(
            findings,
            {"mod.py": "# weedlint: ignore[hot-loop-sleep] — bounded\n"
                       "time.sleep(1)\n"},
        )
        assert not kept and len(suppressed) == 1

    def test_inline_ignore_does_not_bleed_to_next_line(self):
        """An inline ignore must not silence an adjacent unannotated
        finding on the following line."""
        findings = [
            Finding("hot-loop-sleep", "mod.py", 1, "annotated"),
            Finding("hot-loop-sleep", "mod.py", 2, "NOT annotated"),
        ]
        kept, suppressed = apply_suppressions(
            findings,
            {"mod.py": "time.sleep(a)  # weedlint: ignore[hot-loop-sleep] — bounded\n"
                       "time.sleep(b)\n"},
        )
        assert len(suppressed) == 1 and suppressed[0].line == 1
        assert len(kept) == 1 and kept[0].line == 2


# ---------------------------------------------------------------------------
# static lock-order


class TestLockOrder:
    def test_cycle_detected(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def ab(self):
                    with self.la:
                        with self.lb:
                            pass

                def ba(self):
                    with self.lb:
                        with self.la:
                            pass
        """})
        findings, _ = lockorder.check(root)
        assert any(f.rule == "lock-order" for f in findings)
        msg = next(f for f in findings if f.rule == "lock-order").message
        assert "A.la" in msg and "A.lb" in msg

    def test_interprocedural_cycle_via_method_call(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def helper(self):
                    with self.lb:
                        pass

                def ab(self):
                    with self.la:
                        self.helper()

                def ba(self):
                    with self.lb:
                        with self.la:
                            pass
        """})
        findings, _ = lockorder.check(root)
        assert any(f.rule == "lock-order" for f in findings)

    def test_callback_param_edge(self, tmp_path):
        """The precheck-callback idiom: locks a callback takes are
        ordered after locks the callee holds at its param() call."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Vol:
                def __init__(self):
                    self.vlock = threading.Lock()

                def write(self, precheck=None):
                    with self.vlock:
                        if precheck is not None and not precheck():
                            raise RuntimeError()

            class Worker:
                def __init__(self):
                    self.rlock = threading.Lock()
                    self.v = None

                def handle(self, v: Vol):
                    def still_owned():
                        with self.rlock:
                            return True
                    v.write(precheck=still_owned)

                def inverted(self, v: Vol):
                    with self.rlock:
                        with v.vlock:
                            pass
        """})
        findings, index = lockorder.check(root)
        edges = lockorder.build_lock_graph(index)
        assert ("Vol.vlock", "Worker.rlock") in edges
        assert any(f.rule == "lock-order" for f in findings)

    def test_sequential_not_a_cycle(self, tmp_path):
        """The _shard_release shape: take-release then take the other
        — no nesting, no edge, no finding."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def ab(self):
                    with self.la:
                        with self.lb:
                            pass

                def sequential(self):
                    with self.lb:
                        x = 1
                    with self.la:
                        pass
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "lock-order"]

    def test_unguarded_write_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.count = 0

                def good(self):
                    with self.lock:
                        self.count += 1

                def bad(self):
                    self.count += 1
        """})
        findings, _ = lockorder.check(root)
        hits = [f for f in findings if f.rule == "unguarded-write"]
        assert len(hits) == 1 and "C.count" in hits[0].message

    def test_locked_helper_inherits_guard(self, tmp_path):
        """The _refill_locked idiom must NOT be flagged."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.count = 0

                def _bump_locked(self):
                    self.count += 1

                def good(self):
                    with self.lock:
                        self._bump_locked()

                def also_good(self):
                    with self.lock:
                        self.count = 0
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "unguarded-write"]

    def test_duplicate_class_names_do_not_merge(self, tmp_path):
        """Two classes sharing a bare name in different modules must
        stay distinct: the method-uniqueness probe must count BOTH
        `take` definitions (no resolution), never attribute one
        module's call to the other's lock."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {
            "mod_a.py": """
                import threading

                class Reader:
                    def __init__(self):
                        self.la = threading.Lock()

                    def take(self):
                        with self.la:
                            pass
            """,
            "mod_b.py": """
                import threading

                class Reader:
                    def __init__(self):
                        self.lb = threading.Lock()

                    def take(self):
                        pass

                    def caller(self, r):
                        with self.lb:
                            r.take()
            """,
        })
        findings, index = lockorder.check(root)
        assert len(index.classes_by_name["Reader"]) == 2
        assert len(index.methods_by_name["take"]) == 2
        # `r.take()` must stay UNRESOLVED (ambiguous), so no edge
        # lb -> la gets invented
        edges = lockorder.build_lock_graph(index)
        assert ("Reader.lb", "Reader.la") not in edges
        assert not [f for f in findings if f.rule == "lock-order"]

    def test_split_protocol_release_implies_held(self, tmp_path):
        """begin/commit transaction split: commit's writes are under
        the lock acquired in begin."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Tx:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.depth = 0

                def begin(self):
                    self.lock.acquire()
                    self.depth += 1

                def commit(self):
                    self.depth -= 1
                    self.lock.release()
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "unguarded-write"]


# ---------------------------------------------------------------------------
# hot-loop


class TestHotLoop:
    def test_sleep_in_dispatch_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"srv.py": """
            import time
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_GET(self):
                    self._helper()

                def _helper(self):
                    time.sleep(1)
        """})
        findings, _ = hotloop.check(root)
        assert [f.rule for f in findings] == ["hot-loop-sleep"]

    def test_urlopen_without_timeout_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"srv.py": """
            import urllib.request
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_POST(self):
                    urllib.request.urlopen("http://x/")

                def fine(self):
                    urllib.request.urlopen("http://x/", timeout=5)
        """})
        findings, _ = hotloop.check(root)
        assert [f.rule for f in findings] == ["hot-loop-no-timeout"]

    def test_off_dispatch_code_not_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"bg.py": """
            import time

            class Sweeper:
                def loop(self):
                    time.sleep(600)
        """})
        findings, _ = hotloop.check(root)
        assert not findings


# ---------------------------------------------------------------------------
# contracts tier (weedlint v2)


class TestContracts:
    def test_unserved_route_flagged_and_served_not(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"srv.py": """
            import urllib.request
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_GET(self):
                    if self.path == "/served":
                        return

            def dial_ok():
                urllib.request.urlopen(
                    "http://127.0.0.1:1/served", timeout=5
                )

            def dial_drifted():
                urllib.request.urlopen(
                    "http://127.0.0.1:1/renamed-away", timeout=5
                )
        """})
        findings, _, reg = contracts.check(root=root)
        routes = [f for f in findings if f.rule == "contract-route"]
        assert len(routes) == 1 and "/renamed-away" in routes[0].message
        assert "/served" in reg.served.get("other", {})

    def test_relative_ui_link_checked_per_module(self, tmp_path):
        """The PR-6 filer bug class: a UI href must be served by the
        SAME module's dispatch — another daemon's route must not mask
        the 404."""
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"srv.py": """
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_GET(self):
                    if self.path == "/":
                        self.fast_reply(
                            200, b'<a href="/missing-page">x</a>'
                        )
        """})
        findings, _, _reg = contracts.check(root=root)
        assert any(
            f.rule == "contract-route" and "/missing-page" in f.message
            for f in findings
        )

    def test_orphan_metric_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"metrics.py": """
            class Registry:
                def counter(self, name, help_):
                    return object()

            R = Registry()
            USED = R.counter("weed_used_total", "written elsewhere")
            DEAD = R.counter("weed_dead_total", "never touched")
        """, "writer.py": """
            from . import metrics

            def bump():
                metrics.USED.inc()
        """})
        findings, _, _reg = contracts.check(root=root)
        orphans = [
            f for f in findings if f.rule == "contract-metric-orphan"
        ]
        assert len(orphans) == 1 and "weed_dead_total" in orphans[0].message

    def test_queried_unregistered_metric_flagged(self, tmp_path):
        """The alert-wiring drift class: a ring query against a family
        no Registry registers returns empty forever."""
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"alerts.py": """
            def evaluate(ts):
                return ts.rate_sum("weed_ghost_total", 120.0)
        """})
        findings, _, _reg = contracts.check(root=root)
        assert any(
            f.rule == "contract-metric" and "weed_ghost_total" in f.message
            for f in findings
        )

    def test_header_stamped_never_parsed(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"hop.py": """
            def stamp(headers):
                headers["x-weed-ghost"] = "1"

            def stamp_and_parse(headers):
                headers["x-weed-pair"] = "1"
                return headers.get("x-weed-pair")
        """})
        findings, _, _reg = contracts.check(root=root)
        hdr = [f for f in findings if f.rule == "contract-header"]
        assert len(hdr) == 1 and "x-weed-ghost" in hdr[0].message

    def test_status_without_reason_entry(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"handler.py": """
            class H:
                def reply(self):
                    self.fast_reply(418, b"teapot")
                    self.fast_reply(200, b"ok")
        """})
        (tmp_path / "fakepkg" / "util").mkdir()
        (tmp_path / "fakepkg" / "util" / "__init__.py").write_text("")
        (tmp_path / "fakepkg" / "util" / "httpd.py").write_text(
            '_REASON = {200: b"OK"}\n'
        )
        findings, _, _reg = contracts.check(root=str(tmp_path / "fakepkg"))
        hits = [
            f for f in findings if f.rule == "contract-status-reason"
        ]
        assert len(hits) == 1 and "418" in hits[0].message

    def test_env_var_contract_both_directions(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = _write_pkg(tmp_path, {"knobs.py": """
            import os

            DOCUMENTED = os.environ.get("WEED_FIXTURE_DOCUMENTED")
            SECRET = os.environ.get("WEED_FIXTURE_SECRET")
        """})
        docs = {"OPS.md": "set `WEED_FIXTURE_DOCUMENTED` and also "
                          "`WEED_FIXTURE_GONE` (removed in v2)\n"}
        findings, _, _reg = contracts.check(root=root, docs=docs)
        envs = {f.message.split()[2]: f for f in findings
                if f.rule == "contract-env"}
        assert "WEED_FIXTURE_SECRET" in envs  # read, undocumented
        assert "WEED_FIXTURE_GONE" in envs  # documented, never read
        assert "WEED_FIXTURE_DOCUMENTED" not in envs

    def test_real_tree_registries_extracted(self):
        """The real tree's contract registries must keep seeing the
        load-bearing edges (a checker whose extraction silently decays
        to empty would pass every cross-check forever)."""
        from seaweedfs_tpu.analysis import contracts

        _findings, _idx, reg = contracts.check()
        assert "/dir/assign" in reg.served.get("master", {})
        assert "/cluster/register" in reg.served.get("master", {})
        assert "/metrics" in reg.served.get("_funnel", {})
        client_paths = {p for _k, p, _h, _s in reg.client_routes}
        assert "/dir/assign" in client_paths
        assert "/cluster/health" in client_paths  # shell command side
        assert "x-weed-trace" in reg.header_stamped
        assert "x-weed-trace" in reg.header_parsed
        assert "weed_http_request_total" in reg.metric_registered
        assert "weed_http_request_total" in reg.metric_queried
        assert "WEED_NATIVE_POST" in reg.env_read
        assert "WEED_NATIVE_POST" in reg.env_documented

    def test_extra_source_findings_are_suppressible(self):
        """Review regression: findings anchored in bench.py /
        tests/conftest.py / docs must be reachable by the suppression
        scan — check() merges those texts into index.sources so an
        inline `# weedlint: ignore[...]` there actually works."""
        from seaweedfs_tpu.analysis import contracts

        _findings, idx, _reg = contracts.check()
        assert "bench.py" in idx.sources
        assert "OPERATIONS.md" in idx.sources

    def test_dead_seed_metric_families_stay_gone(self):
        """Round-12 contract fix: the five registered-but-never-touched
        seed families must not come back to /metrics as constant-zero
        rows that look like live instrumentation."""
        from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY

        text = DEFAULT_REGISTRY.render_text()
        for dead in (
            "weed_request_total",
            "weed_request_seconds",
            "weed_volumes",
            "weed_filer_store_total",
            "weed_filer_store_seconds",
        ):
            assert dead not in text
        assert "weed_http_request_total" in text  # the real family


class TestNoDeadline:
    """The deadline-bypass rule (docs/CHAOS.md): raw urlopen() on a
    data-plane module can never inherit the request's X-Weed-Deadline
    budget — each site either migrates to http_call or states why the
    bounded one-hop timeout suffices."""

    def _scoped_pkg(self, tmp_path, rel: str, src: str) -> str:
        import textwrap

        root = tmp_path / "seaweedfs_tpu"
        target = root / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        (root / "__init__.py").write_text("")
        init = target.parent / "__init__.py"
        if not init.exists():
            init.write_text("")
        target.write_text(textwrap.dedent(src))
        return str(root)

    def test_planted_urlopen_on_data_plane_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = self._scoped_pkg(tmp_path, "server/mod.py", """
            import urllib.request

            def hop(url):
                return urllib.request.urlopen(url, timeout=10).read()
        """)
        findings, _, reg = contracts.check(root=root)
        hits = [f for f in findings if f.rule == "no-deadline"]
        assert len(hits) == 1 and hits[0].path.endswith("server/mod.py")
        assert len(reg.deadline_bypass) == 1

    def test_out_of_scope_module_not_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import contracts

        root = self._scoped_pkg(tmp_path, "telemetry/mod.py", """
            import urllib.request

            def scrape(url):
                return urllib.request.urlopen(url, timeout=5).read()
        """)
        findings, _, _reg = contracts.check(root=root)
        assert not [f for f in findings if f.rule == "no-deadline"]

    def test_suppression_with_reason_silences(self, tmp_path):
        from seaweedfs_tpu.analysis import apply_suppressions, contracts

        root = self._scoped_pkg(tmp_path, "server/mod.py", """
            import urllib.request

            def hop(url):
                # weedlint: ignore[no-deadline] — one bounded local hop
                return urllib.request.urlopen(url, timeout=10).read()
        """)
        findings, idx, _reg = contracts.check(root=root)
        kept, suppressed = apply_suppressions(findings, idx.sources)
        assert not [f for f in kept if f.rule == "no-deadline"]
        assert [f for f in suppressed if f.rule == "no-deadline"]

    def test_real_tree_deadline_header_contract_whole(self):
        """Satellite: x-weed-deadline joins the stamped-vs-parsed hop
        header registry — both sides must exist in the real tree."""
        from seaweedfs_tpu.analysis import contracts

        _findings, _idx, reg = contracts.check()
        assert "x-weed-deadline" in reg.header_stamped
        assert "x-weed-deadline" in reg.header_parsed


# ---------------------------------------------------------------------------
# lifecycle tier (weedlint v2)


class TestLifecycle:
    def _check(self, tmp_path, src: str):
        from seaweedfs_tpu.analysis import lifecycle

        root = _write_pkg(tmp_path, {"mod.py": src})
        findings, _ = lifecycle.check(root=root)
        return findings

    def test_fd_leaked_across_early_return(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def probe(p):
                fd = os.open(p, os.O_RDONLY)
                if os.fstat(fd).st_size == 0:
                    return None
                os.close(fd)
                return True
        """)
        assert [f.rule for f in findings] == ["lifecycle-fd-leak"]
        assert "returns at line" in findings[0].message

    def test_with_and_try_finally_are_clean(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def with_form(p):
                with open(p, "rb") as f:
                    return f.read()

            def finally_form(p):
                fd = os.open(p, os.O_RDONLY)
                try:
                    if os.fstat(fd).st_size == 0:
                        return None
                    return os.read(fd, 10)
                finally:
                    os.close(fd)
        """)
        assert findings == []

    def test_escapes_are_ownership_transfers(self, tmp_path):
        findings = self._check(tmp_path, """
            import os
            import socket

            class Pool:
                def __init__(self, p):
                    self.fd = os.open(p, os.O_RDONLY)  # stored: Pool owns

                def adopt(self, p):
                    fd = os.open(p, os.O_RDONLY)
                    self.fd = fd  # escapes to self

            def returned(p):
                f = open(p, "rb")
                return f  # caller owns now

            def closure(p):
                f = open(p, "rb")
                def gen():
                    with f:
                        yield f.read()
                return gen()
        """)
        assert findings == []

    def test_thread_started_never_joined(self, tmp_path):
        findings = self._check(tmp_path, """
            import threading

            def fire_and_forget(work):
                t = threading.Thread(target=work)
                t.start()

            def daemon_ok(work):
                t = threading.Thread(target=work, daemon=True)
                t.start()

            def joined_ok(work):
                t = threading.Thread(target=work)
                t.start()
                t.join()
        """)
        assert [f.rule for f in findings] == ["lifecycle-thread-leak"]
        assert "fire_and_forget" in findings[0].message

    def test_interprocedural_allocator_carries_obligation(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def _open_shard(p):
                fd = os.open(p, os.O_RDONLY)
                return fd

            def reader_leaks(p):
                fd = _open_shard(p)
                if os.fstat(fd).st_size == 0:
                    return None
                os.close(fd)
                return fd

            def closer(fd):
                os.close(fd)

            def reader_transfers(p):
                fd = _open_shard(p)
                closer(fd)
        """)
        assert [f.rule for f in findings] == ["lifecycle-fd-leak"]
        assert "reader_leaks" in findings[0].message

    def test_acquisition_args_transfer_ownership(self, tmp_path):
        """Review regression: a tracked resource fed INTO another
        acquisition call transfers ownership — os.fdopen(fd) owns fd
        (f.close() closes it) and Thread(args=(conn,)) hands the
        accepted socket to the worker."""
        findings = self._check(tmp_path, """
            import os
            import threading

            def fdopen_owns_the_fd(p):
                fd = os.open(p, os.O_RDONLY)
                f = os.fdopen(fd)
                f.close()
                return True

            def worker_owns_the_conn(listener, handle):
                conn, addr = listener.accept()
                t = threading.Thread(
                    target=handle, args=(conn,), daemon=True
                )
                t.start()
        """)
        assert findings == []

    def test_owns_annotation_transfers_ownership(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            # weedlint: owns[fd] — the C ring adopts the descriptor
            def ring_register(fd):
                _native_register(fd)

            def no_leak(p):
                fd = os.open(p, os.O_RDONLY)
                ring_register(fd)
        """)
        assert findings == []


# ---------------------------------------------------------------------------
# race: shared-state escape lint (weedlint v4)


class TestRaceLint:
    """Positive/negative matrix for `race-check-then-act`: escaped
    check-then-act caught; constructor, classmethod, confined-class,
    and continuous-hold shapes stay silent."""

    def test_escaped_check_then_act_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._primed = False

                def prime(self):
                    if not self._primed:
                        self._primed = True

            def spin(p: "Pump"):
                threading.Thread(target=p.prime).start()
        """})
        findings, _ = racelint.check(root)
        assert any(
            f.rule == "race-check-then-act" and "prime" in f.message
            for f in findings
        )
        msg = next(f.message for f in findings)
        assert "thread target" in msg  # the escape reason is named

    def test_same_lock_separate_holds_flagged(self, tmp_path):
        """The PR-9 shape: both halves take the SAME lock, but in two
        holds — held-set intersection would pass it; span tracking
        must not."""
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Gate:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._inflight = 0

                def enter(self):
                    with self._lock:
                        if self._inflight >= 4:
                            return False
                    with self._lock:
                        self._inflight += 1
                    return True

            def serve(g: "Gate"):
                threading.Thread(target=g.enter).start()
        """})
        findings, _ = racelint.check(root)
        hits = [f for f in findings if f.rule == "race-check-then-act"]
        assert hits, "torn same-lock check-then-act not flagged"
        assert "SEPARATE holds" in hits[0].message

    def test_continuous_hold_is_silent(self, tmp_path):
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Gate:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._inflight = 0

                def enter(self):
                    with self._lock:
                        if self._inflight >= 4:
                            return False
                        self._inflight += 1
                    return True

            def serve(g: "Gate"):
                threading.Thread(target=g.enter).start()
        """})
        findings, _ = racelint.check(root)
        assert not findings, findings[:2]

    def test_ctor_and_classmethod_are_silent(self, tmp_path):
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._primed = False
                    if not self._primed:
                        self._primed = True

                @classmethod
                def load(cls):
                    p = cls()
                    if not p._primed:
                        p._primed = True
                    return p

                def run(self):
                    pass

            def spin(p: "Pump"):
                threading.Thread(target=p.run).start()
        """})
        findings, _ = racelint.check(root)
        assert not findings, findings[:2]

    def test_confined_class_is_silent(self, tmp_path):
        """Same torn shape, but the instance never escapes a single
        thread — no finding (escape gate)."""
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Local:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._primed = False

                def prime(self):
                    if not self._primed:
                        self._primed = True

            def run_inline():
                p = Local()
                p.prime()
        """})
        findings, _ = racelint.check(root)
        assert not findings, findings[:2]

    def test_module_global_singleton_escapes(self, tmp_path):
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    if k not in self._items:
                        self._items[k] = v

            REGISTRY = Registry()
        """})
        findings, _ = racelint.check(root)
        assert any(
            "module-global" in f.message for f in findings
        ), findings[:2]

    def test_locked_helper_idiom_is_silent(self, tmp_path):
        """A method only ever called under the caller's hold runs
        inside one continuous hold — lockorder's guarded fixpoint
        carries over."""
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._free = []

                def _take_locked(self):
                    if self._free:
                        return self._free.pop()
                    return None

                def take(self):
                    with self._lock:
                        return self._take_locked()

            def serve(p: "Pool"):
                threading.Thread(target=p.take).start()
        """})
        findings, _ = racelint.check(root)
        assert not findings, findings[:2]

    def test_suppression_with_reason_silences(self, tmp_path):
        from seaweedfs_tpu.analysis import racelint

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._primed = False

                def prime(self):
                    if not self._primed:
                        # weedlint: ignore[race-check-then-act] — idempotent flag flip; double prime is a no-op
                        self._primed = True

            def spin(p: "Pump"):
                threading.Thread(target=p.prime).start()
        """})
        findings, index = racelint.check(root)
        kept, suppressed = apply_suppressions(findings, index.sources)
        assert suppressed and not kept


# ---------------------------------------------------------------------------
# stale-suppression audit


class TestStaleSuppressions:
    def test_stale_and_unknown_rule_ignores_become_findings(self):
        from seaweedfs_tpu.analysis import find_stale_suppressions

        sources = {
            "mod.py": (
                "x = 1  # weedlint: ignore[hot-loop-sleep] — was real once\n"
                "y = 2  # weedlint: ignore[hot-loop-lock] — rule never existed\n"
                "z = 3  # weedlint: ignore[hot-loop-sleep] — still live\n"
            )
        }
        live = [Finding("hot-loop-sleep", "mod.py", 3, "m")]
        stale = find_stale_suppressions(live, sources)
        assert sorted(f.line for f in stale) == [1, 2]
        assert all(f.rule == "stale-suppression" for f in stale)

    def test_placeholder_grammar_examples_are_skipped(self):
        from seaweedfs_tpu.analysis import find_stale_suppressions

        sources = {
            "DOC.md": "syntax: `# weedlint: ignore[rule-name] — reason`\n"
        }
        assert find_stale_suppressions([], sources) == []


# ---------------------------------------------------------------------------
# the real tree + CLI


class TestRealTree:
    def test_cli_exits_zero_on_tree(self):
        # --stale-suppressions runs every tier AND the ignore audit in
        # one subprocess: exit 0 proves the tree is finding-free and no
        # suppression has outlived its bug
        proc = subprocess.run(
            [
                sys.executable, "-m", "seaweedfs_tpu.analysis",
                "--stale-suppressions",
            ],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_contracts_and_lifecycle_rules_selectable(self):
        """The acceptance-gate invocation: `--rules contracts,lifecycle`
        must run exactly the new tiers and exit clean on this tree."""
        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "contracts,lifecycle"]) == 0

    def test_race_rules_selectable_and_clean(self):
        """weedlint v4 acceptance gate: `--rules race` runs the
        shared-state escape lint alone and exits clean on this tree —
        the true positives it found (double-spawn start() in scrub
        engine/repair/tier scheduler, the tier-move cap recheck) are
        fixed, and every deliberate pattern carries a reasoned
        suppression."""
        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "race"]) == 0

    def test_crash_rules_selectable_and_clean(self, capsys):
        """weedlint v3 acceptance gate: `--rules crash` runs the
        durability-order tier alone and exits clean on this tree (the
        true positives it found — the commit_compact swap, the scrub
        state publish, the quarantine rename — are fixed, not
        suppressed). `c` must still select only the C tier."""
        import json as _json

        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "crash"]) == 0
        out = capsys.readouterr().out
        assert "suppressed" in out  # the fuzz/_build crash ignores ran
        # family-matcher boundary: "c" and "crash" never cross-select
        assert main(["--rules", "c", "--json"]) == 0
        assert "contracts" not in _json.loads(capsys.readouterr().out)

    def test_c_and_contracts_families_do_not_cross_select(self, capsys):
        """Review regression: `--rules c` must run ONLY the C tier —
        "contracts".startswith("c") used to drag the whole contract
        tier (and its package walk) into a C-only run, and vice
        versa. The --json registry dump is the observable: present
        exactly when the contracts tier ran."""
        import json as _json

        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "c", "--json"]) == 0
        assert "contracts" not in _json.loads(capsys.readouterr().out)
        assert main(["--rules", "contracts", "--json"]) == 0
        assert "contracts" in _json.loads(capsys.readouterr().out)

    def test_ctier_failure_message_has_no_nameerror(self, monkeypatch):
        """Regression: ctier's compile-failure message referenced an
        undefined `mode` — reachable exactly when a shim FAILS to
        compile, i.e. when the diagnostics matter. Force the failure
        path and assert it formats."""
        from seaweedfs_tpu.analysis import ctier

        monkeypatch.setattr(
            ctier, "_UNITS", (("does_not_exist.c", False),)
        )
        findings = ctier.check_warnings()
        if findings:  # toolchain present: the path must format cleanly
            assert findings[0].rule == "c-warnings"

    def test_full_rule_name_selects_its_family(self, capsys):
        """`--rules hot-loop-no-timeout` must run the hot-loop family
        (regression: the old prefix test selected NOTHING and false-
        greened), and an unknown rule must be an argparse error."""
        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "hot-loop-no-timeout"]) == 0
        out = capsys.readouterr().out
        assert "suppressed" in out  # the hot-loop suppressions ran
        with pytest.raises(SystemExit) as exc:
            main(["--rules", "no-such-rule"])
        assert exc.value.code == 2

    def test_gil_release_check_passes(self):
        from seaweedfs_tpu.analysis import ctier

        assert ctier.check_gil_release() == []


# ---------------------------------------------------------------------------
# dynamic witness


class TestWitness:
    def test_inversion_detected_and_clean_order_passes(self):
        """Two locks taken A→B on one thread and B→A on another must
        produce exactly one inversion; consistent order produces none.
        Runs against the installed witness when tier-1 has it on,
        else installs locally."""
        from seaweedfs_tpu.analysis import witness

        installed_here = not witness._installed
        if installed_here:
            witness.install()
        try:
            la = threading.Lock()
            lb = threading.Lock()
            if not isinstance(la, witness._WitnessLock):
                pytest.skip("witness not active (WEED_LOCK_WITNESS=0)")
            before = len(witness.inversions())
            with la:
                with lb:
                    pass
            assert len(witness.inversions()) == before  # consistent

            def invert():
                with lb:
                    with la:
                        pass

            t = threading.Thread(target=invert)
            t.start()
            t.join()
            found = witness.inversions()[before:]
            assert len(found) == 1
            assert "test_weedlint.py" in found[0]["acquiring"]
            # consume the planted inversion so the autouse tier-1
            # witness fixture doesn't fail THIS test for it
            with witness._state_lock:
                del witness._inversions[before:]
            # and unwind the planted edges so later tests that take
            # these site-locks in either order stay clean
            with witness._state_lock:
                for k in list(witness._edges):
                    if "test_weedlint.py" in k:
                        del witness._edges[k]
        finally:
            if installed_here:
                witness.uninstall()

    def test_condition_keeps_held_stack_honest(self):
        from seaweedfs_tpu.analysis import witness

        installed_here = not witness._installed
        if installed_here:
            witness.install()
        try:
            lk = threading.Lock()
            if not isinstance(lk, witness._WitnessLock):
                pytest.skip("witness not active (WEED_LOCK_WITNESS=0)")
            cond = threading.Condition(lk)
            hits = []

            def waiter():
                with cond:
                    cond.wait(timeout=5)
                    hits.append(len(witness._held()))

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cond:
                cond.notify()
            t.join()
            # inside the with after wakeup exactly the cv lock is held
            assert hits == [1]
            assert not witness._held()  # this thread released cleanly
        finally:
            if installed_here:
                witness.uninstall()
