"""weedlint self-tests: the analysis plane must catch what it claims.

A checker that silently goes blind is worse than no checker — every
rule here gets a positive control (a synthetic tree with a planted
bug the rule MUST flag) and the real tree gets the negative control
(`python -m seaweedfs_tpu.analysis` exits 0, which is also the
acceptance gate bench.py --check drives).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
import threading
import time

import pytest

from seaweedfs_tpu.analysis import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)


def _write_pkg(tmp_path, files: dict[str, str]) -> str:
    root = tmp_path / "fakepkg"
    root.mkdir()
    (root / "__init__.py").write_text("")
    for name, src in files.items():
        (root / name).write_text(textwrap.dedent(src))
    return str(root)


# ---------------------------------------------------------------------------
# suppression policy


class TestSuppressions:
    def test_reason_required(self):
        sup = scan_suppressions(
            "x = 1  # weedlint: ignore[hot-loop-sleep]\n"
            "y = 2  # weedlint: ignore[lock-order] — held across tx\n"
        )
        assert sup.bare == [(1, "hot-loop-sleep")]
        assert "lock-order" in sup.by_line[2]

    def test_bare_ignore_becomes_finding(self):
        kept, _ = apply_suppressions(
            [], {"mod.py": "a = 1  # weedlint: ignore[x]\n"}
        )
        assert [f.rule for f in kept] == ["bare-ignore"]

    def test_comment_above_silences_next_line(self):
        findings = [Finding("hot-loop-sleep", "mod.py", 2, "m")]
        kept, suppressed = apply_suppressions(
            findings,
            {"mod.py": "# weedlint: ignore[hot-loop-sleep] — bounded\n"
                       "time.sleep(1)\n"},
        )
        assert not kept and len(suppressed) == 1

    def test_inline_ignore_does_not_bleed_to_next_line(self):
        """An inline ignore must not silence an adjacent unannotated
        finding on the following line."""
        findings = [
            Finding("hot-loop-sleep", "mod.py", 1, "annotated"),
            Finding("hot-loop-sleep", "mod.py", 2, "NOT annotated"),
        ]
        kept, suppressed = apply_suppressions(
            findings,
            {"mod.py": "time.sleep(a)  # weedlint: ignore[hot-loop-sleep] — bounded\n"
                       "time.sleep(b)\n"},
        )
        assert len(suppressed) == 1 and suppressed[0].line == 1
        assert len(kept) == 1 and kept[0].line == 2


# ---------------------------------------------------------------------------
# static lock-order


class TestLockOrder:
    def test_cycle_detected(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def ab(self):
                    with self.la:
                        with self.lb:
                            pass

                def ba(self):
                    with self.lb:
                        with self.la:
                            pass
        """})
        findings, _ = lockorder.check(root)
        assert any(f.rule == "lock-order" for f in findings)
        msg = next(f for f in findings if f.rule == "lock-order").message
        assert "A.la" in msg and "A.lb" in msg

    def test_interprocedural_cycle_via_method_call(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def helper(self):
                    with self.lb:
                        pass

                def ab(self):
                    with self.la:
                        self.helper()

                def ba(self):
                    with self.lb:
                        with self.la:
                            pass
        """})
        findings, _ = lockorder.check(root)
        assert any(f.rule == "lock-order" for f in findings)

    def test_callback_param_edge(self, tmp_path):
        """The precheck-callback idiom: locks a callback takes are
        ordered after locks the callee holds at its param() call."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Vol:
                def __init__(self):
                    self.vlock = threading.Lock()

                def write(self, precheck=None):
                    with self.vlock:
                        if precheck is not None and not precheck():
                            raise RuntimeError()

            class Worker:
                def __init__(self):
                    self.rlock = threading.Lock()
                    self.v = None

                def handle(self, v: Vol):
                    def still_owned():
                        with self.rlock:
                            return True
                    v.write(precheck=still_owned)

                def inverted(self, v: Vol):
                    with self.rlock:
                        with v.vlock:
                            pass
        """})
        findings, index = lockorder.check(root)
        edges = lockorder.build_lock_graph(index)
        assert ("Vol.vlock", "Worker.rlock") in edges
        assert any(f.rule == "lock-order" for f in findings)

    def test_sequential_not_a_cycle(self, tmp_path):
        """The _shard_release shape: take-release then take the other
        — no nesting, no edge, no finding."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class A:
                def __init__(self):
                    self.la = threading.Lock()
                    self.lb = threading.Lock()

                def ab(self):
                    with self.la:
                        with self.lb:
                            pass

                def sequential(self):
                    with self.lb:
                        x = 1
                    with self.la:
                        pass
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "lock-order"]

    def test_unguarded_write_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.count = 0

                def good(self):
                    with self.lock:
                        self.count += 1

                def bad(self):
                    self.count += 1
        """})
        findings, _ = lockorder.check(root)
        hits = [f for f in findings if f.rule == "unguarded-write"]
        assert len(hits) == 1 and "C.count" in hits[0].message

    def test_locked_helper_inherits_guard(self, tmp_path):
        """The _refill_locked idiom must NOT be flagged."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.count = 0

                def _bump_locked(self):
                    self.count += 1

                def good(self):
                    with self.lock:
                        self._bump_locked()

                def also_good(self):
                    with self.lock:
                        self.count = 0
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "unguarded-write"]

    def test_duplicate_class_names_do_not_merge(self, tmp_path):
        """Two classes sharing a bare name in different modules must
        stay distinct: the method-uniqueness probe must count BOTH
        `take` definitions (no resolution), never attribute one
        module's call to the other's lock."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {
            "mod_a.py": """
                import threading

                class Reader:
                    def __init__(self):
                        self.la = threading.Lock()

                    def take(self):
                        with self.la:
                            pass
            """,
            "mod_b.py": """
                import threading

                class Reader:
                    def __init__(self):
                        self.lb = threading.Lock()

                    def take(self):
                        pass

                    def caller(self, r):
                        with self.lb:
                            r.take()
            """,
        })
        findings, index = lockorder.check(root)
        assert len(index.classes_by_name["Reader"]) == 2
        assert len(index.methods_by_name["take"]) == 2
        # `r.take()` must stay UNRESOLVED (ambiguous), so no edge
        # lb -> la gets invented
        edges = lockorder.build_lock_graph(index)
        assert ("Reader.lb", "Reader.la") not in edges
        assert not [f for f in findings if f.rule == "lock-order"]

    def test_split_protocol_release_implies_held(self, tmp_path):
        """begin/commit transaction split: commit's writes are under
        the lock acquired in begin."""
        from seaweedfs_tpu.analysis import lockorder

        root = _write_pkg(tmp_path, {"mod.py": """
            import threading

            class Tx:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.depth = 0

                def begin(self):
                    self.lock.acquire()
                    self.depth += 1

                def commit(self):
                    self.depth -= 1
                    self.lock.release()
        """})
        findings, _ = lockorder.check(root)
        assert not [f for f in findings if f.rule == "unguarded-write"]


# ---------------------------------------------------------------------------
# hot-loop


class TestHotLoop:
    def test_sleep_in_dispatch_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"srv.py": """
            import time
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_GET(self):
                    self._helper()

                def _helper(self):
                    time.sleep(1)
        """})
        findings, _ = hotloop.check(root)
        assert [f.rule for f in findings] == ["hot-loop-sleep"]

    def test_urlopen_without_timeout_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"srv.py": """
            import urllib.request
            from seaweedfs_tpu.util.httpd import FastHandler

            class H(FastHandler):
                def do_POST(self):
                    urllib.request.urlopen("http://x/")

                def fine(self):
                    urllib.request.urlopen("http://x/", timeout=5)
        """})
        findings, _ = hotloop.check(root)
        assert [f.rule for f in findings] == ["hot-loop-no-timeout"]

    def test_off_dispatch_code_not_flagged(self, tmp_path):
        from seaweedfs_tpu.analysis import hotloop

        root = _write_pkg(tmp_path, {"bg.py": """
            import time

            class Sweeper:
                def loop(self):
                    time.sleep(600)
        """})
        findings, _ = hotloop.check(root)
        assert not findings


# ---------------------------------------------------------------------------
# the real tree + CLI


class TestRealTree:
    def test_cli_exits_zero_on_tree(self):
        proc = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu.analysis"],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_full_rule_name_selects_its_family(self, capsys):
        """`--rules hot-loop-no-timeout` must run the hot-loop family
        (regression: the old prefix test selected NOTHING and false-
        greened), and an unknown rule must be an argparse error."""
        from seaweedfs_tpu.analysis.__main__ import main

        assert main(["--rules", "hot-loop-no-timeout"]) == 0
        out = capsys.readouterr().out
        assert "suppressed" in out  # the hot-loop suppressions ran
        with pytest.raises(SystemExit) as exc:
            main(["--rules", "no-such-rule"])
        assert exc.value.code == 2

    def test_gil_release_check_passes(self):
        from seaweedfs_tpu.analysis import ctier

        assert ctier.check_gil_release() == []


# ---------------------------------------------------------------------------
# dynamic witness


class TestWitness:
    def test_inversion_detected_and_clean_order_passes(self):
        """Two locks taken A→B on one thread and B→A on another must
        produce exactly one inversion; consistent order produces none.
        Runs against the installed witness when tier-1 has it on,
        else installs locally."""
        from seaweedfs_tpu.analysis import witness

        installed_here = not witness._installed
        if installed_here:
            witness.install()
        try:
            la = threading.Lock()
            lb = threading.Lock()
            if not isinstance(la, witness._WitnessLock):
                pytest.skip("witness not active (WEED_LOCK_WITNESS=0)")
            before = len(witness.inversions())
            with la:
                with lb:
                    pass
            assert len(witness.inversions()) == before  # consistent

            def invert():
                with lb:
                    with la:
                        pass

            t = threading.Thread(target=invert)
            t.start()
            t.join()
            found = witness.inversions()[before:]
            assert len(found) == 1
            assert "test_weedlint.py" in found[0]["acquiring"]
            # consume the planted inversion so the autouse tier-1
            # witness fixture doesn't fail THIS test for it
            with witness._state_lock:
                del witness._inversions[before:]
            # and unwind the planted edges so later tests that take
            # these site-locks in either order stay clean
            with witness._state_lock:
                for k in list(witness._edges):
                    if "test_weedlint.py" in k:
                        del witness._edges[k]
        finally:
            if installed_here:
                witness.uninstall()

    def test_condition_keeps_held_stack_honest(self):
        from seaweedfs_tpu.analysis import witness

        installed_here = not witness._installed
        if installed_here:
            witness.install()
        try:
            lk = threading.Lock()
            if not isinstance(lk, witness._WitnessLock):
                pytest.skip("witness not active (WEED_LOCK_WITNESS=0)")
            cond = threading.Condition(lk)
            hits = []

            def waiter():
                with cond:
                    cond.wait(timeout=5)
                    hits.append(len(witness._held()))

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cond:
                cond.notify()
            t.join()
            # inside the with after wakeup exactly the cv lock is held
            assert hits == [1]
            assert not witness._held()  # this thread released cleanly
        finally:
            if installed_here:
                witness.uninstall()
