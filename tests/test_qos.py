"""QoS / tail-latency plane tests (docs/QOS.md).

Covers the four defenses end to end: hedged reads (adaptive delay,
loser cancellation, counters), per-client admission control (503 +
Retry-After, client retry honor, -serveProcs budget split), group
commit (byte identity, flush reduction, crash consistency), and
queue-depth-aware assignment (heartbeat fields → p2c pick), plus the
vid_map circuit breaker and the weedload extensions that drive the
BENCH_r09 A/Bs.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import tempfile
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu import qos
from seaweedfs_tpu.client import vid_map as vm
from seaweedfs_tpu.qos import hedge
from seaweedfs_tpu.qos.admission import AdmissionController, client_key
from seaweedfs_tpu.qos.group_commit import GroupCommitter
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import CookieMismatch, Volume

from tests.faults import SlowReplicaProxy


# ----------------------------------------------------------------------
# helpers


class _StubServer:
    """Minimal HTTP/1.1 blob server for hedge tests: serves a fixed
    body, optionally after a delay; records request headers and whether
    each response write completed (the loser-cancellation probe)."""

    def __init__(
        self,
        body: bytes = b"stub-body",
        delay_s: float = 0.0,
        split_response: bool = False,
    ):
        self.body = body
        self.delay_s = delay_s
        # split_response: head first, then body after a pause — the
        # only way a test can OBSERVE a client-side cancel, since one
        # small sendall to a freshly-closed socket still lands in the
        # kernel buffer without error
        self.split_response = split_response
        self.requests: list[dict] = []
        self.completed_writes = 0
        self.broken_writes = 0
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self._stop = threading.Event()
        threading.Thread(target=self._loop, daemon=True).start()

    @property
    def addr(self) -> str:
        return "127.0.0.1:%d" % self._sock.getsockname()[1]

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn) -> None:
        try:
            buf = b""
            while b"\r\n\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
            head = buf.split(b"\r\n\r\n", 1)[0].decode("latin-1")
            headers = {}
            for line in head.split("\r\n")[1:]:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
            self.requests.append(headers)
            if self.delay_s:
                time.sleep(self.delay_s)
            head = (
                b"HTTP/1.1 200 OK\r\nContent-Length: %d\r\n"
                b"Connection: close\r\n\r\n" % len(self.body)
            )
            try:
                if self.split_response:
                    conn.sendall(head)
                    time.sleep(0.3)
                    conn.sendall(self.body)
                else:
                    conn.sendall(head + self.body)
                self.completed_writes += 1
            except OSError:
                self.broken_writes += 1
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def _mk_needle(i: int, payload: bytes = b"", cookie: int = 0x1234) -> Needle:
    n = Needle(cookie=cookie, id=1000 + i, data=payload or b"qos-%d" % i * 10)
    n.set_has_last_modified_date()
    n.last_modified = 1700000000
    return n


@pytest.fixture(autouse=True)
def _fresh_breaker():
    vm._broken_until.clear()
    yield
    vm._broken_until.clear()


# ----------------------------------------------------------------------
# hedged reads


class TestHedge:
    def test_slow_primary_hedge_wins_and_loser_cancelled(self):
        """The headline behavior: primary stalls, the hedge fires to
        the second replica, wins, and the slow attempt's connection is
        torn down (no duplicate body consumed); counters agree."""
        slow = _StubServer(body=b"A" * 64, delay_s=2.0, split_response=True)
        fast = _StubServer(body=b"A" * 64, delay_s=0.0)
        stats: dict = {}
        try:
            os.environ["WEED_QOS_HEDGE_MS"] = "30"
            data, _ = hedge.download(
                [f"{slow.addr}/1,00000001", f"{fast.addr}/1,00000001"],
                key="t1", stats=stats,
            )
        finally:
            os.environ.pop("WEED_QOS_HEDGE_MS", None)
        assert data == b"A" * 64
        assert stats.get("fired") == 1
        assert stats.get("won") == 1
        assert stats.get("cancelled") == 1
        # the hedged attempt carried the hop header; the primary didn't
        assert any(qos.HEDGE_HEADER in h for h in fast.requests)
        assert all(qos.HEDGE_HEADER not in h for h in slow.requests)
        # exactly ONE body was consumed by the driver; the slow server's
        # split write lands on a closed socket (give its delayed reply
        # time: 2s stall + 0.3s split pause)
        time.sleep(2.6)
        assert slow.broken_writes == 1, (
            f"loser not cancelled: completed={slow.completed_writes}"
        )
        slow.stop()
        fast.stop()

    def test_fast_primary_no_hedge(self):
        fast = _StubServer(body=b"B" * 16)
        backup = _StubServer(body=b"B" * 16)
        stats: dict = {}
        try:
            data, _ = hedge.download(
                [f"{fast.addr}/2,00000002", f"{backup.addr}/2,00000002"],
                key="t2", stats=stats,
            )
            assert data == b"B" * 16
            assert stats.get("fired", 0) == 0
            assert backup.requests == []
        finally:
            fast.stop()
            backup.stop()

    def test_attempt_pool_reuses_threads(self):
        """ROADMAP tail-latency follow-on: hedged-capable GETs ride a
        reusable attempt-worker pool instead of spawning 1-2 fresh
        threads each. After a warm-up, a burst of reads must not grow
        the pool's lifetime thread count (reuse) nor the process's live
        thread count beyond the parked-worker cap (no leak)."""
        fast = _StubServer(body=b"P" * 32)
        backup = _StubServer(body=b"P" * 32)
        try:
            urls = [f"{fast.addr}/9,00000009", f"{backup.addr}/9,00000009"]
            for _ in range(4):  # warm the pool
                hedge.download(urls, key="pool-warm")
            spawned_before = hedge._ATTEMPTS.spawned
            live_before = threading.active_count()
            for _ in range(30):
                data, _ = hedge.download(urls, key="pool-test")
                assert data == b"P" * 32
            assert hedge._ATTEMPTS.spawned - spawned_before <= 2, (
                "attempt pool is not reusing workers: "
                f"{hedge._ATTEMPTS.spawned - spawned_before} fresh "
                "threads for 30 sequential reads"
            )
            # live threads: at most the parked-worker cap over baseline
            # (stub servers spawn-and-exit per connection; give the
            # tail a moment to drain)
            time.sleep(0.2)
            assert threading.active_count() <= live_before + \
                hedge._AttemptPool._MAX_IDLE
        finally:
            fast.stop()
            backup.stop()

    def test_primary_connect_failure_fails_over(self):
        """A dead primary shouldn't wait out the delay-then-timeout
        dance: the failure reroutes to the replica immediately and the
        breaker demotes the dead node."""
        fast = _StubServer(body=b"C" * 16)
        dead_port = socket.socket()
        dead_port.bind(("127.0.0.1", 0))
        dead = "127.0.0.1:%d" % dead_port.getsockname()[1]
        dead_port.close()  # nothing listens here now
        try:
            data, _ = hedge.download(
                [f"{dead}/3,00000003", f"{fast.addr}/3,00000003"], key="t3"
            )
            assert data == b"C" * 16
            assert vm.penalized(dead)
        finally:
            fast.stop()

    def test_kill_switch_restores_single_attempt(self, monkeypatch):
        fast = _StubServer(body=b"D" * 16)
        backup = _StubServer(body=b"D" * 16)
        monkeypatch.setenv("WEED_QOS", "0")
        try:
            data, _ = hedge.download(
                [f"{fast.addr}/4,00000004", f"{backup.addr}/4,00000004"],
                key="t4",
            )
            assert data == b"D" * 16
            assert backup.requests == []  # never contacted
        finally:
            fast.stop()
            backup.stop()

    def test_adaptive_delay_tracks_quantile(self):
        tr = hedge.LatencyTracker()
        key = "vol9"
        # before history: the configured initial delay
        assert tr.delay_s(key) == pytest.approx(0.025, abs=1e-3)
        for _ in range(64):
            tr.record(key, 0.004)
        d = tr.delay_s(key)
        assert 0.003 <= d <= 0.006  # hugs the volume's own p95

    def test_slow_replica_proxy_delays_responses(self):
        srv = _StubServer(body=b"E" * 32)
        proxy = SlowReplicaProxy(srv.addr, delay_s=0.15)
        try:
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                f"http://{proxy.addr}/5,00000005", timeout=5
            ) as r:
                body = r.read()
            assert body == b"E" * 32
            assert time.perf_counter() - t0 >= 0.14
            assert proxy.responses_delayed >= 1
        finally:
            proxy.stop()
            srv.stop()


# ----------------------------------------------------------------------
# vid_map circuit breaker


class TestBreaker:
    def test_lookup_demotes_failed_replica_until_ttl(self):
        m = vm.VidMap()
        m.add_location(7, vm.Location("h1:80", "h1:80"))
        m.add_location(7, vm.Location("h2:80", "h2:80"))
        vm.note_failure("h1:80", now=time.time())
        for _ in range(4):  # every rotation, not just alternate ones
            urls = m.lookup_file_id("7,00000007")
            assert urls[0] == "http://h2:80/7,00000007"
        # TTL expiry restores rotation
        vm._broken_until["h1:80"] = time.time() - 0.01
        firsts = {m.lookup_file_id("7,00000007")[0] for _ in range(4)}
        assert len(firsts) == 2

    def test_all_penalized_keeps_original_order(self):
        vm.note_failure("a:1")
        vm.note_failure("b:1")
        urls = vm.order_by_health(["a:1/9,x", "b:1/9,x"])
        assert urls == ["a:1/9,x", "b:1/9,x"]

    def test_success_clears_penalty(self):
        vm.note_failure("c:1")
        assert vm.penalized("c:1")
        vm.note_success("c:1")
        assert not vm.penalized("c:1")


# ----------------------------------------------------------------------
# admission control


class _FakeHandler:
    def __init__(self, headers=None, addr=("10.0.0.9", 1234)):
        from seaweedfs_tpu.util.httpd import FastHeaders

        self.headers = FastHeaders()
        for k, v in (headers or {}).items():
            self.headers[k.lower()] = v
        self.client_address = addr
        self.replies = []
        self.close_connection = False
        self.command = "GET"
        self._trace_status = 0

    def fast_reply(self, status, body=b"", headers=None):
        self._trace_status = status
        self.replies.append((status, body, headers))


class TestAdmission:
    def test_client_key_prefers_s3_access_key(self):
        h = _FakeHandler({
            "Authorization":
                "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20130524/us-east-1/"
                "s3/aws4_request, SignedHeaders=host, Signature=abc"
        })
        assert client_key(h) == "AKIDEXAMPLE"
        h2 = _FakeHandler({"Authorization": "AWS AKLEGACY:sig=="})
        assert client_key(h2) == "AKLEGACY"
        h3 = _FakeHandler()
        assert client_key(h3) == "10.0.0.9"

    def test_token_bucket_sheds_with_retry_after(self):
        ctrl = AdmissionController(rate=2.0, burst=2.0, label="t")
        now = 1000.0
        assert ctrl.admit("k", now) is None
        assert ctrl.admit("k", now) is None
        retry = ctrl.admit("k", now)
        assert retry is not None and retry > 0
        # refill: half a second restores one token
        assert ctrl.admit("k", now + 0.5) is None
        # other clients unaffected
        assert ctrl.admit("other", now) is None

    def test_serveprocs_divides_budget(self):
        """Satellite: admission keyed correctly behind -serveProcs —
        each sibling process enforces 1/N of the global budget so the
        group total stays what the operator configured."""
        whole = AdmissionController(rate=8.0, burst=8.0, procs=1)
        quarter = AdmissionController(rate=8.0, burst=8.0, procs=4)
        assert quarter.rate == pytest.approx(whole.rate / 4)
        assert quarter.burst == pytest.approx(whole.burst / 4)
        now = 0.0
        admitted = sum(
            1 for _ in range(8) if quarter.admit("k", now) is None
        )
        assert admitted == 2  # 8 burst / 4 procs

    def test_inflight_cap_sheds_any_client(self):
        ctrl = AdmissionController(rate=0.0, max_inflight=1, label="t")
        h = _FakeHandler()
        entered = threading.Event()
        release = threading.Event()

        def slow_method(handler):
            entered.set()
            release.wait(5)

        t = threading.Thread(target=ctrl.gate, args=(slow_method, h))
        t.start()
        assert entered.wait(5)
        h2 = _FakeHandler()
        ctrl.gate(lambda _h: None, h2)
        release.set()
        t.join(5)
        assert h2.replies and h2.replies[0][0] == 503
        assert h2.replies[0][2]["Retry-After"]
        # capacity restored after the slow request drained
        h3 = _FakeHandler()
        ctrl.gate(lambda _h: None, h3)
        assert not h3.replies

    def test_inflight_cap_atomic_under_burst(self):
        """Regression (review): the cap check and the in-flight
        increment must share one lock hold — a simultaneous burst of N
        threads must never see more than max_inflight in service."""
        ctrl = AdmissionController(rate=0.0, max_inflight=2, label="t")
        live = []
        peak = []
        lock = threading.Lock()
        release = threading.Event()
        barrier = threading.Barrier(12)

        def method(handler):
            with lock:
                live.append(1)
                peak.append(len(live))
            release.wait(5)
            with lock:
                live.pop()

        def run():
            barrier.wait(5)
            ctrl.gate(method, _FakeHandler())

        ts = [threading.Thread(target=run) for _ in range(12)]
        for t in ts:
            t.start()
        time.sleep(0.3)
        release.set()
        for t in ts:
            t.join(5)
        assert peak and max(peak) <= 2, f"cap breached: peak={max(peak)}"

    def test_kill_switch_admits_everything(self, monkeypatch):
        monkeypatch.setenv("WEED_QOS", "0")
        ctrl = AdmissionController(rate=0.001, burst=0.001)
        assert all(ctrl.admit("k") is None for _ in range(50))

    def test_env_flip_mid_flight_never_underflows_inflight(
        self, monkeypatch
    ):
        """Regression (review): with admission env-disabled, gate()
        must not decrement an in-flight it never incremented — the
        underflow would silently widen the cap once re-enabled."""
        ctrl = AdmissionController(rate=0.0, max_inflight=2, label="t")
        monkeypatch.setenv("WEED_QOS_ADMISSION", "0")
        for _ in range(5):
            ctrl.gate(lambda _h: None, _FakeHandler())
        assert ctrl.status()["Inflight"] == 0
        monkeypatch.delenv("WEED_QOS_ADMISSION")
        assert ctrl.inflight() == 0

    def test_http_call_honors_retry_after_with_jitter(self):
        """Satellite: a 503 + Retry-After from admission control is
        retried (with a jittered wait), not surfaced — one shed plus
        one success looks like one slow request to the caller."""
        from seaweedfs_tpu.client import operation as op

        hits = []

        class _Once:
            def __init__(self):
                self.sock = socket.socket()
                self.sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                )
                self.sock.bind(("127.0.0.1", 0))
                self.sock.listen(8)
                self.addr = "127.0.0.1:%d" % self.sock.getsockname()[1]
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                while True:
                    try:
                        conn, _ = self.sock.accept()
                    except OSError:
                        return
                    buf = b""
                    while b"\r\n\r\n" not in buf:
                        c = conn.recv(65536)
                        if not c:
                            break
                        buf += c
                    hits.append(time.perf_counter())
                    if len(hits) == 1:
                        conn.sendall(
                            b"HTTP/1.1 503 Service Unavailable\r\n"
                            b"Retry-After: 0.2\r\n"
                            b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                        )
                    else:
                        conn.sendall(
                            b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
                            b"Connection: close\r\n\r\nok"
                        )
                    conn.close()

        srv = _Once()
        try:
            status, _, body = op.http_call("GET", f"{srv.addr}/x")
            assert status == 200 and body == b"ok"
            assert len(hits) == 2
            # the jittered wait honored at least half the server's hint
            assert hits[1] - hits[0] >= 0.099
        finally:
            srv.sock.close()

    def test_http_call_passes_503_through_when_qos_off(self, monkeypatch):
        from seaweedfs_tpu.client import operation as op

        monkeypatch.setenv("WEED_QOS", "0")
        calls = []

        class _Always503:
            def __init__(self):
                self.sock = socket.socket()
                self.sock.bind(("127.0.0.1", 0))
                self.sock.listen(8)
                self.addr = "127.0.0.1:%d" % self.sock.getsockname()[1]
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                while True:
                    try:
                        conn, _ = self.sock.accept()
                    except OSError:
                        return
                    buf = b""
                    while b"\r\n\r\n" not in buf:
                        c = conn.recv(65536)
                        if not c:
                            break
                        buf += c
                    calls.append(1)
                    conn.sendall(
                        b"HTTP/1.1 503 Service Unavailable\r\n"
                        b"Retry-After: 0.1\r\nContent-Length: 0\r\n"
                        b"Connection: close\r\n\r\n"
                    )
                    conn.close()

        srv = _Always503()
        try:
            status, _, _ = op.http_call("GET", f"{srv.addr}/x")
            assert status == 503
            assert len(calls) == 1  # no retry: wholesale restore
        finally:
            srv.sock.close()


# ----------------------------------------------------------------------
# group commit


class TestGroupCommit:
    def _serial_twin(self, d, needles):
        os.mkdir(os.path.join(d, "serial"))
        v = Volume(os.path.join(d, "serial"), 1)
        for n in needles:
            v.write_needle(n)
        v.close()
        with open(v.base_name + ".dat", "rb") as f:
            return f.read()

    def test_batch_byte_identical_to_serial(self, monkeypatch):
        monkeypatch.setattr(
            Volume, "_now_ns", lambda self: self.last_append_at_ns + 1
        )
        payloads = [(b"gc-%02d\xff\x00" % i) * 37 for i in range(12)]
        with tempfile.TemporaryDirectory() as d:
            serial_dat = self._serial_twin(
                d, [_mk_needle(i, p) for i, p in enumerate(payloads)]
            )
            os.mkdir(os.path.join(d, "batch"))
            vb = Volume(os.path.join(d, "batch"), 1)
            outcomes = vb.write_needles(
                [(_mk_needle(i, p), None) for i, p in enumerate(payloads)],
                durable=True,
            )
            assert all(isinstance(o, tuple) and not o[2] for o in outcomes)
            with open(vb.base_name + ".dat", "rb") as f:
                batch_dat = f.read()
            assert batch_dat == serial_dat
            # every needle reads back through the normal path
            for i, p in enumerate(payloads):
                assert bytes(vb.read_needle(1000 + i).data) == p
            vb.close()

    def test_batch_per_needle_errors_dont_fail_batchmates(self):
        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            first = _mk_needle(0, b"original" * 10)
            v.write_needle(first)
            bad = _mk_needle(0, b"overwrite" * 10, cookie=0xBAD)  # same id
            good = _mk_needle(1, b"fine" * 10)
            outcomes = v.write_needles([(bad, None), (good, None)])
            assert isinstance(outcomes[0], CookieMismatch)
            assert isinstance(outcomes[1], tuple)
            assert bytes(v.read_needle(1001).data) == b"fine" * 10
            v.close()

    def test_same_id_in_one_batch_keeps_serial_semantics(self):
        """Regression (review): two writes for one needle id inside one
        commit window must behave like serial writes — the later one's
        checks run against the earlier BATCHMATE's committed record,
        so a mismatching cookie raises and a matching duplicate dedups
        — not against the stale pre-batch map."""
        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            first = _mk_needle(0, b"first-copy" * 12)
            bad_cookie = _mk_needle(0, b"evil-write" * 12, cookie=0xBAD)
            dup = _mk_needle(0, b"first-copy" * 12)  # same bytes+cookie
            outcomes = v.write_needles(
                [(first, None), (bad_cookie, None), (dup, None)]
            )
            assert isinstance(outcomes[0], tuple) and not outcomes[0][2]
            assert isinstance(outcomes[1], CookieMismatch)
            assert isinstance(outcomes[2], tuple) and outcomes[2][2], (
                "same-bytes duplicate should dedup as unchanged"
            )
            assert bytes(v.read_needle(1000).data) == b"first-copy" * 12
            v.close()

    def test_committer_coalesces_flushes(self):
        """Concurrent writers through one committer: flushes per POST
        drop by >= 4x versus fsync-per-POST at the same concurrency."""
        from seaweedfs_tpu.stats.metrics import COMMIT_FLUSHES

        n_writers = 16
        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            gc = GroupCommitter(window_us=20000, fsync=True)
            before = COMMIT_FLUSHES.value()
            barrier = threading.Barrier(n_writers)
            errs = []

            def w(i):
                try:
                    barrier.wait(5)
                    gc.write(v, _mk_needle(i, b"flush-%02d" % i * 20))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ts = [
                threading.Thread(target=w, args=(i,))
                for i in range(n_writers)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(10)
            assert not errs
            flushes = COMMIT_FLUSHES.value() - before
            assert flushes * 4 <= n_writers, (
                f"{flushes} flushes for {n_writers} writes"
            )
            for i in range(n_writers):
                assert v.has_needle(1000 + i)
            v.close()

    def test_committer_inactive_is_write_per_post(self, monkeypatch):
        monkeypatch.setenv("WEED_QOS_COMMIT", "0")
        from seaweedfs_tpu.stats.metrics import GROUP_COMMIT_BATCHES

        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            gc = GroupCommitter(window_us=500, fsync=False)
            before = GROUP_COMMIT_BATCHES.value()
            gc.write(v, _mk_needle(0))
            assert GROUP_COMMIT_BATCHES.value() == before  # no batching
            assert v.has_needle(1000)
            v.close()

    def test_crash_between_commit_points_replays_clean(self):
        """Satellite: kill between window commit points → no torn
        needle. A batch whose tail record hit the .dat but not the .idx
        (the crash window) must reload cleanly with every indexed
        needle intact and the torn tail invisible."""
        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            outcomes = v.write_needles(
                [(_mk_needle(i, b"crash-%d" % i * 25), None) for i in range(4)],
                durable=True,
            )
            assert all(isinstance(o, tuple) for o in outcomes)
            dat, idx = v.base_name + ".dat", v.base_name + ".idx"
            v.close()
            # simulate the crash: the last record's idx entry never made
            # it (truncate 16 bytes) and the .dat tail tore mid-record
            with open(idx, "r+b") as f:
                f.truncate(os.path.getsize(idx) - 16)
            with open(dat, "r+b") as f:
                f.truncate(os.path.getsize(dat) - 11)
            v2 = Volume(d, 1, create=False)
            for i in range(3):
                assert bytes(v2.read_needle(1000 + i).data) == (
                    b"crash-%d" % i * 25
                )
            assert not v2.has_needle(1003)
            # and the volume still accepts writes after the replay
            v2.write_needle(_mk_needle(9, b"post-crash" * 10))
            assert v2.has_needle(1009)
            v2.close()


# ----------------------------------------------------------------------
# queue-depth-aware assignment


class TestAssignment:
    def _layout(self):
        from seaweedfs_tpu.storage.store import VolumeInfo
        from seaweedfs_tpu.topology.node import DataNode
        from seaweedfs_tpu.topology.volume_layout import VolumeLayout

        layout = VolumeLayout("000", "", 1 << 30)
        nodes = []
        for i in range(2):
            dn = DataNode(f"n{i}:80", ip=f"n{i}", port=80)
            info = VolumeInfo(
                id=i + 1, size=0, collection="", file_count=0,
                delete_count=0, deleted_byte_count=0, read_only=False,
                replica_placement=0, version=3, ttl=0,
            )
            layout.register_volume(info, dn)
            nodes.append(dn)
        return layout, nodes

    def test_p2c_prefers_less_loaded_node(self):
        layout, (a, b) = self._layout()
        a.in_flight, a.write_queue_depth = 50, 10
        b.in_flight, b.write_queue_depth = 1, 0
        picks = [layout.pick_for_write(policy="p2c")[0] for _ in range(32)]
        # vid 2 lives on the idle node; p2c must always choose it when
        # both candidates are sampled (two writables → always compared)
        assert all(p == 2 for p in picks)
        # and the location list leads with the least-loaded replica
        _, locs = layout.pick_for_write(policy="p2c")
        assert locs[0] is b

    def test_random_policy_stays_blind(self):
        layout, (a, b) = self._layout()
        a.in_flight = 10_000
        picks = {
            layout.pick_for_write(policy="random")[0] for _ in range(64)
        }
        assert picks == {1, 2}  # load-blind by contract

    def test_heartbeat_fields_roundtrip(self):
        from seaweedfs_tpu.pb import master_pb2

        req = master_pb2.HeartbeatRequest(
            ip="h", port=1, in_flight_requests=11, write_queue_depth=4
        )
        out = master_pb2.HeartbeatRequest()
        out.ParseFromString(req.SerializeToString())
        assert out.in_flight_requests == 11
        assert out.write_queue_depth == 4

    def test_qos_off_forces_random(self, monkeypatch):
        """WEED_QOS=0 wholesale-restore: the master's assign path must
        pass policy=random even with -assignPolicy p2c."""
        monkeypatch.setenv("WEED_QOS", "0")
        captured = {}

        from seaweedfs_tpu.server.master_server import MasterServer

        ms = MasterServer.__new__(MasterServer)
        ms.assign_policy = "p2c"
        assert (
            ms.assign_policy if qos.enabled("assign") else "random"
        ) == "random"


# ----------------------------------------------------------------------
# live-cluster integration: heartbeat load → master, hedge spans,
# admission through a real server


class TestQosCluster:
    def test_load_reaches_master_and_cluster_top(self):
        from seaweedfs_tpu.telemetry import ClusterCollector
        from seaweedfs_tpu.util.availability import start_cluster

        with tempfile.TemporaryDirectory() as d:
            master, servers = start_cluster(
                [tempfile.mkdtemp(dir=d)],
                master_kwargs={"telemetry_interval": 0.5},
            )
            vs = servers[0]
            try:
                # fake live load, then force a beat and wait for ingest
                for _ in range(5):
                    vs.load.enter()
                vs._hb_wake.set()
                deadline = time.time() + 10
                dn = master.topology.data_nodes()[0]
                while time.time() < deadline and dn.in_flight != 5:
                    time.sleep(0.05)
                assert dn.in_flight == 5
                assert dn.queue_load() == 5
                # /cluster/top surfaces the columns
                collector = ClusterCollector(master, interval=0.5)
                master.telemetry = collector
                collector.collect_once()
                top = collector.top_payload(5)
                vol_rows = [
                    r for r in top["Nodes"] if r["Kind"] == "volume"
                ]
                assert vol_rows and vol_rows[0]["InFlight"] == 5
            finally:
                for _ in range(5):
                    vs.load.exit()
                for s in servers:
                    s.stop()
                master.stop()

    def test_admission_on_live_volume_server(self):
        """End-to-end shed: a volume server with a tiny budget sheds
        with 503 + Retry-After through the real mini loop, the counter
        moves, and WEED_QOS=0 would admit (checked via controller)."""
        from seaweedfs_tpu.stats.metrics import ADMISSION_REJECTED
        from seaweedfs_tpu.util.availability import start_cluster

        with tempfile.TemporaryDirectory() as d:
            master, servers = start_cluster(
                [tempfile.mkdtemp(dir=d)],
                admission_rate=1.0,
                admission_burst=1.0,
            )
            vs = servers[0]
            addr = f"127.0.0.1:{vs.port}"
            before = ADMISSION_REJECTED.value("volume")
            try:
                statuses = []
                for _ in range(6):
                    conn = socket.create_connection(
                        ("127.0.0.1", vs.port), timeout=5
                    )
                    conn.sendall(b"GET /status HTTP/1.1\r\n\r\n")
                    buf = b""
                    while b"\r\n\r\n" not in buf:
                        c = conn.recv(65536)
                        if not c:
                            break
                        buf += c
                    statuses.append(int(buf[9:12]))
                    conn.close()
                assert 200 in statuses
                assert 503 in statuses
                assert ADMISSION_REJECTED.value("volume") > before
            finally:
                for s in servers:
                    s.stop()
                master.stop()

    def test_group_commit_on_live_write_path(self):
        """POSTs through a committer-armed volume server batch and stay
        byte-correct (read-back identical), and the C fast path stands
        down (reply still 201)."""
        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.stats.metrics import GROUP_COMMIT_WRITES
        from seaweedfs_tpu.util.availability import start_cluster

        with tempfile.TemporaryDirectory() as d:
            master, servers = start_cluster(
                [tempfile.mkdtemp(dir=d)],
                commit_window_us=2000,
                commit_fsync=True,
            )
            m = f"127.0.0.1:{master.port}"
            before = GROUP_COMMIT_WRITES.value()
            try:
                payloads = {}
                results = []

                def put(i):
                    body = (b"live-%02d\x00\xff" % i) * 64
                    ar = op.assign(m)
                    ur = op.upload(f"{ar.url}/{ar.fid}", body, jwt=ar.auth)
                    results.append(ur.error or "")
                    payloads[ar.fid] = body

                ts = [
                    threading.Thread(target=put, args=(i,)) for i in range(8)
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(15)
                assert all(e == "" for e in results), results
                assert GROUP_COMMIT_WRITES.value() - before >= 8
                for fid, body in payloads.items():
                    url = op.lookup_file_id(m, fid)
                    data, _ = op.download(url)
                    assert data == body
            finally:
                for s in servers:
                    s.stop()
                master.stop()

    def test_hedged_read_spans_visible_in_trace(self):
        """Acceptance: hedged requests carry plane=serve spans visible
        through the trace ring (the substrate of trace.dump)."""
        from seaweedfs_tpu import trace

        slow = _StubServer(body=b"T" * 32, delay_s=1.0)
        fast = _StubServer(body=b"T" * 32)
        trace.set_enabled(True)
        try:
            os.environ["WEED_QOS_HEDGE_MS"] = "30"
            with trace.span("test.client") as root:
                trace_id = root.trace_id
                hedge.download(
                    [f"{slow.addr}/8,00000008", f"{fast.addr}/8,00000008"],
                    key="t8",
                )
        finally:
            os.environ.pop("WEED_QOS_HEDGE_MS", None)
            slow.stop()
            fast.stop()
        spans = [
            s for s in trace.debug_payload(512)["recent"]
            if s["trace"] == trace_id and s["name"] == "qos.hedge"
        ]
        assert spans, "qos.hedge span missing from the ring"
        sp = spans[0]
        assert sp["plane"] == "serve"
        assert sp.get("annot", {}).get("hedged") == "1"


# ----------------------------------------------------------------------
# weedload extensions


class TestWeedloadQos:
    def test_mixed_mode_worker_alternates(self):
        """Unit-drive the worker loop in-process (no spawn): mixed mode
        must issue both PUTs and GETs against a live cluster."""
        from seaweedfs_tpu.telemetry import weedload
        from seaweedfs_tpu.util.availability import start_cluster

        with tempfile.TemporaryDirectory() as d:
            master, servers = start_cluster([tempfile.mkdtemp(dir=d)])
            m = f"127.0.0.1:{master.port}"
            try:
                payload = b"mix\x00\xff" * 40
                keys = weedload.seed_keys(m, 4, payload)
                out: queue.Queue = queue.Queue()
                weedload._worker(
                    {
                        "mode": "mixed",
                        "master": m,
                        "duration_s": 1.0,
                        "payload": payload,
                        "rate": 0.0,
                        "keys": keys,
                        "index": 0,
                        "hedge": False,
                    },
                    out,
                )
                row = out.get(timeout=5)
                assert row["mode"] == "mixed"
                assert row["errors"] == 0
                assert row["ops"] >= 4
                assert row["shed"] == 0
            finally:
                for s in servers:
                    s.stop()
                master.stop()

    def test_hedged_worker_reports_counts(self):
        from seaweedfs_tpu.telemetry import weedload

        slow = _StubServer(body=b"W" * 24, delay_s=0.5)
        fast = _StubServer(body=b"W" * 24)
        out: queue.Queue = queue.Queue()
        try:
            os.environ["WEED_QOS_HEDGE_MS"] = "20"
            weedload._worker(
                {
                    "mode": "get",
                    "master": "unused",
                    "duration_s": 1.2,
                    "payload": b"",
                    "rate": 0.0,
                    "keys": [("1,0000000a", [slow.addr, fast.addr])],
                    "index": 0,
                    "hedge": True,
                },
                out,
            )
        finally:
            os.environ.pop("WEED_QOS_HEDGE_MS", None)
            slow.stop()
            fast.stop()
        row = out.get(timeout=5)
        assert row["errors"] == 0
        assert row["ops"] >= 2
        # the primary rotated onto the slow replica at least once, so
        # hedges fired and the counts rode the row
        assert row["hedge"].get("fired", 0) >= 1
        assert row["hedge"].get("won", 0) >= 1

    def test_shed_counted_separately(self):
        from seaweedfs_tpu.telemetry import weedload

        class _Shedder(_StubServer):
            def _serve(self, conn):
                try:
                    buf = b""
                    while b"\r\n\r\n" not in buf:
                        c = conn.recv(65536)
                        if not c:
                            return
                        buf += c
                    conn.sendall(
                        b"HTTP/1.1 503 Service Unavailable\r\n"
                        b"Retry-After: 1\r\nContent-Length: 0\r\n"
                        b"Connection: close\r\n\r\n"
                    )
                finally:
                    conn.close()

        srv = _Shedder()
        out: queue.Queue = queue.Queue()
        try:
            weedload._worker(
                {
                    "mode": "get",
                    "master": "unused",
                    "duration_s": 0.4,
                    "payload": b"",
                    "rate": 0.0,
                    "keys": [("1,0000000b", srv.addr)],
                    "index": 0,
                    "hedge": False,
                },
                out,
            )
        finally:
            srv.stop()
        row = out.get(timeout=5)
        assert row["shed"] >= 1
        assert row["errors"] == 0
        assert row["ops"] == 0
