"""The ported S3 and WebDAV gateways under the mini request loop's
adversarial input matrix (the test_httpd_miniloop.py cases, re-aimed):
malformed request lines, bad Content-Length, oversized heads (431),
unknown methods (405), split reads, pipelining, keep-alive semantics,
and unread-body realignment. Both gateways now ride
util/httpd.serve_connection — no serving path in the repo is left on
the stdlib per-request machinery — so the from-scratch parser's abuse
suite must hold against them too.

The gateways point at a dead filer port: every case here either fails
in the parser (never reaching a handler) or in a handler branch that
rules before any filer access (S3 bucket-name validation, WebDAV
OPTIONS/PROPPATCH), so no test depends on backend latency.
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.s3api.s3api_server import S3ApiServer
from seaweedfs_tpu.webdav.webdav_server import WebDavServer


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


@pytest.fixture(scope="module")
def s3():
    srv = S3ApiServer(filer=f"127.0.0.1:{free_port()}", port=free_port())
    srv.start()
    time.sleep(0.05)
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def dav():
    srv = WebDavServer(filer=f"127.0.0.1:{free_port()}", port=free_port())
    srv.start()
    time.sleep(0.05)
    yield srv
    srv.stop()


def _connect(port: int):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
    return s


_leftover: dict[socket.socket, bytes] = {}


def _read_response(s) -> tuple[int, bytes]:
    """(status, body) for one Content-Length-framed response, carrying
    per-socket leftovers so pipelined responses coalesced into one
    segment do not starve the next read."""
    buf = _leftover.pop(s, b"")
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(65536)
        if not chunk:
            return 0, b""
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    status = int(head.split(None, 2)[1])
    length = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            length = int(v.strip())
    while len(rest) < length:
        chunk = s.recv(65536)
        if not chunk:
            break
        rest += chunk
    if rest[length:]:
        _leftover[s] = rest[length:]
    return status, rest[:length]


# requests that fail before any filer/backend access:
#   S3: PUT on a too-short bucket name -> 400 InvalidBucketName XML
#   WebDAV: OPTIONS -> 200, PROPPATCH -> 207 (properties not persisted)
S3_OK = b"PUT /x HTTP/1.1\r\nHost: s3\r\nContent-Length: 0\r\n\r\n"
DAV_OK = b"OPTIONS /any HTTP/1.1\r\nHost: dav\r\n\r\n"


class TestS3MiniLoop:
    def test_parse_level_reply(self, s3):
        s = _connect(s3.port)
        s.sendall(S3_OK)
        status, body = _read_response(s)
        assert status == 400 and b"InvalidBucketName" in body
        s.close()

    def test_garbage_request_line_400(self, s3):
        s = _connect(s3.port)
        s.sendall(b"NOT A REQUEST\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_bad_content_length_400(self, s3):
        s = _connect(s3.port)
        s.sendall(b"PUT /x HTTP/1.1\r\nContent-Length: abc\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_oversized_head_431(self, s3):
        s = _connect(s3.port)
        s.sendall(b"GET / HTTP/1.1\r\n")
        junk = b"X-Filler: " + b"a" * 8000 + b"\r\n"
        try:
            for _ in range(40):  # ~320 KB of headers > the 128 KB cap
                s.sendall(junk)
            s.sendall(b"\r\n")
        except (BrokenPipeError, ConnectionResetError):
            return  # server already slammed the door: acceptable
        status, _ = _read_response(s)
        assert status in (0, 431)
        s.close()

    def test_unknown_method_405(self, s3):
        s = _connect(s3.port)
        s.sendall(b"BREW / HTTP/1.1\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 405
        s.close()

    def test_partial_head_across_packets(self, s3):
        s = _connect(s3.port)
        for piece in (b"PUT /", b"x HT", b"TP/1.1\r\nHost: s3\r\nConte",
                      b"nt-Length: 0\r", b"\n\r\n"):
            s.sendall(piece)
            time.sleep(0.02)
        status, body = _read_response(s)
        assert status == 400 and b"InvalidBucketName" in body
        s.close()

    def test_pipelined_requests_two_responses(self, s3):
        s = _connect(s3.port)
        s.sendall(S3_OK + S3_OK)
        st1, b1 = _read_response(s)
        st2, b2 = _read_response(s)
        assert st1 == st2 == 400 and b1 == b2
        s.close()

    def test_keep_alive_many_requests_one_connection(self, s3):
        s = _connect(s3.port)
        for _ in range(10):
            s.sendall(S3_OK)
            status, body = _read_response(s)
            assert status == 400 and b"InvalidBucketName" in body
        s.close()

    def test_http10_defaults_to_close(self, s3):
        s = _connect(s3.port)
        s.sendall(b"PUT /x HTTP/1.0\r\nContent-Length: 0\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.settimeout(5)
        assert s.recv(64) == b""
        s.close()

    def test_unread_body_does_not_desync(self, s3):
        """An S3 reply to a request whose body the handler read only
        partially (or not at all — a PUT the router 400s before
        draining): the loop must realign, and the next pipelined
        request on the same connection must parse cleanly."""
        body = b"B" * 512
        s = _connect(s3.port)
        s.sendall(
            b"BREW /x HTTP/1.1\r\nHost: s3\r\n"
            + b"Content-Length: %d\r\n\r\n" % len(body)
        )
        status, _ = _read_response(s)
        assert status == 405  # unknown method replies before the body
        s.close()
        # unread-but-small body on a keep-alive connection: DELETE
        # carries a body the handler never reads
        s = _connect(s3.port)
        s.sendall(
            b"PUT /x HTTP/1.1\r\nHost: s3\r\n"
            + b"Content-Length: %d\r\n\r\n" % len(body)
        )
        # handler reads the body itself; still send it, then pipeline
        s.sendall(body)
        status, b1 = _read_response(s)
        assert status == 400
        s.sendall(S3_OK)
        status, b2 = _read_response(s)
        assert status == 400 and b"InvalidBucketName" in b2
        s.close()


class TestWebDavMiniLoop:
    def test_options_200_with_dav_header(self, dav):
        s = _connect(dav.port)
        s.sendall(DAV_OK)
        status, _ = _read_response(s)
        assert status == 200
        s.close()

    def test_dav_verb_dispatch_propppatch_207(self, dav):
        """Non-RFC-2616 verbs must dispatch through the mini loop's
        do_* table exactly like GET."""
        s = _connect(dav.port)
        s.sendall(b"PROPPATCH /f HTTP/1.1\r\nHost: d\r\nContent-Length: 0\r\n\r\n")
        status, body = _read_response(s)
        assert status == 207 and b"multistatus" in body
        s.close()

    def test_garbage_request_line_400(self, dav):
        s = _connect(dav.port)
        s.sendall(b"%%%\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_unknown_method_405(self, dav):
        s = _connect(dav.port)
        s.sendall(b"FROBNICATE / HTTP/1.1\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 405
        s.close()

    def test_oversized_head_431(self, dav):
        s = _connect(dav.port)
        s.sendall(b"OPTIONS / HTTP/1.1\r\n")
        junk = b"X-Filler: " + b"a" * 8000 + b"\r\n"
        try:
            for _ in range(40):
                s.sendall(junk)
            s.sendall(b"\r\n")
        except (BrokenPipeError, ConnectionResetError):
            return
        status, _ = _read_response(s)
        assert status in (0, 431)
        s.close()

    def test_split_reads_and_keep_alive(self, dav):
        s = _connect(dav.port)
        for _ in range(5):
            for piece in (b"OPTIONS /a", b"ny HTTP/1.1\r\nHo", b"st: d\r\n\r\n"):
                s.sendall(piece)
                time.sleep(0.01)
            status, _ = _read_response(s)
            assert status == 200
        s.close()

    def test_pipelined_dav_verbs(self, dav):
        s = _connect(dav.port)
        s.sendall(DAV_OK + b"PROPPATCH /f HTTP/1.1\r\nHost: d\r\nContent-Length: 0\r\n\r\n" + DAV_OK)
        assert _read_response(s)[0] == 200
        assert _read_response(s)[0] == 207
        assert _read_response(s)[0] == 200
        s.close()

    def test_unread_body_realign(self, dav):
        """OPTIONS ignores its body; the loop must skip the declared
        bytes so the next request stays framed."""
        body = b"Z" * 300
        s = _connect(dav.port)
        s.sendall(
            b"OPTIONS / HTTP/1.1\r\nHost: d\r\n"
            + b"Content-Length: %d\r\n\r\n" % len(body)
            + body
            + DAV_OK
        )
        assert _read_response(s)[0] == 200
        assert _read_response(s)[0] == 200
        s.close()

    def test_huge_unread_body_closes_instead_of_blocking(self, dav):
        s = _connect(dav.port)
        s.sendall(
            b"OPTIONS / HTTP/1.1\r\nHost: d\r\n"
            b"Content-Length: 104857600\r\n\r\n"
        )
        status, _ = _read_response(s)
        assert status == 200
        s.settimeout(5)
        assert s.recv(64) == b""  # connection closed, not waiting 100 MB
        s.close()
