"""Crash-consistency plane tests (docs/ANALYSIS.md v3).

Three layers, mirroring the plane itself:

  * crashlint — planted-bug positive controls for every durability-
    order rule plus negative controls proving the blessed idioms
    (durable.publish, fsync-then-rename-then-dirsync) pass;
  * the enumerator — model unit tests (fsync pins a prefix, renames
    can land before data, torn pwritev at iov cuts, budget truncation
    is flagged) and the planted dynamic bug that must be DETECTED;
  * recovery — Volume repair-mode heals (idx truncate, dat re-index,
    torn-tail truncate, vacuum marker roll-forward/back) and the
    acceptance crash matrices: vacuum crashed at every enumerated
    point and a group-commit torn-final-pwritev, both tier-1 (slow-
    exempt) via small bounded state budgets.
"""

from __future__ import annotations

import json
import os
import tempfile
import textwrap

import pytest

from seaweedfs_tpu.analysis import crash, crashlint
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import CorruptNeedle, Needle
from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume


def _mk(nid: int, data: bytes) -> Needle:
    return Needle(cookie=0x5EED, id=nid, data=data)


# ---------------------------------------------------------------------------
# static tier: planted-bug controls per rule


class TestCrashLint:
    def _check(self, tmp_path, source: str, subdir: str = ""):
        root = tmp_path / "fixturepkg" / subdir if subdir else tmp_path / "fixturepkg"
        root.mkdir(parents=True)
        (tmp_path / "fixturepkg" / "__init__.py").write_text("")
        if subdir:
            (root / "__init__.py").write_text("")
        (root / "mod.py").write_text(textwrap.dedent(source))
        findings, _idx = crashlint.check(root=str(tmp_path / "fixturepkg"))
        return findings

    def test_rename_unsynced_src_detected(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def publish(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("x")
                os.replace(tmp, path)
        """)
        rules = {f.rule for f in findings}
        assert "crash-rename-unsynced-src" in rules
        assert "crash-rename-no-dirsync" in rules

    def test_fsync_then_rename_then_dirsync_clean(self, tmp_path):
        findings = self._check(tmp_path, """
            import os
            from seaweedfs_tpu.util.durable import fsync_dir

            def publish(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("x")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                fsync_dir(os.path.dirname(path))
        """)
        assert [f.rule for f in findings] == []

    def test_durable_publish_helper_recognized(self, tmp_path):
        findings = self._check(tmp_path, """
            from seaweedfs_tpu.util import durable

            def save(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("x")
                durable.publish(tmp, path)
        """)
        assert [f.rule for f in findings] == []

    def test_fsync_after_close_detected(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def flushed_too_late(path):
                f = open(path, "wb")
                f.write(b"x")
                f.close()
                os.fsync(f.fileno())
        """)
        assert any(f.rule == "crash-fsync-after-close" for f in findings)

    def test_reassigned_handle_not_flagged(self, tmp_path):
        # the FUSE RELEASE/FLUSH shape: close one handle, fetch a
        # DIFFERENT one into the same name, flush that
        findings = self._check(tmp_path, """
            def dispatch(table, fh):
                f = table.pop(fh)
                f.close()
                f = table.get(fh + 1)
                if f is not None:
                    f.flush()
        """)
        assert [f.rule for f in findings] == []

    def test_idx_before_dat_detected(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def backwards_write(self, blob, offset):
                self.nm.put(1, offset, len(blob))
                os.pwrite(self._fd, blob, offset)
        """, subdir="storage")
        assert any(f.rule == "crash-idx-before-dat" for f in findings)

    def test_dat_then_idx_clean(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def forwards_write(self, blob, offset):
                os.pwrite(self._fd, blob, offset)
                self.nm.put(1, offset, len(blob))
        """, subdir="storage")
        assert [f.rule for f in findings] == []

    def test_replace_unflushed_detected(self, tmp_path):
        findings = self._check(tmp_path, """
            import os

            def leaky_publish(path):
                tmp = path + ".tmp"
                f = open(tmp, "w")
                f.write("x")
                os.replace(tmp, path)
        """)
        assert any(f.rule == "crash-replace-unflushed" for f in findings)

    def test_critical_write_detected(self, tmp_path):
        findings = self._check(tmp_path, """
            def clobber(state_dir):
                with open(state_dir + "/scrub_state.json", "w") as f:
                    f.write("{}")
        """)
        assert any(f.rule == "crash-critical-write" for f in findings)

    def test_critical_write_via_tmp_clean(self, tmp_path):
        findings = self._check(tmp_path, """
            from seaweedfs_tpu.util import durable

            def save(state_dir):
                final = state_dir + "/scrub_state.json"
                tmp = final + ".tmp"
                with open(tmp, "w") as f:
                    f.write("{}")
                durable.publish(tmp, final)
        """)
        assert [f.rule for f in findings] == []


# ---------------------------------------------------------------------------
# the enumerator model


class TestEnumerator:
    def test_fsync_pins_prefix(self):
        """Writes before an fsync survive EVERY legal state at a later
        crash point; writes after it may be lost in some state."""
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "f")
            with open(p, "wb") as f:
                f.write(b"")
            rec = crash.Recorder(d)
            with rec:
                fd = os.open(p, os.O_WRONLY)
                os.pwrite(fd, b"AAAA", 0)
                os.fsync(fd)
                os.pwrite(fd, b"BBBB", 4)
                os.close(fd)
            states, truncated, _n = crash.enumerate_states(
                rec.trace, budget=64
            )
            assert not truncated
            contents = {s.files["f"] for s in states}
            # after the fsync the first write is pinned: no state may
            # hold the second write without the first
            assert not any(
                c[4:8] == b"BBBB" and c[:4] != b"AAAA" for c in contents
            )
            assert b"AAAA" in contents, "no state lost the un-fsynced write"
            assert b"AAAABBBB" in contents
            # states crashing after the barrier never lose the fsynced
            # bytes
            assert all(
                s.files["f"][:4] == b"AAAA"
                for s in states if s.crash_index >= 2
            )

    def test_rename_can_land_before_data(self):
        """The rename-visible-before-data hazard must be in the model:
        some legal state has the destination name with EMPTY bytes."""
        with tempfile.TemporaryDirectory() as d:
            rec = crash.Recorder(d)
            with rec:
                tmp = os.path.join(d, "x.tmp")
                with open(tmp, "wb") as f:
                    f.write(b"NEWDATA")
                os.replace(tmp, os.path.join(d, "x"))
            states, _tr, _n = crash.enumerate_states(rec.trace, budget=64)
            published = [s for s in states if "x" in s.files]
            assert any(s.files["x"] == b"NEWDATA" for s in published)
            assert any(s.files["x"] == b"" for s in published), (
                "model must allow the rename to land without the data"
            )

    def test_torn_pwritev_cuts_at_iov_boundaries(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "f")
            with open(p, "wb") as f:
                f.write(b"")
            rec = crash.Recorder(d)
            with rec:
                fd = os.open(p, os.O_WRONLY)
                os.pwritev(fd, [b"1111", b"2222", b"3333"], 0)
                os.close(fd)
            states, _tr, _n = crash.enumerate_states(rec.trace, budget=64)
            contents = {s.files["f"] for s in states if "f" in s.files}
            # iov-boundary tears of the final write
            assert b"1111" in contents
            assert b"11112222" in contents
            assert b"111122223333" in contents

    def test_budget_truncation_is_flagged(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "f")
            with open(p, "wb") as f:
                f.write(b"")
            rec = crash.Recorder(d)
            with rec:
                fd = os.open(p, os.O_WRONLY)
                for i in range(40):
                    os.pwrite(fd, b"%04d" % i, i * 4)
                os.close(fd)
            states, truncated, candidates = crash.enumerate_states(
                rec.trace, budget=10
            )
            assert truncated and candidates > 10
            assert len(states) <= 10
            # the sampler must be able to reach the END of the
            # candidate space (review finding: a floor-stride spread
            # never picked the torn states of the trace's final writes
            # — generated last — so a recovery bug firing only there
            # would report 0 violations every run)
            full, _tr, _n = crash.enumerate_states(
                rec.trace, budget=10_000
            )
            assert states[-1].digest() == full[-1].digest()

    def test_planted_broken_publish_is_detected(self):
        """The dynamic positive control (also the bench --check crash
        smoke): an unsynced tmp+rename publish MUST yield at least one
        violating crash state."""
        rep = crash.run_broken_publish(budget=64)
        assert rep.violations, "enumerator went blind: planted bug missed"


# ---------------------------------------------------------------------------
# recovery: Volume repair mode


class TestVolumeRepair:
    def _volume_with(self, d, n=3):
        v = Volume(d, 1)
        data = {}
        for i in range(1, n + 1):
            data[i] = b"rec-%03d\xcd" % i * 30
            v.write_needle(_mk(i, data[i]))
        v.commit()
        return v, data

    def test_idx_entry_past_dat_healed(self, tmp_path):
        d = str(tmp_path)
        v, data = self._volume_with(d)
        v.close()
        # plant an entry referencing bytes the .dat does not have
        with open(v.base_name + ".idx", "ab") as f:
            f.write(idx_codec.pack_entry(99, t.offset_to_units(1 << 20), 640))
        with pytest.raises((CorruptNeedle, ValueError)):
            Volume(d, 1, create=False)  # non-repair open still refuses
        v2 = Volume(d, 1, create=False, repair=True)
        assert not v2.has_needle(99)
        for nid, payload in data.items():
            assert v2.read_needle(nid).data == payload
        v2.close()

    def test_lost_idx_tail_reindexed_from_dat(self, tmp_path):
        d = str(tmp_path)
        v, data = self._volume_with(d)
        v.close()
        idx = v.base_name + ".idx"
        os.truncate(idx, os.path.getsize(idx) - 16)  # lose the last entry
        v2 = Volume(d, 1, create=False, repair=True)
        for nid, payload in data.items():
            assert v2.read_needle(nid).data == payload, f"needle {nid} lost"
        v2.close()

    def test_torn_dat_tail_truncated(self, tmp_path):
        d = str(tmp_path)
        v, data = self._volume_with(d)
        v.close()
        idx = v.base_name + ".idx"
        os.truncate(idx, os.path.getsize(idx) - 16)
        # a torn record: half of a fresh append hit the disk, no idx
        torn = _mk(50, b"torn-needle" * 20).encode_record(3)
        with open(v.base_name + ".dat", "ab") as f:
            f.write(torn[: len(torn) // 2])
        v2 = Volume(d, 1, create=False, repair=True)
        for nid, payload in data.items():
            assert v2.read_needle(nid).data == payload
        assert not v2.has_needle(50)
        # the torn bytes are gone: appends land on a clean tail
        v2.write_needle(_mk(60, b"after-repair" * 10))
        assert v2.read_needle(60).data == b"after-repair" * 10
        v2.close()

    def test_commit_marker_rolls_forward(self, tmp_path):
        d = str(tmp_path)
        v, data = self._volume_with(d)
        v.delete_needle(_mk(2, b""))
        del data[2]
        old_rev = v.super_block.compaction_revision
        v.compact()
        # crash simulation: scratch written + marker durable, renames
        # never ran (commit_compact's window between commit point and
        # the swap)
        with open(v.base_name + ".cpm", "wb") as f:
            f.write(b"commit\n")
        v.close()
        v2 = Volume(d, 1, create=False, repair=True)
        assert v2.super_block.compaction_revision == old_rev + 1
        for nid, payload in data.items():
            assert v2.read_needle(nid).data == payload
        with pytest.raises(NeedleNotFound):
            v2.read_needle(2)
        assert not os.path.exists(v.base_name + ".cpm")
        assert not os.path.exists(v.base_name + ".cpd")
        assert not os.path.exists(v.base_name + ".cpx")
        v2.close()

    def test_db_map_sdb_removed_inside_marker_window(self, tmp_path):
        """Review finding: the db needle map's sqlite table is
        checkpointed CLEAN (old watermark) by nm.close() before the
        swap; if it survives a crash whose marker was already removed,
        a compacted idx of coincidentally equal size would skip the
        rebuild and serve pre-compaction offsets. The unlink order in
        commit_compact is the contract: .idx.sdb strictly before .cpm
        (every crash state then either keeps the marker — recovery
        drops the table — or already lost the table)."""
        d = str(tmp_path)
        v = Volume(d, 1, needle_map_kind="db")
        data = {}
        for i in range(1, 5):
            data[i] = b"db-%03d\xee" % i * 25
            v.write_needle(_mk(i, data[i]))
        v.delete_needle(_mk(3, b""))
        del data[3]
        v.commit()
        v.close()
        rec = crash.Recorder(d)
        with rec:
            v = Volume(d, 1, create=False, needle_map_kind="db")
            v.compact()
            v.commit_compact()
            v.close()
        unlinks = [
            e.path for e in rec.trace.events if e.kind == "unlink"
        ]
        assert "1.idx.sdb" in unlinks and "1.cpm" in unlinks
        assert unlinks.index("1.idx.sdb") < unlinks.index("1.cpm")
        # and marker-present recovery drops a stale table even when
        # the scratch files are already gone (renames done, crash
        # before the sdb/marker unlinks reached disk)
        v = Volume(d, 1, create=False, needle_map_kind="db")
        for nid, payload in data.items():
            v.write_needle(_mk(nid, payload))  # repopulate the sdb
        v.close()
        sdb = os.path.join(d, "1.idx.sdb")
        assert os.path.exists(sdb)
        # poison the checkpointed-clean table the way the crash would
        # leave it: offsets that no longer match the (swapped) .dat.
        # Without marker recovery dropping the table, load() trusts
        # the clean flag + watermark and serves these corrupt offsets.
        import sqlite3

        db = sqlite3.connect(sdb)
        db.execute("UPDATE needles SET offset = offset + 1")
        db.commit()
        db.close()
        with open(os.path.join(d, "1.cpm"), "wb") as f:
            f.write(b"commit\n")
        v = Volume(
            d, 1, create=False, needle_map_kind="db", repair=True
        )
        for nid, payload in data.items():
            assert v.read_needle(nid).data == payload, (
                "stale sqlite table survived marker recovery"
            )
        v.close()

    def test_no_marker_rolls_back(self, tmp_path):
        d = str(tmp_path)
        v, data = self._volume_with(d)
        old_rev = v.super_block.compaction_revision
        v.compact()  # scratch exists, commit point never reached
        v.close()
        v2 = Volume(d, 1, create=False, repair=True)
        assert v2.super_block.compaction_revision == old_rev
        for nid, payload in data.items():
            assert v2.read_needle(nid).data == payload
        assert not os.path.exists(v.base_name + ".cpd")
        assert not os.path.exists(v.base_name + ".cpx")
        v2.close()


# ---------------------------------------------------------------------------
# the acceptance crash matrices (tier-1: small bounded budgets)


class TestCrashMatrix:
    def test_vacuum_recovers_old_or_new_never_hybrid(self):
        """Crash at every enumerated point of compact→commit_compact:
        recovery reaches the old or the new generation, every durably
        acked needle survives, deletes stay deleted."""
        rep = crash.run_vacuum(budget=96)
        assert rep.states_tested >= 48
        assert rep.violations == []

    def test_group_commit_torn_final_pwritev(self):
        """The batch lands via ONE pwritev; tearing it at any iov
        boundary must never surface a torn record as valid or lose an
        acked needle."""
        rep = crash.run_group_commit(budget=96)
        assert rep.states_tested >= 32
        assert rep.violations == []

    def test_group_commit_trace_contains_multi_iov_tears(self):
        """Guard the guard: the sweep above is only meaningful if the
        trace really contains a multi-iov batch write and the
        enumerator really tears it."""
        from seaweedfs_tpu.storage.volume import Volume as V

        with tempfile.TemporaryDirectory() as d:
            v = V(d, 1)
            v.commit()
            v.close()
            rec = crash.Recorder(d)
            with rec:
                v = V(d, 1, create=False)
                outs = v.write_needles(
                    [(_mk(i, b"t%03d" % i * 40), None) for i in range(5)],
                    durable=True,
                )
                assert not any(isinstance(o, BaseException) for o in outs)
                v.close()
            batch_writes = [
                e for e in rec.trace.events
                if e.kind == "write" and len(e.chunks) >= 5
            ]
            assert batch_writes, "no multi-iov pwritev in the trace"
            states, _tr, _n = crash.enumerate_states(rec.trace, budget=256)
            assert any(s.label.startswith("torn@") for s in states)

    def test_quarantine_rename_and_state_publish(self):
        rep = crash.run_quarantine(budget=96)
        assert rep.states_tested >= 32
        assert rep.violations == []

    def test_ec_encode_durable_ordering_clean(self):
        """The EC shard writer-pool flush (ISSUE 12 / PR-11 follow-on):
        with durable ordering — shard fds fsynced, .ecx via
        durable.publish — no crash state shows a complete index over
        missing/torn shard bytes."""
        rep = crash.run_ec_encode(budget=96)
        assert rep.states_tested >= 24
        assert rep.violations == []

    def test_ec_encode_pre_fix_ordering_detected(self):
        """Regression proof the durable flag is load-bearing: replaying
        the OLD ordering (no shard fsyncs, .ecx written in place) must
        yield complete-looking-index-over-page-cache-only-shards
        states — the exact finding the sweep fixed."""
        rep = crash.run_ec_encode(budget=96, durable=False)
        assert rep.violations, (
            "the unsynced encode should be catchable — either the "
            "enumerator went blind or posix_fallocate/pwritev streams "
            "stopped being recorded"
        )

    def test_ecc_publish_durable_ordering_clean(self):
        """The `.ecc` sidecar attests shard bytes, so it must never
        reach its final name before those bytes are durable: with the
        durable ordering (shard fsyncs, then durable.publish for the
        sidecar) no crash state shows a complete sidecar vouching for
        missing/torn shard tails."""
        rep = crash.run_ecc_publish(budget=1200)
        assert rep.states_tested >= 256
        assert rep.violations == []

    def test_ecc_publish_unsynced_ordering_detected(self):
        """Regression proof the ordering is load-bearing: skipping the
        shard fsyncs and publishing the sidecar with a bare rename must
        yield confident-sidecar-over-page-cache-only-shards states.
        budget=1200: the planted states live deep in the enumeration
        (durable-data frontier + all-namespace syncs)."""
        rep = crash.run_ecc_publish(budget=1200, durable=False)
        assert rep.violations, (
            "the unsynced sidecar publish should be catchable — either "
            "the enumerator went blind or the sidecar rename/fsync "
            "stream stopped being recorded"
        )

    def test_shard_handback_acked_writes_survive(self):
        """-shardWrites ownership handback: worker-owned appends,
        release, lead catch-up appends, commit — every needle acked at
        the commit survives recovery, idx never outruns the .dat."""
        rep = crash.run_shard_handback(budget=96)
        assert rep.states_tested >= 32
        assert rep.violations == []

    def test_legacy_unsynced_swap_is_caught(self):
        """Regression proof that the commit marker protocol is load-
        bearing: replaying the OLD commit_compact (bare double rename,
        no fsync, no marker) through the enumerator yields violations —
        the exact bug class ISSUE 11 named as the known suspect."""
        with tempfile.TemporaryDirectory() as d:
            v = Volume(d, 1)
            live = {i: b"legacy-%03d\xaa" % i * 50 for i in range(1, 7)}
            for nid, data in live.items():
                v.write_needle(_mk(nid, data))
            old_rev = v.super_block.compaction_revision
            v.commit()
            v.close()
            rec = crash.Recorder(d)
            rec.mark(dict(live))
            with rec:
                v = Volume(d, 1, create=False)
                v.compact()
                cpd, cpx = v.base_name + ".cpd", v.base_name + ".cpx"
                v._makeup_diff(cpd, cpx)
                v._dat.close()
                v.nm.close()
                os.replace(cpd, v.base_name + ".dat")
                os.replace(cpx, v.base_name + ".idx")
                v._dat = open(v.base_name + ".dat", "r+b")
                v._bind_fd()
                v.nm = v._load_needle_map()
                v.close()

            def recover(state_dir, _st, acked_payloads):
                acked: dict[int, bytes] = {}
                for p in acked_payloads:
                    acked.update(p)
                crash.verify_volume(
                    state_dir, 1, acked, revisions=(old_rev, old_rev + 1)
                )

            rep = crash.sweep(
                rec.trace, recover, workload="legacy-swap", budget=200
            )
            assert rep.violations, (
                "the unsynced two-rename swap should be catchable — "
                "either the enumerator went blind or the model lost "
                "rename-before-data states"
            )


# ---------------------------------------------------------------------------
# fixed-site regression: scrub state publish survives every crash state


class TestScrubStatePublish:
    def test_scrub_state_save_is_atomic_and_durable(self):
        from seaweedfs_tpu.scrub.state import ScrubState

        with tempfile.TemporaryDirectory() as d:
            sp = os.path.join(d, "scrub_state.json")
            st = ScrubState(path=sp)
            h = st.get(5, False)
            h.cursor = 100
            st.save()
            rec = crash.Recorder(d)
            with rec:
                h.cursor = 200
                h.sweeps += 1
                st.save()

            def recover(state_dir, _s, _a):
                with open(os.path.join(state_dir, "scrub_state.json")) as f:
                    doc = json.load(f)  # torn JSON = violation
                (row,) = doc["volumes"]
                assert row["cursor"] in (100, 200)

            rep = crash.sweep(
                rec.trace, recover, workload="scrub-state", budget=64
            )
            assert rep.violations == []
