"""Cluster telemetry plane tests (docs/TELEMETRY.md).

Units: quantile estimators, the Prometheus text parser against the
repo's own renderer, ring TSDB rate/retention/reset math, weedload's
log histograms, alert state transitions, the render-snapshot
consistency regression (stats/metrics satellite), CpuProfile
multi-thread aggregation + skipped-thread warning, and the continuous
sampling profiler.

E2E: the acceptance scenario — kill a volume server under a live
cluster, watch scrape_staleness transition to firing in
/cluster/health + cluster.alerts, restart, watch it resolve — plus
gateway registration, /debug/profile over HTTP, the cluster.* shell
commands, and a real multi-process weedload run.
"""

from __future__ import annotations

import io
import json
import logging
import threading
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.stats.quantile import histogram_quantile, percentile


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def wait_until(pred, what: str, deadline_s: float = 30.0):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        try:
            out = pred()
            if out:
                return out
        except Exception:  # noqa: BLE001 - not-ready counts as false
            pass
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


# ----------------------------------------------------------------------
# quantile helpers (the dedupe satellite)


class TestQuantile:
    def test_percentile_nearest_rank(self):
        vals = list(range(1, 11))  # 1..10
        assert percentile(vals, 0.5) == 5
        assert percentile(vals, 0.0) == 1
        assert percentile(vals, 1.0) == 10
        assert percentile(vals, 0.99) == 10
        assert percentile([7.0], 0.99) == 7.0

    def test_percentile_unsorted_input(self):
        assert percentile([9, 1, 5, 3, 7], 0.5) == 5

    def test_percentile_empty_raises(self):
        with pytest.raises(ValueError):
            percentile([], 0.5)
        with pytest.raises(ValueError):
            percentile([1.0], 1.5)

    def test_histogram_quantile_interpolates(self):
        # 100 observations uniform in one bucket (0.1, 0.2]
        bounds = [0.1, 0.2, 0.4]
        counts = [0, 100, 0]
        assert histogram_quantile(bounds, counts, 0.5) == pytest.approx(0.15)
        assert histogram_quantile(bounds, counts, 1.0) == pytest.approx(0.2)

    def test_histogram_quantile_overflow_bucket(self):
        bounds = [0.1, 0.2]
        counts = [0, 0, 5]  # all observations above the last bound
        assert histogram_quantile(bounds, counts, 0.5) == pytest.approx(0.2)

    def test_histogram_quantile_empty_and_validation(self):
        assert histogram_quantile([0.1], [0], 0.99) == 0.0
        with pytest.raises(ValueError):
            histogram_quantile([0.1, 0.2], [1], 0.5)


# ----------------------------------------------------------------------
# Prometheus text parser


class TestParse:
    def test_roundtrip_with_registry(self):
        from seaweedfs_tpu.stats.metrics import Registry
        from seaweedfs_tpu.telemetry.parse import parse_prometheus_text

        reg = Registry()
        c = reg.counter("t_total", "help", ("server", "status"))
        c.labels("vol a", "200").inc(3)
        g = reg.gauge("t_gauge", "help")
        g.set(2.5)
        h = reg.histogram("t_hist", "help", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        samples = parse_prometheus_text(reg.render_text())
        d = {(n, l): v for n, l, v in samples}
        assert d[("t_total", (("server", "vol a"), ("status", "200")))] == 3.0
        assert d[("t_gauge", ())] == 2.5
        assert d[("t_hist_bucket", (("le", "0.1"),))] == 1.0
        assert d[("t_hist_bucket", (("le", "+Inf"),))] == 2.0
        assert d[("t_hist_count", ())] == 2.0

    def test_escapes_and_malformed_lines(self):
        from seaweedfs_tpu.telemetry.parse import parse_prometheus_text

        text = (
            '# HELP x help text\n'
            '# TYPE x counter\n'
            'x{path="a\\"b\\\\c\\nd"} 1\n'
            'garbage line without value\n'
            'noval \n'
            'y 2.5e-3\n'
            'z +Inf\n'
        )
        samples = parse_prometheus_text(text)
        d = {(n, l): v for n, l, v in samples}
        assert d[("x", (("path", 'a"b\\c\nd'),))] == 1.0
        assert d[("y", ())] == pytest.approx(0.0025)
        assert d[("z", ())] == float("inf")
        assert len(samples) == 3


# ----------------------------------------------------------------------
# ring TSDB


class TestSeriesRing:
    def test_retention_cap(self):
        from seaweedfs_tpu.telemetry.ring import SeriesRing

        r = SeriesRing(cap=4)
        for i in range(10):
            r.append(float(i), float(i * 10))
        assert r.count == 4
        assert [v for _, v in r.items()] == [60.0, 70.0, 80.0, 90.0]
        assert r.last() == (9.0, 90.0)

    def test_increase_is_reset_aware(self):
        from seaweedfs_tpu.telemetry.ring import SeriesRing

        r = SeriesRing(cap=16)
        now = 1000.0
        # counter climbs to 50, daemon restarts (reset to 0), climbs to 7
        for i, v in enumerate([10, 30, 50, 0, 3, 7]):
            r.append(now + i, float(v))
        # naive last-first would be -3; reset-aware = 40 + 7
        assert r.increase(100.0, now=now + 6) == pytest.approx(47.0)
        assert r.rate(100.0, now=now + 6) == pytest.approx(47.0 / 5.0)

    def test_rate_needs_two_samples(self):
        from seaweedfs_tpu.telemetry.ring import SeriesRing

        r = SeriesRing(cap=4)
        r.append(1.0, 5.0)
        assert r.rate(100.0, now=2.0) == 0.0

    def test_target_store_quantile_from_buckets(self):
        from seaweedfs_tpu.telemetry.ring import TargetStore

        ts = TargetStore("n1:80", "volume")
        mk = lambda le, v: ("w_seconds_bucket", (("le", le), ("name", "x")), v)
        ts.record_scrape(
            [mk("0.1", 0), mk("1.0", 0), mk("+Inf", 0)], t=100.0
        )
        # 100 obs landed in (0.1, 1.0] since the first scrape
        ts.record_scrape(
            [mk("0.1", 0), mk("1.0", 100), mk("+Inf", 100)], t=110.0
        )
        q = ts.quantile("w_seconds", 0.5, window_s=60.0, now=111.0)
        assert q == pytest.approx(0.55, rel=0.01)
        # no new observations in a later, narrow window
        assert ts.quantile("w_seconds", 0.5, window_s=0.5, now=200.0) is None

    def test_target_store_staleness_and_health(self):
        from seaweedfs_tpu.telemetry.ring import TargetStore

        ts = TargetStore("n1:80", "volume")
        ts.record_scrape([("up", (), 1.0)], t=100.0)
        assert ts.staleness(now=130.0) == pytest.approx(30.0)
        ts.record_failure("boom", t=140.0)
        row = ts.health_row(now=140.0)
        assert row["LastError"] == "boom"
        assert not row["Up"]
        assert row["Series"] == 1


# ----------------------------------------------------------------------
# weedload histograms


class TestLogHistogram:
    def test_record_merge_quantile(self):
        from seaweedfs_tpu.telemetry.weedload import LogHistogram

        a, b = LogHistogram(), LogHistogram()
        for _ in range(99):
            a.record(0.001)
        b.record(1.0)
        a.merge(LogHistogram.from_row(b.to_row()))
        assert a.total == 100
        assert a.quantile(0.5) == pytest.approx(0.001, rel=0.3)
        assert a.quantile(0.999) == pytest.approx(1.0, rel=0.3)
        assert a.max == pytest.approx(1.0)

    def test_quantiles_monotone(self):
        from seaweedfs_tpu.telemetry.weedload import LogHistogram

        h = LogHistogram()
        for i in range(1, 1000):
            h.record(i * 1e-4)
        qs = [h.quantile(q) for q in (0.5, 0.9, 0.99, 0.999)]
        assert qs == sorted(qs)


# ----------------------------------------------------------------------
# alert state machine


class TestAlertManager:
    def test_pending_firing_resolved_cycle(self):
        from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule

        rule = AlertRule("r", "critical", for_s=5.0)
        mgr = AlertManager()
        mgr.evaluate([(rule, "n1", True, 1.0, "d")], now=100.0)
        assert not mgr.firing()  # pending, not yet firing
        assert len(mgr.payload()["Pending"]) == 1
        mgr.evaluate([(rule, "n1", True, 2.0, "d")], now=106.0)
        firing = mgr.firing()
        assert len(firing) == 1 and firing[0]["Alert"] == "r"
        from seaweedfs_tpu.stats.metrics import ALERT_FIRING

        assert ALERT_FIRING.value("r", "n1") == 1.0
        mgr.evaluate([(rule, "n1", False, 0.0, "")], now=110.0)
        assert not mgr.firing()
        assert ALERT_FIRING.value("r", "n1") == 0.0
        hist = mgr.payload()["History"]
        assert len(hist) == 1 and hist[0]["State"] == "resolved"

    def test_absent_pair_resolves(self):
        from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule

        rule = AlertRule("gone", for_s=0.0)
        mgr = AlertManager()
        mgr.evaluate([(rule, "n2", True, 1.0, "d")], now=10.0)
        assert mgr.firing()
        mgr.evaluate([], now=20.0)  # target forgotten entirely
        assert not mgr.firing()

    def test_flap_does_not_reach_history(self):
        from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule

        rule = AlertRule("flappy", for_s=60.0)
        mgr = AlertManager()
        mgr.evaluate([(rule, "n1", True, 1.0, "")], now=0.0)
        mgr.evaluate([(rule, "n1", False, 0.0, "")], now=1.0)
        assert mgr.payload()["History"] == []  # never fired → no entry


# ----------------------------------------------------------------------
# stats/metrics satellite: snapshot-consistent rendering


class TestRenderSnapshotConsistency:
    def test_concurrent_mutation_keeps_exposition_consistent(self):
        from seaweedfs_tpu.stats.metrics import Registry
        from seaweedfs_tpu.telemetry.parse import parse_prometheus_text

        reg = Registry()
        hist = reg.histogram("c_hist", "h", ("k",), buckets=(0.1, 0.5, 1.0))
        ctr = reg.counter("c_total", "h", ("k",))
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                hist.observe((i % 13) / 10.0, "a")
                hist.observe((i % 7) / 10.0, "b")
                ctr.labels("a").inc()
                i += 1

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(60):
                samples = parse_prometheus_text(reg.render_text())
                buckets: dict[str, list[tuple[float, float]]] = {}
                counts: dict[str, float] = {}
                for name, labels, value in samples:
                    ld = dict(labels)
                    if name == "c_hist_bucket":
                        le = (
                            float("inf")
                            if ld["le"] == "+Inf"
                            else float(ld["le"])
                        )
                        buckets.setdefault(ld["k"], []).append((le, value))
                    elif name == "c_hist_count":
                        counts[ld["k"]] = value
                for k, rows in buckets.items():
                    rows.sort()
                    vals = [v for _, v in rows]
                    # cumulative buckets must be monotone AND agree
                    # with the _count line rendered moments later —
                    # the exact property the pre-fix live-list render
                    # violated under concurrent observe()
                    assert vals == sorted(vals), (k, vals)
                    assert vals[-1] == counts[k], (k, vals, counts[k])
        finally:
            stop.set()
            for t in threads:
                t.join()


# ----------------------------------------------------------------------
# util/profiling satellite


class TestCpuProfile:
    def test_aggregates_finished_threads_and_warns_on_running(self, tmp_path):
        import pstats

        from seaweedfs_tpu.util.profiling import CpuProfile

        records: list[logging.LogRecord] = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        handler = Capture()
        logging.getLogger("seaweedfs_tpu").addHandler(handler)
        release = threading.Event()
        path = str(tmp_path / "prof.pstats")

        def finished_work():
            sum(i * i for i in range(20_000))

        def running_work():
            release.wait(30)

        try:
            with CpuProfile(path):
                t1 = threading.Thread(target=finished_work)
                t1.start()
                t1.join()
                t2 = threading.Thread(target=running_work)
                t2.start()
        finally:
            release.set()
            t2.join(timeout=30)
            logging.getLogger("seaweedfs_tpu").removeHandler(handler)
        # the finished thread's frames made it into the dump
        stats = pstats.Stats(path)
        funcs = {fn for _, _, fn in stats.stats}
        assert "finished_work" in funcs
        # the still-running thread was counted and warned about
        warned = [r for r in records if "still running at exit" in r.getMessage()]
        assert len(warned) == 1
        assert "1 thread(s)" in warned[0].getMessage()


# ----------------------------------------------------------------------
# continuous sampling profiler


class TestSamplingProfiler:
    def test_capture_sees_busy_thread(self):
        from seaweedfs_tpu.telemetry import profiler

        assert profiler.ensure_started()
        stop = threading.Event()

        def distinctive_busy_loop_for_profiler_test():
            while not stop.is_set():
                sum(i for i in range(5_000))

        t = threading.Thread(target=distinctive_busy_loop_for_profiler_test)
        t.start()
        try:
            payload = profiler.capture(0.6)
        finally:
            stop.set()
            t.join()
        assert payload["samples"] > 0
        stacks = payload["stacks"]
        assert any(
            "distinctive_busy_loop_for_profiler_test" in s for s in stacks
        ), list(stacks)[:5]
        folded = profiler.render_folded(payload)
        line = folded.splitlines()[0]
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1 and stack

    def test_pause_resume(self):
        from seaweedfs_tpu.telemetry import profiler

        profiler.ensure_started()
        profiler.set_paused(True)
        try:
            s0, _ = profiler.snapshot()
            time.sleep(0.15)
            s1, _ = profiler.snapshot()
            assert s1 == s0  # no samples while paused
        finally:
            profiler.set_paused(False)
        deadline = time.time() + 5
        while time.time() < deadline:
            if profiler.snapshot()[0] > s1:
                break
            time.sleep(0.02)
        assert profiler.snapshot()[0] > s1  # sampling again


# ----------------------------------------------------------------------
# e2e: the acceptance scenario


@pytest.fixture(scope="class")
def telemetry_cluster(tmp_path_factory):
    from seaweedfs_tpu.util.availability import start_cluster

    dirs = [
        str(tmp_path_factory.mktemp("tele-v0")),
        str(tmp_path_factory.mktemp("tele-v1")),
    ]
    master, servers = start_cluster(
        dirs,
        master_kwargs={"telemetry_interval": 0.3},
        scrub_interval=0.0,
    )
    yield master, servers, dirs
    for vs in servers:
        try:
            vs.stop()
        except Exception:  # noqa: BLE001 - some get stopped by tests
            pass
    master.stop()


class TestClusterTelemetryE2E:
    def _shell(self, master, line: str) -> str:
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import COMMANDS
        import shlex

        env = CommandEnv([f"127.0.0.1:{master.port}"])
        out = io.StringIO()
        parts = shlex.split(line)
        COMMANDS[parts[0]].run(env, parts[1:], out)
        return out.getvalue()

    def test_kill_volume_server_fires_staleness_then_restart_resolves(
        self, telemetry_cluster
    ):
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.stats.metrics import ALERT_FIRING

        master, servers, dirs = telemetry_cluster
        m = f"127.0.0.1:{master.port}"
        victim = servers[1]
        victim_url = f"127.0.0.1:{victim.port}"

        # phase 0: all three targets (master + 2 volumes) healthy
        def all_up():
            h = _get_json(f"http://{m}/cluster/health")
            rows = h.get("Targets", {})
            return (
                len(rows) >= 3
                and all(r["Up"] for r in rows.values())
                and h["Cycles"] >= 2
            )

        wait_until(all_up, "all targets scraped and up")
        health = _get_json(f"http://{m}/cluster/health")
        assert health["Targets"][victim_url]["Kind"] == "volume"
        assert not _get_json(f"http://{m}/cluster/alerts")["Firing"]

        # phase 1: kill the volume server → scrape_staleness FIRING
        victim.stop()

        def staleness_firing():
            alerts = _get_json(f"http://{m}/cluster/alerts")["Firing"]
            return any(
                a["Alert"] == "scrape_staleness" and a["Target"] == victim_url
                for a in alerts
            )

        wait_until(staleness_firing, "staleness alert firing", 30.0)
        health = _get_json(f"http://{m}/cluster/health")
        assert not health["Targets"][victim_url]["Up"]
        assert health["FiringAlerts"] >= 1
        # re-exported as a gauge on the master's own /metrics
        assert ALERT_FIRING.value("scrape_staleness", victim_url) == 1.0
        # and visible through the operator shell
        text = self._shell(master, "cluster.alerts")
        assert "FIRING" in text and "scrape_staleness" in text
        assert victim_url in text
        health_text = self._shell(master, "cluster.health")
        assert "DOWN" in health_text

        # phase 2: restart on the same port/dir → alert resolves
        revived = VolumeServer(
            [dirs[1]],
            port=victim.port,
            master=m,
            rack="rack1",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            scrub_interval=0.0,
        )
        servers[1] = revived
        revived.start()

        def resolved():
            alerts = _get_json(f"http://{m}/cluster/alerts")
            still = any(
                a["Alert"] == "scrape_staleness" and a["Target"] == victim_url
                for a in alerts["Firing"]
            )
            up = _get_json(f"http://{m}/cluster/health")["Targets"][
                victim_url
            ]["Up"]
            return not still and up
        wait_until(resolved, "staleness alert resolved after restart", 30.0)
        assert ALERT_FIRING.value("scrape_staleness", victim_url) == 0.0
        hist = _get_json(f"http://{m}/cluster/alerts")["History"]
        assert any(
            a["Alert"] == "scrape_staleness" and a["Target"] == victim_url
            for a in hist
        )

    def test_cluster_top_ranks_traffic(self, telemetry_cluster):
        master, servers, _dirs = telemetry_cluster
        m = f"127.0.0.1:{master.port}"
        # generate some traffic so rates are non-zero
        for _ in range(30):
            a = _get_json(f"http://{m}/dir/assign")
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}",
                    data=b"telemetry-top-payload" * 40,
                    method="POST",
                ),
                timeout=10,
            ).close()

        def has_rates():
            top = _get_json(f"http://{m}/cluster/top?n=5")
            ok = top.get("Nodes") and any(
                r["ReqPerSec"] > 0 for r in top["Nodes"]
            ) and top.get("Volumes")
            return top if ok else None

        top = wait_until(has_rates, "cluster.top sees traffic", 30.0)
        assert top["Volumes"][0]["SizeBytes"] > 0
        text = self._shell(master, "cluster.top -n 5")
        assert "busiest nodes" in text and "req/s" in text

    def test_gateway_registration_becomes_scrape_target(
        self, telemetry_cluster
    ):
        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.util.availability import free_port

        master, _servers, _dirs = telemetry_cluster
        m = f"127.0.0.1:{master.port}"
        filer = FilerServer(
            [m], port=free_port(), announce_interval=0.2
        )
        filer.start()
        try:
            filer_url = f"127.0.0.1:{filer.port}"

            def filer_scraped():
                h = _get_json(f"http://{m}/cluster/health")
                row = h["Targets"].get(filer_url)
                return row and row["Kind"] == "filer" and row["Up"]

            wait_until(filer_scraped, "filer registered and scraped", 30.0)
        finally:
            filer.stop()

    def test_debug_profile_over_http(self, telemetry_cluster):
        master, servers, _dirs = telemetry_cluster
        payload = _get_json(
            f"http://127.0.0.1:{servers[0].port}/debug/profile?seconds=0.4",
            timeout=15,
        )
        assert payload["samples"] > 0
        assert any(";" in s for s in payload["stacks"])
        # folded text format for flamegraph.pl
        with urllib.request.urlopen(
            f"http://127.0.0.1:{servers[0].port}"
            "/debug/profile?seconds=0.2&fmt=folded",
            timeout=15,
        ) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
        assert body.strip(), "folded output empty"
        stack, _, count = body.splitlines()[0].rpartition(" ")
        assert int(count) >= 1
        # profile.capture shell command against the same node
        text = self._shell(
            master,
            f"profile.capture -node 127.0.0.1:{servers[0].port} -seconds 0.3",
        )
        assert "sample(s)" in text

    def test_register_endpoint_validates(self, telemetry_cluster):
        master, _servers, _dirs = telemetry_cluster
        m = f"127.0.0.1:{master.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{m}/cluster/register?kind=s3", timeout=5
            )
        assert ei.value.code == 400


class TestWeedloadE2E:
    def test_multiprocess_load_reports_quantiles(self, tmp_path):
        from seaweedfs_tpu.telemetry.weedload import run_load
        from seaweedfs_tpu.util.availability import start_cluster

        master, servers = start_cluster([str(tmp_path)], scrub_interval=0.0)
        try:
            report = run_load(
                f"127.0.0.1:{master.port}",
                duration_s=2.0,
                writers=1,
                readers=1,
                payload_bytes=512,
                rate=0.0,
                seed_n=8,
            )
        finally:
            for vs in servers:
                vs.stop()
            master.stop()
        assert report["config"]["processes"] == 2
        for mode in ("put", "get"):
            row = report[mode]
            assert row["ops"] > 0, report
            assert row["errors"] == 0, report
            assert 0 < row["p50_ms"] <= row["p99_ms"] <= row["p999_ms"]

    def test_paced_mode_is_co_safe(self, tmp_path):
        """With a rate schedule, a stalled server charges the latency of
        every request queued behind the stall (measured from the
        SCHEDULED start) — the pure closed-loop lie is off."""
        from seaweedfs_tpu.telemetry.weedload import (
            LogHistogram,
            _worker,
        )

        # a fake one-shot "server": the first request stalls 0.5s, the
        # rest are instant; at 50 req/s the stall spans ~25 schedules
        class FakeQ:
            def __init__(self):
                self.rows = []

            def put(self, row):
                self.rows.append(row)

        calls = {"n": 0}

        import seaweedfs_tpu.telemetry.weedload as wl

        real_http = wl._http

        def stalling_http(conns, netloc, method, path, body=None, timeout=30.0):
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.5)
            if method == "GET" and path == "/dir/assign":
                return 200, json.dumps(
                    {"fid": "1,ff", "url": "fake"}
                ).encode()
            return 201, b"{}"

        q = FakeQ()
        wl._http = stalling_http
        try:
            _worker(
                {
                    "mode": "put",
                    "master": "fake",
                    "duration_s": 1.0,
                    "payload": b"x",
                    "rate": 50.0,
                    "keys": [],
                    "index": 0,
                },
                q,
            )
        finally:
            wl._http = real_http
        row = q.rows[0]
        hist = LogHistogram.from_row(row["hist"])
        # ~25 schedules piled up behind the 0.5s stall; CO correction
        # charges each from its SCHEDULED start, so the upper quantiles
        # carry the queue delay. Without the correction only ONE op
        # records the stall and p90 collapses to the ~1ms service time
        # — the classic coordinated-omission lie this test pins down.
        assert row["ops"] >= 20
        assert hist.quantile(0.9) > 0.05, hist.quantile(0.9)
        assert hist.max > 0.4, hist.max
