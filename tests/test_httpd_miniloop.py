"""The mini per-connection request loop (util/httpd.serve_connection):
adversarial and edge-case input against a live volume server socket.

From-scratch HTTP parsing earns from-scratch abuse tests: malformed
request lines, bad Content-Length, oversized heads, pipelining,
keep-alive semantics, partial heads across packets, and unread-body
realignment — the server must answer per spec or close cleanly, and
must NEVER desync a keep-alive connection (serving one request's body
bytes as the next request's head is the catastrophic failure mode).
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.util.availability import start_cluster


@pytest.fixture(scope="module")
def vs(tmp_path_factory):
    master, servers = start_cluster(
        [str(tmp_path_factory.mktemp("mini"))], volume_size_limit_mb=64
    )
    yield servers[0]
    for s in servers:
        s.stop()
    master.stop()


def _connect(vs):
    s = socket.create_connection(("127.0.0.1", vs.port), timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
    return s


_leftover: dict[socket.socket, bytes] = {}


def _read_response(s) -> tuple[int, bytes]:
    """(status, body) for one Content-Length-framed response. Carries
    per-socket leftovers (keyed by the LIVE socket object, so a freed
    id cannot alias another connection): pipelined responses can
    coalesce into one TCP segment, and dropping the tail would starve
    the next read."""
    buf = _leftover.pop(s, b"")
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(65536)
        if not chunk:
            return 0, b""
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    status = int(head.split(None, 2)[1])
    length = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            length = int(v.strip())
    while len(rest) < length:
        chunk = s.recv(65536)
        if not chunk:
            break
        rest += chunk
    if rest[length:]:
        _leftover[s] = rest[length:]
    return status, rest[:length]


class TestMiniLoopEdges:
    def test_garbage_request_line_400(self, vs):
        s = _connect(vs)
        s.sendall(b"NOT A REQUEST\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_bad_version_400(self, vs):
        s = _connect(vs)
        s.sendall(b"GET /status FTP/9\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_bad_content_length_400(self, vs):
        s = _connect(vs)
        s.sendall(
            b"POST /1,00000000 HTTP/1.1\r\nContent-Length: abc\r\n\r\n"
        )
        status, _ = _read_response(s)
        assert status == 400
        s.close()

    def test_oversized_head_431(self, vs):
        s = _connect(vs)
        s.sendall(b"GET /status HTTP/1.1\r\n")
        junk = b"X-Filler: " + b"a" * 8000 + b"\r\n"
        try:
            for _ in range(40):  # ~320 KB of headers > the 128 KB cap
                s.sendall(junk)
            s.sendall(b"\r\n")
        except (BrokenPipeError, ConnectionResetError):
            return  # server already slammed the door: acceptable
        status, _ = _read_response(s)
        assert status in (0, 431)  # 431 or hard close
        s.close()

    def test_unknown_method_405(self, vs):
        s = _connect(vs)
        s.sendall(b"BREW /status HTTP/1.1\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 405
        s.close()

    def test_partial_head_across_packets(self, vs):
        s = _connect(vs)
        for piece in (b"GET /sta", b"tus HT", b"TP/1.1\r\nHost: x\r", b"\n\r\n"):
            s.sendall(piece)
            time.sleep(0.02)
        status, body = _read_response(s)
        assert status == 200 and b"seaweedfs_tpu" in body
        s.close()

    def test_pipelined_requests_two_responses(self, vs):
        s = _connect(vs)
        s.sendall(
            b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n"
        )
        st1, b1 = _read_response(s)
        st2, b2 = _read_response(s)
        assert st1 == st2 == 200 and b1 == b2
        s.close()

    def test_keep_alive_many_requests_one_connection(self, vs):
        s = _connect(vs)
        for _ in range(20):
            s.sendall(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
            status, body = _read_response(s)
            assert status == 200 and b"Volumes" in body
        s.close()

    def test_connection_close_honored(self, vs):
        s = _connect(vs)
        s.sendall(
            b"GET /status HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n"
        )
        status, _ = _read_response(s)
        assert status == 200
        # server must close its side; the next recv sees EOF
        s.settimeout(5)
        assert s.recv(64) == b""
        s.close()

    def test_http10_defaults_to_close(self, vs):
        s = _connect(vs)
        s.sendall(b"GET /status HTTP/1.0\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 200
        s.settimeout(5)
        assert s.recv(64) == b""
        s.close()

    def test_unread_error_body_does_not_desync(self, vs):
        """A 4xx reply to a request whose body the handler never read:
        the loop must skip the body bytes, and the NEXT request on the
        same connection must parse cleanly (not the stale body)."""
        s = _connect(vs)
        body = b"B" * 512
        # invalid fid -> 400 before the handler touches the body
        s.sendall(
            b"POST /not-a-fid HTTP/1.1\r\nHost: x\r\n"
            + b"Content-Length: %d\r\n\r\n" % len(body)
            + body
        )
        status, _ = _read_response(s)
        assert status in (400, 404)
        s.sendall(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
        status, resp = _read_response(s)
        assert status == 200 and b"Volumes" in resp
        s.close()

    def test_huge_unread_body_closes_instead_of_blocking(self, vs):
        """Past the 1 MiB skip budget the loop closes rather than
        reading a body nobody wants."""
        s = _connect(vs)
        s.sendall(
            b"POST /not-a-fid HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 104857600\r\n\r\n"
        )
        status, _ = _read_response(s)
        assert status in (400, 404)
        s.settimeout(5)
        assert s.recv(64) == b""  # connection closed, not waiting 100 MB
        s.close()

    def test_expect_100_continue(self, vs):
        s = _connect(vs)
        s.sendall(
            b"POST /not-a-fid HTTP/1.1\r\nHost: x\r\n"
            b"Expect: 100-continue\r\nContent-Length: 4\r\n\r\n"
        )
        buf = b""
        while b"100 Continue\r\n\r\n" not in buf:
            chunk = s.recv(4096)
            assert chunk, "no 100 Continue interim"
            buf += chunk
        s.sendall(b"data")
        # the final response follows on the same stream
        rest = buf.split(b"100 Continue\r\n\r\n", 1)[1]
        while b"\r\n\r\n" not in rest:
            rest += s.recv(4096)
        assert rest.split(None, 2)[1] in (b"400", b"404")
        s.close()

    def test_half_open_connection_no_thread_leak(self, vs):
        """Clients that connect and send nothing then vanish must not
        wedge anything: the loop's recv sees EOF and returns."""
        for _ in range(10):
            s = _connect(vs)
            s.close()
        # and one that sends half a head then disconnects
        s = _connect(vs)
        s.sendall(b"GET /sta")
        s.close()
        # server still healthy
        s = _connect(vs)
        s.sendall(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
        status, _ = _read_response(s)
        assert status == 200
        s.close()
