"""`volume -workers N` SO_REUSEPORT read workers (server/volume_workers.py).

The lead stays the single writer (the reference's per-volume write
ordering, volume_read_write.go:66); workers serve GET/HEAD from the
shared directories with `.idx` tail-replay freshness and proxy
everything else to the lead's internal listener.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.server.volume_workers import SharedReadVolume, VolumeReadWorker
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


class TestSharedReadVolume:
    def _needle(self, nid: int, data: bytes) -> Needle:
        n = Needle(cookie=0x42, id=nid, data=data)
        n.name = b"w.bin"
        n.set_has_name()
        return n

    def test_sees_writes_made_after_open(self, tmp_path):
        owner = Volume(str(tmp_path), 5)
        owner.write_needle(self._needle(1, b"first"))
        reader = SharedReadVolume(str(tmp_path), 5)
        assert reader.read_needle(1, cookie=0x42).data == b"first"
        # writes landing AFTER the reader opened must become visible
        # (idx tail replay — read-your-writes across processes)
        owner.write_needle(self._needle(2, b"second"))
        assert reader.read_needle(2, cookie=0x42).data == b"second"
        # overwrite: the reader must serve the new version
        owner.write_needle(self._needle(1, b"first-v2"))
        assert reader.read_needle(1, cookie=0x42).data == b"first-v2"

    def test_sees_deletes(self, tmp_path):
        owner = Volume(str(tmp_path), 6)
        owner.write_needle(self._needle(1, b"doomed"))
        reader = SharedReadVolume(str(tmp_path), 6)
        assert reader.read_needle(1).data == b"doomed"
        owner.delete_needle(Needle(cookie=0x42, id=1))
        with pytest.raises(NeedleNotFound):
            reader.read_needle(1)

    def test_survives_vacuum_commit(self, tmp_path):
        owner = Volume(str(tmp_path), 7)
        for i in range(1, 6):
            owner.write_needle(self._needle(i, b"x%d" % i))
        owner.delete_needle(Needle(cookie=0x42, id=2))
        reader = SharedReadVolume(str(tmp_path), 7)
        assert reader.read_needle(3).data == b"x3"
        owner.compact()
        owner.commit_compact()
        # new inode pair: the reader reopens and keeps serving
        assert reader.read_needle(3).data == b"x3"
        with pytest.raises(NeedleNotFound):
            reader.read_needle(2)
        # post-vacuum writes flow through the reopened index
        owner.write_needle(self._needle(9, b"after-vacuum"))
        assert reader.read_needle(9).data == b"after-vacuum"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    mport, vport, wport = free_port(), free_port(), free_port()
    iport = free_port()
    master = MasterServer(port=mport)
    master.start()
    vdir = str(tmp_path_factory.mktemp("wvol"))
    lead = VolumeServer(
        [vdir],
        port=vport,
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        internal_port=iport,
    )
    lead.start()
    deadline = time.time() + 20
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    worker = VolumeReadWorker(
        [vdir],
        host="127.0.0.1",
        port=free_port(),  # its own shared-port stand-in
        lead=f"127.0.0.1:{iport}",
        worker_port=wport,
    )
    worker.start()
    yield master, lead, worker, mport, vport, wport
    worker.stop()
    lead.stop()
    master.stop()


class TestVolumeReadWorker:
    def _assign(self, mport):
        import json

        with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/dir/assign"
        ) as r:
            return json.load(r)

    def test_worker_serves_lead_writes(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{vport}/{a['fid']}?filename=t.txt",
            data=b"through the lead",
            method="POST",
        )
        urllib.request.urlopen(req).read()
        # read via the WORKER port: local fast path, not the lead
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            assert r.read() == b"through the lead"
            assert r.headers.get("ETag")

    def test_worker_proxies_writes_to_lead(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}",
            data=b"written via worker proxy",
            method="POST",
        )
        body = urllib.request.urlopen(req).read()
        assert b"eTag" in body
        # and the lead really owns it
        with urllib.request.urlopen(
            f"http://127.0.0.1:{vport}/{a['fid']}"
        ) as r:
            assert r.read() == b"written via worker proxy"

    def test_worker_read_your_write_after_proxy(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}",
            data=b"immediately visible",
            method="POST",
        )
        urllib.request.urlopen(req).read()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            assert r.read() == b"immediately visible"

    def test_worker_proxies_deletes_and_sees_tombstone(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{wport}/{a['fid']}",
                data=b"doomed",
                method="POST",
            )
        ).read()
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{wport}/{a['fid']}", method="DELETE"
            )
        ).read()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{wport}/{a['fid']}")
        assert ei.value.code == 404

    def test_worker_proxies_status_pages(self, stack):
        master, lead, worker, mport, vport, wport = stack
        with urllib.request.urlopen(f"http://127.0.0.1:{wport}/status") as r:
            assert b"Volumes" in r.read()

    def test_worker_range_and_304(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{vport}/{a['fid']}",
                data=b"0123456789",
                method="POST",
            )
        ).read()
        req = urllib.request.Request(f"http://127.0.0.1:{wport}/{a['fid']}")
        req.add_header("Range", "bytes=2-5")
        with urllib.request.urlopen(req) as r:
            assert r.status == 206 and r.read() == b"2345"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            etag = r.headers["ETag"]
        req = urllib.request.Request(f"http://127.0.0.1:{wport}/{a['fid']}")
        req.add_header("If-None-Match", etag)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 304

    def test_concurrent_mixed_load(self, stack):
        """Writes proxied + reads served locally under concurrency —
        the worker must never serve stale or torn data."""
        master, lead, worker, mport, vport, wport = stack
        errors = []

        def one(i):
            try:
                a = self._assign(mport)
                payload = b"payload-%d" % i
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{wport}/{a['fid']}",
                        data=payload,
                        method="POST",
                    )
                ).read()
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{wport}/{a['fid']}"
                ) as r:
                    got = r.read()
                if got != payload:
                    errors.append((i, got, payload))
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(24)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]


class TestWorkersCli:
    """The real `volume -workers N` spawn path: a CLI lead brings up
    SO_REUSEPORT worker subprocesses sharing its port; fresh-connection
    reads spread across processes and writes land through whichever
    process accepts."""

    def test_cli_workers_share_port(self, tmp_path):
        import json
        import os
        import subprocess
        import sys

        mport, vport = free_port(), free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu", WEED_EC_CODEC="cpu")

        def spawn(*args):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "import jax; jax.config.update('jax_platforms', 'cpu');"
                    "from seaweedfs_tpu.__main__ import main; main()",
                    *args,
                ],
                env=env,
                cwd="/root/repo",
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )

        procs = [spawn("master", "-port", str(mport))]
        try:
            # generous spawn deadlines: each subprocess pays a fresh
            # interpreter + jax import, which stretches from ~3 s to
            # tens of seconds when the host throttles mid-suite (this
            # test failed a full-suite run on exactly that)
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/stats/health", timeout=2
                    ).read()
                    break
                except OSError:
                    time.sleep(0.2)
            procs.append(
                spawn(
                    "volume",
                    "-port", str(vport),
                    "-mserver", f"127.0.0.1:{mport}",
                    "-dir", str(tmp_path),
                    "-max", "8",
                    "-workers", "3",
                )
            )
            # lead + 2 worker subprocesses all listening (workers take a
            # few seconds each: fresh interpreter + jax import)
            def assigned():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign", timeout=2
                ) as r:
                    return json.loads(r.read())

            deadline = time.time() + 120
            fid = None
            while time.time() < deadline:
                try:
                    a = assigned()
                    if "fid" in a:
                        fid = a["fid"]
                        break
                except OSError:
                    pass
                time.sleep(0.3)
            assert fid, "volume lead never registered"
            url = f"http://127.0.0.1:{vport}/{fid}"
            urllib.request.urlopen(
                urllib.request.Request(url, data=b"cli worker payload", method="POST"),
                timeout=10,
            ).read()
            # give worker subprocesses time to finish binding, then read
            # over MANY fresh connections: the kernel spreads them over
            # all SO_REUSEPORT listeners, so every process must serve
            deadline = time.time() + 45
            while time.time() < deadline:
                try:
                    ok = all(
                        urllib.request.urlopen(url, timeout=5).read()
                        == b"cli worker payload"
                        for _ in range(12)
                    )
                    if ok:
                        break
                except (OSError, AssertionError):
                    pass
                time.sleep(0.5)
            for _ in range(12):
                with urllib.request.urlopen(url, timeout=10) as r:
                    assert r.read() == b"cli worker payload"
            # delete propagates through whichever process accepts
            urllib.request.urlopen(
                urllib.request.Request(url, method="DELETE"), timeout=10
            ).read()
            for _ in range(6):
                with pytest.raises(urllib.error.HTTPError):
                    urllib.request.urlopen(url, timeout=10)
        finally:
            for p in reversed(procs):
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestTornReadUnderVacuum:
    """VERDICT r4 weak #4: the worker freshness design rests on
    fstat-per-lookup; the feared window is a vacuum commit landing
    between a worker's fstat and its pread of the old .dat fd. The
    design answer is that the window is CLOSED by construction — the
    worker preads a BOUND fd, and commit_compact renames a fresh
    .cpd/.cpx pair over the names, so an fd opened before the commit
    still addresses the pre-vacuum bytes that its replayed index
    offsets describe (consistent, at worst one commit stale); the next
    fstat sees the inode change and reopens. These tests hammer that
    story across ≥50 real commits and fail on ANY torn byte: needle
    CRC is verified on every read (Volume.read_needle), cookies are
    enforced, and every body must be a version that was actually
    written."""

    def _needle(self, nid: int, data: bytes) -> Needle:
        n = Needle(cookie=0x42, id=nid, data=data)
        return n

    def test_inprocess_reader_vs_looped_vacuum(self, tmp_path):
        owner = Volume(str(tmp_path), 21)
        # stable keys that survive every vacuum
        stable = {i: b"stable-%d " % i * 40 for i in range(1, 6)}
        for nid, data in stable.items():
            owner.write_needle(self._needle(nid, data))
        reader = SharedReadVolume(str(tmp_path), 21)

        hot_lock = threading.Lock()
        hot_round = [0]
        owner.write_needle(self._needle(9, b"hot-v0 " * 50))

        stop = threading.Event()
        failures: list[str] = []
        reads = [0]

        def read_with_retry(nid):
            # mid-commit transients surface as OSError; the worker
            # architecture proxies those to the lead, so the in-process
            # stand-in retries a few times before calling it a failure
            # (a single retry can itself land in the next commit's
            # window when the whole host is loaded)
            last = None
            delay = 0.003
            for _ in range(8):  # ~0.4 s total: spans scheduler stalls
                try:
                    return reader.read_needle(nid, cookie=0x42).data
                except OSError as e:
                    last = e
                    time.sleep(delay)
                    delay *= 2
            raise last

        def read_loop():
            while not stop.is_set():
                for nid, want in stable.items():
                    try:
                        got = read_with_retry(nid)
                    except OSError as e:
                        failures.append(f"stable {nid}: {e!r}")
                        continue
                    if got != want:
                        failures.append(f"stable {nid}: torn/wrong body")
                    reads[0] += 1
                try:
                    got = read_with_retry(9)
                except OSError as e:
                    failures.append(f"hot key: {e!r}")
                    continue
                except NeedleNotFound:
                    failures.append("hot key vanished")
                    continue
                # CRC is verified inside read_needle; here we assert the
                # body is SELF-CONSISTENT — exactly one version repeated
                # in the written pattern. Staleness is allowed (a reader
                # descheduled across commits legitimately returns an
                # older version); torn or mixed bytes never parse back
                # to a single round's pattern.
                prefix = got.split(b" ", 1)[0]  # b"hot-vN"
                with hot_lock:
                    current = hot_round[0]
                ok = (
                    prefix.startswith(b"hot-v")
                    and prefix[5:].isdigit()
                    and int(prefix[5:]) <= current
                    and got == (prefix + b" ") * 50
                )
                if not ok:
                    failures.append(f"hot key: torn body {got[:40]!r}")
                reads[0] += 1

        threads = [threading.Thread(target=read_loop) for _ in range(2)]
        for t in threads:
            t.start()
        commits = 0
        try:
            for round_no in range(1, 56):  # >= 50 commits
                body = (b"hot-v%d " % round_no) * 50
                with hot_lock:
                    hot_round[0] = round_no
                owner.write_needle(self._needle(9, body))
                # churn: a doomed needle per round keeps vacuum honest
                owner.write_needle(self._needle(1000 + round_no, b"junk" * 64))
                owner.delete_needle(Needle(cookie=0x42, id=1000 + round_no))
                owner.compact()
                owner.commit_compact()
                commits += 1
                # PACE, don't race: wait until the readers demonstrably
                # crossed this commit before firing the next one. The
                # old free-running loop asserted a read RATE
                # (reads > 3×commits), which is a scheduler property —
                # on a loaded 1-vCPU host the readers can legitimately
                # starve and the assertion flaked (CHANGES PR 3). The
                # torn-read property needs INTERLEAVING, and pacing
                # guarantees ≥1 read per commit deterministically.
                target = reads[0] + 1
                deadline = time.time() + 30
                while reads[0] < target and time.time() < deadline:
                    time.sleep(0.002)
                assert reads[0] >= target, (
                    f"readers made no progress across commit {commits} "
                    f"within 30s; failures so far: {failures[:5]}"
                )
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)

        assert commits >= 50
        assert not failures, failures[:10]
        # interleaving floor now holds by construction (paced loop)
        assert reads[0] >= commits, f"only {reads[0]} reads crossed the loop"

    def test_stack_reader_vs_grpc_vacuum_loop(self, stack):
        """Same property through the wire: hammer the worker's HTTP
        port while the lead runs compact→commit cycles over gRPC."""
        import grpc
        import json

        from seaweedfs_tpu.pb import rpc, volume_pb2

        master, lead, worker, mport, vport, wport = stack
        assign = self._assign_to(mport)
        vid = int(assign["fid"].split(",")[0])
        payload = b"torn-read stack payload " * 64
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}",
                data=payload,
                method="POST",
            ),
            timeout=10,
        ).close()

        stop = threading.Event()
        failures: list[str] = []
        reads = [0]

        def read_loop():
            url = f"http://127.0.0.1:{wport}/{assign['fid']}"
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        if r.read() != payload:
                            failures.append("body mismatch")
                except Exception as e:  # noqa: BLE001
                    failures.append(repr(e))
                reads[0] += 1

        t = threading.Thread(target=read_loop)
        t.start()
        commits = 0
        try:
            with grpc.insecure_channel(f"127.0.0.1:{lead.grpc_port}") as ch:
                stub = rpc.volume_stub(ch)
                for i in range(52):
                    # churn then vacuum: doomed needle makes real garbage
                    _, a2 = 0, self._assign_to(mport)
                    if int(a2["fid"].split(",")[0]) == vid:
                        urllib.request.urlopen(
                            urllib.request.Request(
                                f"http://{a2['url']}/{a2['fid']}",
                                data=b"doomed",
                                method="POST",
                            ),
                            timeout=10,
                        ).close()
                        urllib.request.urlopen(
                            urllib.request.Request(
                                f"http://{a2['url']}/{a2['fid']}",
                                method="DELETE",
                            ),
                            timeout=10,
                        ).close()
                    stub.VacuumVolumeCompact(
                        volume_pb2.VacuumVolumeCompactRequest(volume_id=vid)
                    )
                    stub.VacuumVolumeCommit(
                        volume_pb2.VacuumVolumeCommitRequest(volume_id=vid)
                    )
                    commits += 1
                    # PACE the commit loop on demonstrated read
                    # progress (same deflake as the in-process test):
                    # the wire property is reads INTERLEAVING commits,
                    # and the old free-running `reads > 50` floor was
                    # a scheduler-rate assertion that flaked whenever
                    # the reader thread starved on a loaded host
                    target = reads[0] + 1
                    deadline = time.time() + 30
                    while reads[0] < target and time.time() < deadline:
                        time.sleep(0.002)
                    assert reads[0] >= target, (
                        f"reader made no progress across commit "
                        f"{commits} within 30s; failures: {failures[:5]}"
                    )
        finally:
            stop.set()
            t.join(timeout=30)

        assert commits >= 50
        assert not failures, failures[:10]
        # ≥1 read per commit holds by construction (paced loop)
        assert reads[0] >= commits

    def _assign_to(self, mport):
        import json

        with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/dir/assign"
        ) as r:
            return json.load(r)


class TestWorkerAdmission:
    """`volume -workers N` read workers enforce admission control
    (ROADMAP tail-latency follow-on: until now only the lead gated, so
    N-1 of every N SO_REUSEPORT connections bypassed the budget)."""

    def _worker_with_admission(self, tmp_path, rate=1.0, procs=1):
        vol = Volume(str(tmp_path), 9)
        n = Needle(cookie=0x42, id=1, data=b"gated" * 8)
        vol.write_needle(n)
        vol.close()
        worker = VolumeReadWorker(
            [str(tmp_path)],
            host="127.0.0.1",
            port=free_port(),
            lead="127.0.0.1:1",  # never dialed: the blob is local
            admission_rate=rate,
            admission_burst=rate,
            admission_procs=procs,
        )
        worker.start()
        return worker

    def test_worker_sheds_over_budget_with_retry_after(self, tmp_path):
        worker = self._worker_with_admission(tmp_path, rate=1.0)
        try:
            from seaweedfs_tpu.storage.file_id import FileId

            url = f"http://127.0.0.1:{worker.port}/{FileId(9, 1, 0x42)}"
            with urllib.request.urlopen(url, timeout=10) as r:
                assert r.status == 200
                assert r.read() == b"gated" * 8
            # burst spent: the immediate second request must shed with
            # 503 + Retry-After through the worker's own gate (the
            # lead is unreachable, so a proxy fallback would 502)
            try:
                urllib.request.urlopen(url, timeout=10)
                raise AssertionError("second request was not shed")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert float(e.headers["Retry-After"]) > 0
            assert worker.admission.rejected == 1
        finally:
            worker.stop()

    def test_budget_splits_across_group(self, tmp_path):
        """Same convention as -serveProcs siblings: each member of a
        -workers group enforces rate/procs of the per-client budget."""
        worker = self._worker_with_admission(tmp_path, rate=8.0, procs=4)
        try:
            assert worker.admission.rate == pytest.approx(2.0)
        finally:
            worker.stop()

    def test_internal_listener_not_gated(self, tmp_path):
        """The lead↔worker release handshake must never be shed — a
        503 mid-handback would wedge write ownership."""
        vol = Volume(str(tmp_path), 9)
        vol.close()
        worker = VolumeReadWorker(
            [str(tmp_path)],
            host="127.0.0.1",
            port=free_port(),
            lead="127.0.0.1:1",
            shard_writes=True,
            writer_index=1,
            n_writers=2,
            internal_port=free_port(),
            admission_rate=1.0,
            admission_burst=1.0,
        )
        worker.start()
        try:
            assert worker._internal_server is not None
            assert worker._internal_server.admission is None
            for s in worker._servers:
                if s is not worker._internal_server:
                    assert s.admission is worker.admission
        finally:
            worker.stop()
