"""`volume -workers N` SO_REUSEPORT read workers (server/volume_workers.py).

The lead stays the single writer (the reference's per-volume write
ordering, volume_read_write.go:66); workers serve GET/HEAD from the
shared directories with `.idx` tail-replay freshness and proxy
everything else to the lead's internal listener.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.server.volume_workers import SharedReadVolume, VolumeReadWorker
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestSharedReadVolume:
    def _needle(self, nid: int, data: bytes) -> Needle:
        n = Needle(cookie=0x42, id=nid, data=data)
        n.name = b"w.bin"
        n.set_has_name()
        return n

    def test_sees_writes_made_after_open(self, tmp_path):
        owner = Volume(str(tmp_path), 5)
        owner.write_needle(self._needle(1, b"first"))
        reader = SharedReadVolume(str(tmp_path), 5)
        assert reader.read_needle(1, cookie=0x42).data == b"first"
        # writes landing AFTER the reader opened must become visible
        # (idx tail replay — read-your-writes across processes)
        owner.write_needle(self._needle(2, b"second"))
        assert reader.read_needle(2, cookie=0x42).data == b"second"
        # overwrite: the reader must serve the new version
        owner.write_needle(self._needle(1, b"first-v2"))
        assert reader.read_needle(1, cookie=0x42).data == b"first-v2"

    def test_sees_deletes(self, tmp_path):
        owner = Volume(str(tmp_path), 6)
        owner.write_needle(self._needle(1, b"doomed"))
        reader = SharedReadVolume(str(tmp_path), 6)
        assert reader.read_needle(1).data == b"doomed"
        owner.delete_needle(Needle(cookie=0x42, id=1))
        with pytest.raises(NeedleNotFound):
            reader.read_needle(1)

    def test_survives_vacuum_commit(self, tmp_path):
        owner = Volume(str(tmp_path), 7)
        for i in range(1, 6):
            owner.write_needle(self._needle(i, b"x%d" % i))
        owner.delete_needle(Needle(cookie=0x42, id=2))
        reader = SharedReadVolume(str(tmp_path), 7)
        assert reader.read_needle(3).data == b"x3"
        owner.compact()
        owner.commit_compact()
        # new inode pair: the reader reopens and keeps serving
        assert reader.read_needle(3).data == b"x3"
        with pytest.raises(NeedleNotFound):
            reader.read_needle(2)
        # post-vacuum writes flow through the reopened index
        owner.write_needle(self._needle(9, b"after-vacuum"))
        assert reader.read_needle(9).data == b"after-vacuum"


class TestVolumeReadWorker:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        mport, vport, wport = free_port(), free_port(), free_port()
        iport = free_port()
        master = MasterServer(port=mport)
        master.start()
        vdir = str(tmp_path_factory.mktemp("wvol"))
        lead = VolumeServer(
            [vdir],
            port=vport,
            master=f"127.0.0.1:{mport}",
            heartbeat_interval=0.2,
            internal_port=iport,
        )
        lead.start()
        deadline = time.time() + 20
        while time.time() < deadline and not master.topology.data_nodes():
            time.sleep(0.05)
        worker = VolumeReadWorker(
            [vdir],
            host="127.0.0.1",
            port=free_port(),  # its own shared-port stand-in
            lead=f"127.0.0.1:{iport}",
            worker_port=wport,
        )
        worker.start()
        yield master, lead, worker, mport, vport, wport
        worker.stop()
        lead.stop()
        master.stop()

    def _assign(self, mport):
        import json

        with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/dir/assign"
        ) as r:
            return json.load(r)

    def test_worker_serves_lead_writes(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{vport}/{a['fid']}?filename=t.txt",
            data=b"through the lead",
            method="POST",
        )
        urllib.request.urlopen(req).read()
        # read via the WORKER port: local fast path, not the lead
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            assert r.read() == b"through the lead"
            assert r.headers.get("ETag")

    def test_worker_proxies_writes_to_lead(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}",
            data=b"written via worker proxy",
            method="POST",
        )
        body = urllib.request.urlopen(req).read()
        assert b"eTag" in body
        # and the lead really owns it
        with urllib.request.urlopen(
            f"http://127.0.0.1:{vport}/{a['fid']}"
        ) as r:
            assert r.read() == b"written via worker proxy"

    def test_worker_read_your_write_after_proxy(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        req = urllib.request.Request(
            f"http://127.0.0.1:{wport}/{a['fid']}",
            data=b"immediately visible",
            method="POST",
        )
        urllib.request.urlopen(req).read()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            assert r.read() == b"immediately visible"

    def test_worker_proxies_deletes_and_sees_tombstone(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{wport}/{a['fid']}",
                data=b"doomed",
                method="POST",
            )
        ).read()
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{wport}/{a['fid']}", method="DELETE"
            )
        ).read()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{wport}/{a['fid']}")
        assert ei.value.code == 404

    def test_worker_proxies_status_pages(self, stack):
        master, lead, worker, mport, vport, wport = stack
        with urllib.request.urlopen(f"http://127.0.0.1:{wport}/status") as r:
            assert b"Volumes" in r.read()

    def test_worker_range_and_304(self, stack):
        master, lead, worker, mport, vport, wport = stack
        a = self._assign(mport)
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{vport}/{a['fid']}",
                data=b"0123456789",
                method="POST",
            )
        ).read()
        req = urllib.request.Request(f"http://127.0.0.1:{wport}/{a['fid']}")
        req.add_header("Range", "bytes=2-5")
        with urllib.request.urlopen(req) as r:
            assert r.status == 206 and r.read() == b"2345"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{wport}/{a['fid']}"
        ) as r:
            etag = r.headers["ETag"]
        req = urllib.request.Request(f"http://127.0.0.1:{wport}/{a['fid']}")
        req.add_header("If-None-Match", etag)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 304

    def test_concurrent_mixed_load(self, stack):
        """Writes proxied + reads served locally under concurrency —
        the worker must never serve stale or torn data."""
        master, lead, worker, mport, vport, wport = stack
        errors = []

        def one(i):
            try:
                a = self._assign(mport)
                payload = b"payload-%d" % i
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{wport}/{a['fid']}",
                        data=payload,
                        method="POST",
                    )
                ).read()
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{wport}/{a['fid']}"
                ) as r:
                    got = r.read()
                if got != payload:
                    errors.append((i, got, payload))
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(24)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]


class TestWorkersCli:
    """The real `volume -workers N` spawn path: a CLI lead brings up
    SO_REUSEPORT worker subprocesses sharing its port; fresh-connection
    reads spread across processes and writes land through whichever
    process accepts."""

    def test_cli_workers_share_port(self, tmp_path):
        import json
        import os
        import subprocess
        import sys

        mport, vport = free_port(), free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu", WEED_EC_CODEC="cpu")

        def spawn(*args):
            return subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "import jax; jax.config.update('jax_platforms', 'cpu');"
                    "from seaweedfs_tpu.__main__ import main; main()",
                    *args,
                ],
                env=env,
                cwd="/root/repo",
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )

        procs = [spawn("master", "-port", str(mport))]
        try:
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/stats/health", timeout=2
                    ).read()
                    break
                except OSError:
                    time.sleep(0.2)
            procs.append(
                spawn(
                    "volume",
                    "-port", str(vport),
                    "-mserver", f"127.0.0.1:{mport}",
                    "-dir", str(tmp_path),
                    "-max", "8",
                    "-workers", "3",
                )
            )
            # lead + 2 worker subprocesses all listening (workers take a
            # few seconds each: fresh interpreter + jax import)
            def assigned():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign", timeout=2
                ) as r:
                    return json.loads(r.read())

            deadline = time.time() + 60
            fid = None
            while time.time() < deadline:
                try:
                    a = assigned()
                    if "fid" in a:
                        fid = a["fid"]
                        break
                except OSError:
                    pass
                time.sleep(0.3)
            assert fid, "volume lead never registered"
            url = f"http://127.0.0.1:{vport}/{fid}"
            urllib.request.urlopen(
                urllib.request.Request(url, data=b"cli worker payload", method="POST"),
                timeout=10,
            ).read()
            # give worker subprocesses time to finish binding, then read
            # over MANY fresh connections: the kernel spreads them over
            # all SO_REUSEPORT listeners, so every process must serve
            deadline = time.time() + 45
            while time.time() < deadline:
                try:
                    ok = all(
                        urllib.request.urlopen(url, timeout=5).read()
                        == b"cli worker payload"
                        for _ in range(12)
                    )
                    if ok:
                        break
                except (OSError, AssertionError):
                    pass
                time.sleep(0.5)
            for _ in range(12):
                with urllib.request.urlopen(url, timeout=10) as r:
                    assert r.read() == b"cli worker payload"
            # delete propagates through whichever process accepts
            urllib.request.urlopen(
                urllib.request.Request(url, method="DELETE"), timeout=10
            ).read()
            for _ in range(6):
                with pytest.raises(urllib.error.HTTPError):
                    urllib.request.urlopen(url, timeout=10)
        finally:
            for p in reversed(procs):
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
