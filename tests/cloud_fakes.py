"""In-memory protocol fakes for the cloud replication sinks.

Each speaks exactly the REST surface its sink uses (tests drive the
real wire protocol over a real socket, offline):

  FakeGcs    GCS JSON API: media upload, objects list/delete
  FakeAzure  Azure Blob REST: Put/Delete Blob, List Blobs; validates
             the SharedKey signature with the same canonicalization
             the sink computes (self-consistency, not Azure itself)
  FakeB2     B2 native API: authorize_account, list_buckets,
             get_upload_url, upload, list_file_names,
             delete_file_version
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _FakeBase:
    page_size = 1000  # tests shrink this to exercise pagination

    def __init__(self):
        self.objects: dict[str, bytes] = {}
        self._server = ThreadingHTTPServer(
            ("127.0.0.1", 0), self._handler_class()
        )
        self.port = self._server.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class FakeGcs(_FakeBase):
    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                u = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(u.query))
                if u.path.startswith("/upload/storage/v1/b/"):
                    name = q["name"]
                    n = int(self.headers.get("Content-Length", "0"))
                    fake.objects[name] = self.rfile.read(n)
                    return self._json({"name": name})
                self._json({"error": "bad path"}, 404)

            def do_GET(self):
                u = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(u.query))
                if u.path.endswith("/o"):
                    prefix = q.get("prefix", "")
                    names = [
                        k for k in sorted(fake.objects) if k.startswith(prefix)
                    ]
                    start = int(q.get("pageToken", "0") or "0")
                    page = names[start : start + fake.page_size]
                    resp = {"items": [{"name": k} for k in page]}
                    if start + fake.page_size < len(names):
                        resp["nextPageToken"] = str(start + fake.page_size)
                    return self._json(resp)
                self._json({"error": "bad path"}, 404)

            def do_DELETE(self):
                u = urllib.parse.urlparse(self.path)
                name = urllib.parse.unquote(u.path.rsplit("/o/", 1)[-1])
                existed = fake.objects.pop(name, None)
                self._json({}, 204 if existed is not None else 404)

        return H


class FakeAzure(_FakeBase):
    def __init__(self, account: str, key_b64: str, container: str):
        self.account = account
        self.key = base64.b64decode(key_b64)
        self.container = container
        super().__init__()

    def _check_sig(self, handler, method, query, body_len, ctype) -> bool:
        auth = handler.headers.get("Authorization", "")
        if not auth.startswith(f"SharedKey {self.account}:"):
            return False
        headers = {
            k.lower(): v
            for k, v in handler.headers.items()
            if k.lower().startswith("x-ms-")
        }
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(headers.items())
        )
        # canonicalize the path AS SENT (percent-encoded) — the Azure
        # spec's rule, and what the sink signs
        path = urllib.parse.urlparse(handler.path).path
        canon_resource = f"/{self.account}{path}"
        for k in sorted(query):
            canon_resource += f"\n{k.lower()}:{query[k]}"
        string_to_sign = "\n".join(
            [method, "", "", str(body_len) if body_len else "", "",
             ctype, "", "", "", "", "", ""]
        ) + "\n" + canon_headers + canon_resource
        want = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        return auth == f"SharedKey {self.account}:{want}"

    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, status, body=b""):
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", "0"))
                data = self.rfile.read(n)
                q = dict(
                    urllib.parse.parse_qsl(
                        urllib.parse.urlparse(self.path).query
                    )
                )
                if not fake._check_sig(
                    self, "PUT", q, n,
                    self.headers.get("Content-Type", ""),
                ):
                    return self._reply(403, b"bad signature")
                name = urllib.parse.unquote(
                    urllib.parse.urlparse(self.path).path
                ).split(f"/{fake.container}/", 1)[-1]
                fake.objects[name] = data
                self._reply(201)

            def do_DELETE(self):
                q = dict(
                    urllib.parse.parse_qsl(
                        urllib.parse.urlparse(self.path).query
                    )
                )
                if not fake._check_sig(self, "DELETE", q, 0, ""):
                    return self._reply(403, b"bad signature")
                name = urllib.parse.unquote(
                    urllib.parse.urlparse(self.path).path
                ).split(f"/{fake.container}/", 1)[-1]
                existed = fake.objects.pop(name, None)
                self._reply(202 if existed is not None else 404)

            def do_GET(self):
                u = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(u.query))
                if not fake._check_sig(self, "GET", q, 0, ""):
                    return self._reply(403, b"bad signature")
                if q.get("comp") == "list":
                    prefix = q.get("prefix", "")
                    marker = q.get("marker", "")
                    names = [
                        k
                        for k in sorted(fake.objects)
                        if k.startswith(prefix) and k > marker
                    ]
                    from xml.sax.saxutils import escape

                    page = names[: fake.page_size]
                    blobs = "".join(
                        f"<Blob><Name>{escape(k)}</Name></Blob>" for k in page
                    )
                    nxt = (
                        f"<NextMarker>{escape(page[-1])}</NextMarker>"
                        if len(names) > fake.page_size
                        else ""
                    )
                    xml = (
                        "<?xml version='1.0'?><EnumerationResults>"
                        f"<Blobs>{blobs}</Blobs>{nxt}</EnumerationResults>"
                    )
                    return self._reply(200, xml.encode())
                self._reply(404)

        return H


class FakeB2(_FakeBase):
    def __init__(self, key_id: str, app_key: str, bucket: str):
        self.key_id = key_id
        self.app_key = app_key
        self.bucket_name = bucket
        self.bucket_id = "bkt001"
        self._next_id = 0
        # B2 keeps every uploaded version: name -> [(fileId, data)],
        # newest last; `objects` mirrors the latest-visible view
        self.versions: dict[str, list[tuple[str, bytes]]] = {}
        super().__init__()

    def _refresh_latest(self, name: str) -> None:
        vs = self.versions.get(name)
        if vs:
            self.objects[name] = vs[-1][1]
        else:
            self.versions.pop(name, None)
            self.objects.pop(name, None)

    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.endswith("/b2_authorize_account"):
                    basic = base64.b64encode(
                        f"{fake.key_id}:{fake.app_key}".encode()
                    ).decode()
                    if self.headers.get("Authorization") != f"Basic {basic}":
                        return self._json({"code": "unauthorized"}, 401)
                    return self._json(
                        {
                            "apiUrl": fake.endpoint,
                            "authorizationToken": "tok123",
                            "accountId": "acct",
                        }
                    )
                self._json({"code": "not_found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                data = self.rfile.read(n)
                if self.path.endswith("/b2_upload"):
                    if self.headers.get("Authorization") != "uptok":
                        return self._json({"code": "unauthorized"}, 401)
                    name = urllib.parse.unquote(
                        self.headers["X-Bz-File-Name"]
                    )
                    if (
                        hashlib.sha1(data).hexdigest()
                        != self.headers.get("X-Bz-Content-Sha1")
                    ):
                        return self._json({"code": "bad_hash"}, 400)
                    fake._next_id += 1
                    fid = f"f{fake._next_id:06d}"
                    fake.versions.setdefault(name, []).append((fid, data))
                    fake._refresh_latest(name)
                    return self._json({"fileId": fid, "fileName": name})
                if self.headers.get("Authorization") != "tok123":
                    return self._json({"code": "unauthorized"}, 401)
                payload = json.loads(data or b"{}")
                if self.path.endswith("/b2_list_buckets"):
                    return self._json(
                        {
                            "buckets": [
                                {
                                    "bucketId": fake.bucket_id,
                                    "bucketName": fake.bucket_name,
                                }
                            ]
                        }
                    )
                if self.path.endswith("/b2_get_upload_url"):
                    return self._json(
                        {
                            "uploadUrl": f"{fake.endpoint}/b2_upload",
                            "authorizationToken": "uptok",
                        }
                    )
                if self.path.endswith("/b2_list_file_names"):
                    prefix = payload.get("prefix", "")
                    start = payload.get("startFileName", "")
                    names = [
                        k
                        for k in sorted(fake.objects)
                        if k.startswith(prefix) and k >= start
                    ]
                    page = names[: fake.page_size]
                    files = [
                        {"fileName": k, "fileId": fake.versions[k][-1][0]}
                        for k in page
                    ]
                    nxt = (
                        names[fake.page_size]
                        if len(names) > fake.page_size
                        else None
                    )
                    return self._json({"files": files, "nextFileName": nxt})
                if self.path.endswith("/b2_list_file_versions"):
                    prefix = payload.get("prefix", "")
                    files = [
                        {"fileName": k, "fileId": fid}
                        for k in sorted(fake.versions)
                        if k.startswith(prefix)
                        for fid, _ in fake.versions[k]
                    ]
                    return self._json({"files": files, "nextFileName": None})
                if self.path.endswith("/b2_delete_file_version"):
                    name = payload["fileName"]
                    fid = payload["fileId"]
                    vs = fake.versions.get(name, [])
                    fake.versions[name] = [v for v in vs if v[0] != fid]
                    if not fake.versions[name]:
                        del fake.versions[name]
                    fake._refresh_latest(name)
                    return self._json({})
                self._json({"code": "not_found"}, 404)

        return H


class FakeEtcd(_FakeBase):
    """etcd v3 grpc-gateway KV subset: range / put / txn with VALUE and
    CREATE compares — what EtcdSequencer speaks."""

    def __init__(self):
        self.kv: dict[str, str] = {}  # b64 key -> b64 value
        self.create_rev: dict[str, int] = {}
        self._rev = 0
        self._lock = threading.Lock()
        super().__init__()

    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(n) or b"{}")
                with fake._lock:
                    if self.path.endswith("/kv/range"):
                        key = base64.b64decode(payload["key"])
                        end = payload.get("range_end")
                        if end is None:
                            b64k = payload["key"]
                            kvs = (
                                [{"key": b64k, "value": fake.kv[b64k]}]
                                if b64k in fake.kv
                                else []
                            )
                            return self._json({"kvs": kvs})
                        end_b = base64.b64decode(end)
                        hits = sorted(
                            (base64.b64decode(k), v)
                            for k, v in fake.kv.items()
                            if key <= base64.b64decode(k) < end_b
                        )
                        if payload.get("sort_order") == "DESCEND":
                            hits.reverse()
                        limit = int(payload.get("limit", 0) or 0)
                        if limit:
                            hits = hits[:limit]
                        return self._json(
                            {
                                "kvs": [
                                    {
                                        "key": base64.b64encode(k).decode(),
                                        "value": v,
                                    }
                                    for k, v in hits
                                ]
                            }
                        )
                    if self.path.endswith("/kv/deleterange"):
                        key = base64.b64decode(payload["key"])
                        end = payload.get("range_end")
                        if end is None:
                            fake.kv.pop(payload["key"], None)
                            fake.create_rev.pop(payload["key"], None)
                            return self._json({})
                        end_b = base64.b64decode(end)
                        for k in [
                            k
                            for k in fake.kv
                            if key <= base64.b64decode(k) < end_b
                        ]:
                            del fake.kv[k]
                            fake.create_rev.pop(k, None)
                        return self._json({})
                    if self.path.endswith("/kv/put"):
                        fake._put(payload["key"], payload["value"])
                        return self._json({})
                    if self.path.endswith("/kv/txn"):
                        ok = all(
                            fake._compare(c) for c in payload.get("compare", [])
                        )
                        if ok:
                            for op in payload.get("success", []):
                                put = op.get("requestPut")
                                if put:
                                    fake._put(put["key"], put["value"])
                        return self._json({"succeeded": ok})
                self._json({"error": "bad path"}, 404)

        return H

    def _put(self, key: str, value: str) -> None:
        self._rev += 1
        if key not in self.kv:
            self.create_rev[key] = self._rev
        self.kv[key] = value

    def _compare(self, c: dict) -> bool:
        key = c["key"]
        if c.get("target") == "CREATE":
            want = int(c.get("createRevision", c.get("create_revision", 0)))
            return self.create_rev.get(key, 0) == want
        if c.get("target") == "VALUE":
            return self.kv.get(key) == c.get("value")
        return False


class FakeRedis:
    """Minimal RESP2 server over a dict: the command subset the redis
    filer store speaks (SET GET DEL SADD SREM SMEMBERS PING)."""

    def __init__(self):
        import socketserver

        self.strings: dict[bytes, bytes] = {}
        self.sets: dict[bytes, set[bytes]] = {}
        self._lock = threading.Lock()
        fake = self

        class H(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    if not line.startswith(b"*"):
                        return
                    argc = int(line[1:].strip())
                    args = []
                    for _ in range(argc):
                        hdr = self.rfile.readline()
                        n = int(hdr[1:].strip())
                        args.append(self.rfile.read(n + 2)[:-2])
                    self.wfile.write(fake._dispatch(args))
                    self.wfile.flush()

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.address = f"127.0.0.1:{self.port}"

    def _dispatch(self, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        with self._lock:
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"SET":
                self.strings[args[1]] = args[2]
                return b"+OK\r\n"
            if cmd == b"GET":
                v = self.strings.get(args[1])
                if v is None:
                    return b"$-1\r\n"
                return b"$%d\r\n%s\r\n" % (len(v), v)
            if cmd == b"DEL":
                n = 1 if self.strings.pop(args[1], None) is not None else 0
                return b":%d\r\n" % n
            if cmd == b"SADD":
                s = self.sets.setdefault(args[1], set())
                added = sum(1 for m in args[2:] if m not in s)
                s.update(args[2:])
                return b":%d\r\n" % added
            if cmd == b"SREM":
                s = self.sets.get(args[1], set())
                removed = sum(1 for m in args[2:] if m in s)
                s.difference_update(args[2:])
                return b":%d\r\n" % removed
            if cmd == b"SMEMBERS":
                s = sorted(self.sets.get(args[1], set()))
                out = b"*%d\r\n" % len(s)
                for m in s:
                    out += b"$%d\r\n%s\r\n" % (len(m), m)
                return out
        return b"-ERR unknown command\r\n"

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class FakeSqs(_FakeBase):
    """AWS SQS Query-protocol subset: GetQueueUrl + SendMessage.
    Validates the SigV4 signature with the same derivation the queue
    computes (self-consistency)."""

    def __init__(self, access_key: str, secret_key: str, region: str, queue: str):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.queue_name = queue
        self.messages: list[tuple[str, str]] = []  # (key, body)
        super().__init__()

    def _check_sig(self, handler, body: bytes) -> bool:
        from seaweedfs_tpu.s3api.auth import derive_signing_key

        auth = handler.headers.get("Authorization", "")
        if f"Credential={self.access_key}/" not in auth:
            return False
        amz_date = handler.headers.get("x-amz-date", "")
        date = amz_date[:8]
        headers = {
            "content-type": handler.headers.get("Content-Type", ""),
            "host": handler.headers.get("Host", ""),
            "x-amz-date": amz_date,
        }
        signed = sorted(headers)
        canonical = "\n".join(
            [
                "POST", "/", "",
                "".join(f"{k}:{headers[k]}\n" for k in signed),
                ";".join(signed),
                hashlib.sha256(body).hexdigest(),
            ]
        )
        scope = f"{date}/{self.region}/sqs/aws4_request"
        sts = "\n".join(
            ["AWS4-HMAC-SHA256", amz_date, scope,
             hashlib.sha256(canonical.encode()).hexdigest()]
        )
        want = hmac.new(
            derive_signing_key(self.secret_key, date, self.region, "sqs"),
            sts.encode(), hashlib.sha256,
        ).hexdigest()
        return f"Signature={want}" in auth

    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _xml(self, body: str, status=200):
                b = body.encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(b)))
                self.end_headers()
                self.wfile.write(b)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                if not fake._check_sig(self, body):
                    return self._xml("<Error>SignatureDoesNotMatch</Error>", 403)
                params = dict(urllib.parse.parse_qsl(body.decode()))
                action = params.get("Action")
                if action == "GetQueueUrl":
                    if params.get("QueueName") != fake.queue_name:
                        return self._xml(
                            "<Error><Code>AWS.SimpleQueueService."
                            "NonExistentQueue</Code></Error>", 400,
                        )
                    return self._xml(
                        "<GetQueueUrlResponse><GetQueueUrlResult><QueueUrl>"
                        f"{fake.endpoint}/123/{fake.queue_name}"
                        "</QueueUrl></GetQueueUrlResult></GetQueueUrlResponse>"
                    )
                if action == "SendMessage":
                    key = params.get(
                        "MessageAttribute.1.Value.StringValue", ""
                    )
                    fake.messages.append((key, params.get("MessageBody", "")))
                    return self._xml(
                        "<SendMessageResponse><SendMessageResult>"
                        "<MessageId>m1</MessageId>"
                        "</SendMessageResult></SendMessageResponse>"
                    )
                self._xml("<Error>bad action</Error>", 400)

        return H


class FakePubSub(_FakeBase):
    """Google Pub/Sub REST publish subset."""

    def __init__(self, project: str, topic: str):
        self.path = f"/v1/projects/{project}/topics/{topic}:publish"
        self.messages: list[tuple[str, bytes]] = []  # (key, data)
        super().__init__()

    def _handler_class(self):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                b = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(b)))
                self.end_headers()
                self.wfile.write(b)

            def do_GET(self):
                # topic existence probe (GET /v1/projects/p/topics/t)
                if self.path == fake.path.removesuffix(":publish"):
                    return self._json({"name": self.path[4:]})
                self._json({"error": {"code": 404}}, 404)

            def do_PUT(self):
                # topic auto-create (reference: topic.Exists → CreateTopic)
                n = int(self.headers.get("Content-Length", "0"))
                self.rfile.read(n)
                if self.path == fake.path.removesuffix(":publish"):
                    return self._json({"name": self.path[4:]})
                self._json({"error": {"code": 404}}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path != fake.path:
                    return self._json({"error": {"code": 404}}, 404)
                ids = []
                for m in payload.get("messages", []):
                    data = base64.b64decode(m.get("data", ""))
                    key = m.get("attributes", {}).get("key", "")
                    fake.messages.append((key, data))
                    ids.append(str(len(fake.messages)))
                return self._json({"messageIds": ids})

        return H


class FakeCassandra:
    """CQL v4 binary-protocol subset: STARTUP/READY + the five
    filemeta statements (native frames both directions, so the store's
    framing, value encoding, and rows decoding are all exercised)."""

    def __init__(self, keyspace: str = "seaweedfs"):
        import re
        import socketserver
        import struct

        self.keyspace = keyspace
        # (directory, name) -> meta, kept sorted per directory on read
        self.rows: dict[tuple[str, str], bytes] = {}
        self._lock = threading.Lock()
        fake = self
        _re, _struct = re, struct

        OP_ERROR, OP_READY, OP_QUERY, OP_RESULT = 0x00, 0x02, 0x07, 0x08

        class H(socketserver.StreamRequestHandler):
            def _frame(self, stream, opcode, body):
                self.wfile.write(
                    _struct.pack(">BBhBi", 0x84, 0, stream, opcode, len(body))
                    + body
                )
                self.wfile.flush()

            def _rows(self, stream, cols, rows):
                # metadata with global_tables_spec; all cols varchar/blob
                body = _struct.pack(">i", 0x0002)  # kind = Rows
                body += _struct.pack(">ii", 0x0001, len(cols))
                for s in (fake.keyspace, "filemeta"):
                    b = s.encode()
                    body += _struct.pack(">H", len(b)) + b
                for cname, ctype in cols:
                    b = cname.encode()
                    body += _struct.pack(">H", len(b)) + b
                    body += _struct.pack(">h", ctype)
                body += _struct.pack(">i", len(rows))
                for row in rows:
                    for v in row:
                        body += _struct.pack(">i", len(v)) + v
                self._frame(stream, OP_RESULT, body)

            def handle(self):
                while True:
                    hdr = self.rfile.read(9)
                    if len(hdr) < 9:
                        return
                    _v, _f, stream, opcode, length = _struct.unpack(
                        ">BBhBi", hdr
                    )
                    body = self.rfile.read(length)
                    if opcode == 0x01:  # STARTUP
                        self._frame(stream, OP_READY, b"")
                        continue
                    if opcode != OP_QUERY:
                        return
                    off = 0
                    (qlen,) = _struct.unpack_from(">i", body, off)
                    off += 4
                    cql = body[off : off + qlen].decode()
                    off += qlen
                    off += 2  # consistency
                    (flags,) = _struct.unpack_from(">B", body, off)
                    off += 1
                    values = []
                    if flags & 0x01:
                        (n,) = _struct.unpack_from(">H", body, off)
                        off += 2
                        for _ in range(n):
                            (vlen,) = _struct.unpack_from(">i", body, off)
                            off += 4
                            values.append(body[off : off + vlen])
                            off += max(vlen, 0)
                    self._dispatch(stream, cql.strip(), values)

            def _void(self, stream):
                self._frame(stream, OP_RESULT, _struct.pack(">i", 0x0001))

            def _dispatch(self, stream, cql, values):
                up = cql.upper()
                with fake._lock:
                    if up.startswith("USE "):
                        name = cql.split()[1].strip().encode()
                        body = _struct.pack(">i", 0x0003)
                        body += _struct.pack(">H", len(name)) + name
                        return self._frame(stream, OP_RESULT, body)
                    if up.startswith("INSERT INTO FILEMETA"):
                        d, name, meta = (
                            values[0].decode(),
                            values[1].decode(),
                            values[2],
                        )
                        fake.rows[(d, name)] = meta
                        return self._void(stream)
                    if up.startswith("SELECT META"):
                        d, name = values[0].decode(), values[1].decode()
                        meta = fake.rows.get((d, name))
                        rows = [[meta]] if meta is not None else []
                        return self._rows(
                            stream, [("meta", 0x0003)], rows
                        )
                    if up.startswith("DELETE FROM FILEMETA WHERE DIRECTORY=? AND NAME=?"):
                        d, name = values[0].decode(), values[1].decode()
                        fake.rows.pop((d, name), None)
                        return self._void(stream)
                    if up.startswith("DELETE FROM FILEMETA WHERE DIRECTORY=?"):
                        d = values[0].decode()
                        for k in [k for k in fake.rows if k[0] == d]:
                            del fake.rows[k]
                        return self._void(stream)
                    if up.startswith("SELECT NAME, META"):
                        d = values[0].decode()
                        start = values[1].decode()
                        (limit,) = _struct.unpack(">i", values[2])
                        inclusive = "NAME>=?" in up.replace(" ", "")
                        names = sorted(
                            n for (dd, n) in fake.rows if dd == d
                        )
                        out = []
                        for n in names:
                            if inclusive and n < start:
                                continue
                            if not inclusive and n <= start:
                                continue
                            out.append(
                                [n.encode(), fake.rows[(d, n)]]
                            )
                            if len(out) >= limit:
                                break
                        return self._rows(
                            stream,
                            [("name", 0x000D), ("meta", 0x0003)],
                            out,
                        )
                # unknown statement
                err = _struct.pack(">i", 0x2200)
                msg = b"unknown statement"
                err += _struct.pack(">H", len(msg)) + msg
                self._frame(stream, OP_ERROR, err)

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.address = f"127.0.0.1:{self.port}"

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class FakePostgres:
    """PostgreSQL protocol-v3 subset: md5 auth handshake, the extended
    query protocol (Parse/Bind/Execute/Sync, binary formats) for the
    POSTGRES_DIALECT statements, and simple Query for BEGIN/COMMIT/
    ROLLBACK (snapshot-restore transactions)."""

    def __init__(self, user="seaweedfs", password="", database="seaweedfs"):
        import socketserver
        import struct as _struct

        self.user, self.password, self.database = user, password, database
        # (directory, name) -> (dirhash, meta)
        self.rows: dict[tuple[str, str], tuple[int, bytes]] = {}
        self._lock = threading.Lock()
        fake = self

        class H(socketserver.StreamRequestHandler):
            def _msg(self, kind: bytes, body: bytes = b""):
                self.wfile.write(kind + _struct.pack(">i", len(body) + 4) + body)

            def _ready(self):
                self._msg(b"Z", b"I")
                self.wfile.flush()

            def _error(self, sqlstate, message):
                body = b"S" + b"ERROR\0"
                body += b"C" + sqlstate.encode() + b"\0"
                body += b"M" + message.encode() + b"\0\0"
                self._msg(b"E", body)

            def handle(self):
                # startup
                (length,) = _struct.unpack(">i", self.rfile.read(4))
                self.rfile.read(length - 4)  # protocol + params
                salt = b"s4lt"
                self._msg(b"R", _struct.pack(">i", 5) + salt)  # md5
                self.wfile.flush()
                kind = self.rfile.read(1)
                (n,) = _struct.unpack(">i", self.rfile.read(4))
                pw = self.rfile.read(n - 4).rstrip(b"\0").decode()
                inner = hashlib.md5(
                    (fake.password + fake.user).encode()
                ).hexdigest()
                want = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
                if kind != b"p" or pw != want:
                    self._error("28P01", "password authentication failed")
                    self.wfile.flush()
                    return
                self._msg(b"R", _struct.pack(">i", 0))
                self._ready()

                stmt = ""
                params: list[bytes | None] = []
                snapshot = None
                while True:
                    kind = self.rfile.read(1)
                    if not kind:
                        return
                    (n,) = _struct.unpack(">i", self.rfile.read(4))
                    body = self.rfile.read(n - 4)
                    if kind == b"Q":
                        sql = body.rstrip(b"\0").decode().strip().upper()
                        with fake._lock:
                            if sql == "BEGIN":
                                snapshot = dict(fake.rows)
                            elif sql.startswith("ROLLBACK TO"):
                                pass  # statement-level recovery: no-op
                            elif sql == "ROLLBACK":
                                if snapshot is not None:
                                    fake.rows.clear()
                                    fake.rows.update(snapshot)
                                snapshot = None
                            elif sql == "COMMIT":
                                snapshot = None
                        self._msg(b"C", b"OK\0")
                        self._ready()
                    elif kind == b"P":
                        rest = body[1:]  # unnamed stmt \0 prefix
                        stmt = rest.split(b"\0", 1)[0].decode()
                        self._msg(b"1")
                    elif kind == b"B":
                        r = body[2:]  # unnamed portal + stmt
                        (nfmt,) = _struct.unpack(">h", r[:2])
                        r = r[2 + 2 * nfmt :]
                        (nparams,) = _struct.unpack(">h", r[:2])
                        r = r[2:]
                        params = []
                        for _ in range(nparams):
                            (ln,) = _struct.unpack(">i", r[:4])
                            r = r[4:]
                            if ln < 0:
                                params.append(None)
                            else:
                                params.append(r[:ln])
                                r = r[ln:]
                        self._msg(b"2")
                    elif kind == b"E":
                        err = fake._execute(self, stmt, params)
                        if err:
                            self._error(*err)
                    elif kind == b"S":
                        self._ready()
                    else:
                        return

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.address = f"127.0.0.1:{self.port}"

    def _execute(self, h, stmt: str, params):
        import struct as _struct

        from seaweedfs_tpu.filer.abstract_sql import POSTGRES_DIALECT as D

        def text(i):
            return params[i].decode()

        def i64(i):
            return _struct.unpack(">q", params[i])[0]

        def rowmsg(cols):
            body = _struct.pack(">h", len(cols))
            for v in cols:
                body += _struct.pack(">i", len(v)) + v
            h._msg(b"D", body)

        with self._lock:
            if stmt == D.create_table or stmt.upper().startswith("CREATE TABLE"):
                h._msg(b"C", b"CREATE TABLE\0")
                return None
            if stmt.upper().startswith("SAVEPOINT"):
                h._msg(b"C", b"SAVEPOINT\0")
                return None
            if stmt.upper().startswith("RELEASE"):
                h._msg(b"C", b"RELEASE\0")
                return None
            if stmt == D.insert:
                key = (text(2), text(1))
                if key in self.rows:
                    return ("23505", "duplicate key value")
                self.rows[key] = (i64(0), params[3])
                h._msg(b"C", b"INSERT 0 1\0")
                return None
            if stmt == D.update:
                key = (text(3), text(2))
                if key in self.rows:
                    self.rows[key] = (i64(1), params[0])
                h._msg(b"C", b"UPDATE 1\0")
                return None
            if stmt == D.find:
                key = (text(2), text(1))
                hit = self.rows.get(key)
                if hit is not None:
                    rowmsg([hit[1]])
                h._msg(b"C", b"SELECT\0")
                return None
            if stmt == D.delete:
                self.rows.pop((text(2), text(1)), None)
                h._msg(b"C", b"DELETE 1\0")
                return None
            if stmt == D.delete_folder_children:
                d = text(1)
                for k in [k for k in self.rows if k[0] == d]:
                    del self.rows[k]
                h._msg(b"C", b"DELETE\0")
                return None
            if stmt in (D.list_exclusive, D.list_inclusive):
                d, start = text(2), text(1)
                limit = i64(3)
                inclusive = stmt == D.list_inclusive
                names = sorted(n for (dd, n) in self.rows if dd == d)
                emitted = 0
                for n in names:
                    if inclusive and n < start:
                        continue
                    if not inclusive and n <= start:
                        continue
                    rowmsg([n.encode(), self.rows[(d, n)][1]])
                    emitted += 1
                    if emitted >= limit:
                        break
                h._msg(b"C", b"SELECT\0")
                return None
        return ("42601", f"unknown statement {stmt[:60]!r}")

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class FakeMysql:
    """MySQL client/server protocol subset: handshake v10 with
    mysql_native_password validation, COM_STMT_PREPARE/EXECUTE with
    binary rows for the MYSQL_DIALECT statements, COM_QUERY for
    BEGIN/COMMIT/ROLLBACK (snapshot transactions) and DDL."""

    def __init__(self, user="seaweedfs", password="", database="seaweedfs"):
        import socketserver
        import struct as _struct

        self.user, self.password = user, password
        self.rows: dict[tuple[str, str], tuple[int, bytes]] = {}
        self._lock = threading.Lock()
        fake = self

        def lenenc(n):
            if n < 0xFB:
                return bytes([n])
            if n < 1 << 16:
                return b"\xfc" + _struct.pack("<H", n)
            return b"\xfd" + _struct.pack("<I", n)[:3]

        class H(socketserver.StreamRequestHandler):
            def _send(self, payload):
                self.wfile.write(
                    len(payload).to_bytes(3, "little")
                    + bytes([self.seq])
                    + payload
                )
                self.seq += 1
                self.wfile.flush()

            def _read(self):
                hdr = self.rfile.read(4)
                if len(hdr) < 4:
                    return None
                self.seq = hdr[3] + 1
                return self.rfile.read(int.from_bytes(hdr[:3], "little"))

            def _ok(self):
                self._send(b"\x00\x00\x00\x02\x00\x00\x00")

            def _err(self, errno, msg):
                self._send(
                    b"\xff"
                    + _struct.pack("<H", errno)
                    + b"#42000"
                    + msg.encode()
                )

            def _eof(self):
                self._send(b"\xfe\x00\x00\x02\x00")

            def _coldef(self, name, ctype):
                d = b""
                for part in (b"def", b"db", b"t", b"t", name.encode(), name.encode()):
                    d += lenenc(len(part)) + part
                d += lenenc(0x0C)
                d += _struct.pack("<HIBHB2x", 0x21, 1024, ctype, 0, 0)
                self._send(d)

            def handle(self):
                import os as _os

                self.seq = 0
                salt = _os.urandom(8) + _os.urandom(12)
                greet = b"\x0a" + b"5.7-fake\0" + _struct.pack("<I", 1)
                greet += salt[:8] + b"\0"
                greet += _struct.pack("<H", 0xFFFF)  # caps low
                greet += b"\x21" + _struct.pack("<H", 2)
                greet += _struct.pack("<H", 0xFFFF)  # caps high
                greet += bytes([21]) + b"\0" * 10
                greet += salt[8:20] + b"\0"
                greet += b"mysql_native_password\0"
                self._send(greet)
                resp = self._read()
                if resp is None:
                    return
                # parse user + token
                off = 4 + 4 + 1 + 23
                end = resp.index(0, off)
                user = resp[off:end].decode()
                off = end + 1
                tlen = resp[off]
                token = resp[off + 1 : off + 1 + tlen]
                from seaweedfs_tpu.filer.mysql_driver import _scramble_native

                want = _scramble_native(fake.password, salt[:20])
                if user != fake.user or token != want:
                    self._err(1045, "Access denied")
                    return
                self._ok()

                stmts: dict[int, str] = {}
                next_id = 1
                snapshot = None
                while True:
                    pkt = self._read()
                    if pkt is None:
                        return
                    cmd = pkt[0]
                    if cmd == 0x03:  # COM_QUERY
                        sql = pkt[1:].decode().strip().upper()
                        with fake._lock:
                            if sql == "BEGIN":
                                snapshot = dict(fake.rows)
                            elif sql == "ROLLBACK":
                                if snapshot is not None:
                                    fake.rows.clear()
                                    fake.rows.update(snapshot)
                                snapshot = None
                            elif sql == "COMMIT":
                                snapshot = None
                        self._ok()
                    elif cmd == 0x16:  # COM_STMT_PREPARE
                        sql = pkt[1:].decode()
                        sid = next_id
                        next_id += 1
                        stmts[sid] = sql
                        nparams = sql.count("?")
                        self._send(
                            b"\x00"
                            + _struct.pack("<IHH", sid, 0, nparams)
                            + b"\x00" + _struct.pack("<H", 0)
                        )
                        for _ in range(nparams):
                            self._coldef("?", 0xFD)
                        if nparams:
                            self._eof()
                    elif cmd == 0x17:  # COM_STMT_EXECUTE
                        sid = _struct.unpack("<I", pkt[1:5])[0]
                        sql = stmts.get(sid, "")
                        nparams = sql.count("?")
                        off = 10
                        nb = (nparams + 7) // 8
                        null_bm = pkt[off : off + nb]
                        off += nb
                        params = []
                        if nparams:
                            bound = pkt[off]
                            off += 1
                            types = []
                            if bound:
                                for _ in range(nparams):
                                    types.append(pkt[off])
                                    off += 2
                            for i in range(nparams):
                                if null_bm[i // 8] & (1 << (i % 8)):
                                    params.append(None)
                                    continue
                                t = types[i]
                                if t == 0x08:  # LONGLONG
                                    params.append(
                                        _struct.unpack(
                                            "<q", pkt[off : off + 8]
                                        )[0]
                                    )
                                    off += 8
                                else:  # lenenc bytes
                                    first = pkt[off]
                                    off += 1
                                    if first < 0xFB:
                                        n = first
                                    elif first == 0xFC:
                                        n = _struct.unpack(
                                            "<H", pkt[off : off + 2]
                                        )[0]
                                        off += 2
                                    else:
                                        n = int.from_bytes(
                                            pkt[off : off + 3], "little"
                                        )
                                        off += 3
                                    params.append(pkt[off : off + n])
                                    off += n
                        err = fake._execute(self, lenenc, sql, params)
                        if err:
                            self._err(*err)
                    elif cmd == 0x19:  # COM_STMT_CLOSE (no response)
                        pass
                    else:
                        self._ok()

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.address = f"127.0.0.1:{self.port}"

    def _execute(self, h, lenenc, sql, params):
        import struct as _struct

        from seaweedfs_tpu.filer.abstract_sql import MYSQL_DIALECT as D

        def q(stmt):
            return stmt.replace("%s", "?")

        def text(i):
            return params[i].decode()

        def binrow(cols):
            # binary row: 0x00 header + null bitmap (offset 2) + values
            nb = (len(cols) + 9) // 8
            body = b"\x00" + b"\x00" * nb
            for v in cols:
                if isinstance(v, int):
                    body += _struct.pack("<q", v)
                else:
                    body += lenenc(len(v)) + v
            h._send(body)

        def send_rows(col_defs, rows):
            h._send(lenenc(len(col_defs)))
            for name, ctype in col_defs:
                h._coldef(name, ctype)
            h._eof()
            for row in rows:
                binrow(row)
            h._eof()

        with self._lock:
            if sql.upper().startswith("CREATE TABLE"):
                h._ok()
                return None
            if sql == q(D.insert):
                key = (text(2), text(1))
                if key in self.rows:
                    return (1062, "Duplicate entry")
                self.rows[key] = (params[0], params[3])
                h._ok()
                return None
            if sql == q(D.update):
                key = (text(3), text(2))
                if key in self.rows:
                    self.rows[key] = (params[1], params[0])
                h._ok()
                return None
            if sql == q(D.find):
                hit = self.rows.get((text(2), text(1)))
                send_rows(
                    [("meta", 0xFC)], [[hit[1]]] if hit is not None else []
                )
                return None
            if sql == q(D.delete):
                self.rows.pop((text(2), text(1)), None)
                h._ok()
                return None
            if sql == q(D.delete_folder_children):
                d = text(1)
                for k in [k for k in self.rows if k[0] == d]:
                    del self.rows[k]
                h._ok()
                return None
            if sql in (q(D.list_exclusive), q(D.list_inclusive)):
                d, start = text(2), text(1)
                limit = params[3]
                inclusive = sql == q(D.list_inclusive)
                names = sorted(n for (dd, n) in self.rows if dd == d)
                out = []
                for n in names:
                    if inclusive and n < start:
                        continue
                    if not inclusive and n <= start:
                        continue
                    out.append([n.encode(), self.rows[(d, n)][1]])
                    if len(out) >= limit:
                        break
                send_rows([("name", 0xFD), ("meta", 0xFC)], out)
                return None
        return (1064, f"unknown statement {sql[:60]!r}")

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class FakeTikv:
    """One-region TiKV + PD on a single gRPC port: serves the pdpb.PD
    routing verbs (GetMembers/GetRegion/GetStore) and the tikvpb.Tikv
    raw-KV verbs against an in-memory ordered map — the offline stand-in
    for a real PD+TiKV deployment (filer/tikv_store.py client)."""

    CLUSTER_ID = 7881
    REGION_ID = 2
    STORE_ID = 1
    PEER_ID = 3

    def __init__(self):
        import grpc
        from concurrent import futures as _futures

        from seaweedfs_tpu.pb import rpc as _rpc

        self.kv: dict[bytes, bytes] = {}
        self.epoch_version = 1  # bump to force client region refresh
        self.fail_next_with_region_error = 0  # injected staleness
        self._server = grpc.server(_futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers(
            (
                _rpc.servicer_handler(_rpc.PD_SERVICE, _rpc.PD_METHODS, self),
                _rpc.servicer_handler(_rpc.TIKV_SERVICE, _rpc.TIKV_METHODS, self),
            )
        )
        self._port = self._server.add_insecure_port("127.0.0.1:0")
        self.address = f"127.0.0.1:{self._port}"

    # --- pdpb.PD ----------------------------------------------------------
    def _t(self):
        from seaweedfs_tpu.pb import tikv_pb2 as t

        return t

    def GetMembers(self, req, context):
        t = self._t()
        m = t.Member(
            name="pd-fake", member_id=1, client_urls=[f"http://{self.address}"]
        )
        return t.GetMembersResponse(
            header=t.ResponseHeader(cluster_id=self.CLUSTER_ID),
            members=[m],
            leader=m,
        )

    def _region(self):
        t = self._t()
        return t.Region(
            id=self.REGION_ID,
            start_key=b"",
            end_key=b"",
            region_epoch=t.RegionEpoch(conf_ver=1, version=self.epoch_version),
            peers=[t.Peer(id=self.PEER_ID, store_id=self.STORE_ID)],
        )

    def GetRegion(self, req, context):
        t = self._t()
        return t.GetRegionResponse(
            header=t.ResponseHeader(cluster_id=self.CLUSTER_ID),
            region=self._region(),
            leader=t.Peer(id=self.PEER_ID, store_id=self.STORE_ID),
        )

    def GetStore(self, req, context):
        t = self._t()
        return t.GetStoreResponse(
            header=t.ResponseHeader(cluster_id=self.CLUSTER_ID),
            store=t.Store(id=self.STORE_ID, address=self.address),
        )

    # --- tikvpb.Tikv raw-KV ----------------------------------------------
    def _check_ctx(self, req):
        """Region-epoch staleness, as a real TiKV would report it."""
        t = self._t()
        if self.fail_next_with_region_error > 0:
            self.fail_next_with_region_error -= 1
            return t.RegionError(message="epoch_not_match (injected)")
        if (
            req.context.region_id != self.REGION_ID
            or req.context.region_epoch.version != self.epoch_version
        ):
            return t.RegionError(message="epoch_not_match")
        return None

    def RawGet(self, req, context):
        t = self._t()
        err = self._check_ctx(req)
        if err:
            return t.RawGetResponse(region_error=err)
        v = self.kv.get(bytes(req.key))
        if v is None:
            return t.RawGetResponse(not_found=True)
        return t.RawGetResponse(value=v)

    def RawPut(self, req, context):
        t = self._t()
        err = self._check_ctx(req)
        if err:
            return t.RawPutResponse(region_error=err)
        self.kv[bytes(req.key)] = bytes(req.value)
        return t.RawPutResponse()

    def RawDelete(self, req, context):
        t = self._t()
        err = self._check_ctx(req)
        if err:
            return t.RawDeleteResponse(region_error=err)
        self.kv.pop(bytes(req.key), None)
        return t.RawDeleteResponse()

    def RawDeleteRange(self, req, context):
        t = self._t()
        err = self._check_ctx(req)
        if err:
            return t.RawDeleteRangeResponse(region_error=err)
        start, end = bytes(req.start_key), bytes(req.end_key)
        for k in [k for k in self.kv if start <= k and (not end or k < end)]:
            del self.kv[k]
        return t.RawDeleteRangeResponse()

    def RawScan(self, req, context):
        t = self._t()
        err = self._check_ctx(req)
        if err:
            return t.RawScanResponse(region_error=err)
        start, end = bytes(req.start_key), bytes(req.end_key)
        hits = sorted(
            k for k in self.kv if k >= start and (not end or k < end)
        )[: req.limit or 256]
        return t.RawScanResponse(
            kvs=[t.KvPair(key=k, value=self.kv[k]) for k in hits]
        )

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop(grace=0.2)
