"""Fault-injection helpers for corruption end-to-end tests.

Bit-flips and truncations against the on-disk formats (.dat needle
records, .ec shard files, .ecx indexes) so scrub/repair tests inject
exactly the damage the subsystem claims to detect, plus a slow-replica
TCP proxy (QoS plane: the hedged-read A/B needs one replica reliably
slow without touching server code). Helpers return enough to RESTORE
the damage, because several suites share live cluster fixtures.
"""

from __future__ import annotations

import os

from seaweedfs_tpu.analysis.chaos import ChaosProxy
from seaweedfs_tpu.storage import types as t


class SlowReplicaProxy(ChaosProxy):
    """TCP proxy that delays one replica's RESPONSES by `delay_s`.

    Point a client's replica url at `proxy.addr` instead of the real
    volume server and every byte the server sends back is held for the
    delay before forwarding — the injected-slow-replica fault the
    hedged-read A/B (bench.py qos, BENCH_r09) and the hedge tests
    drive. Requests pass through untouched, so the server does all its
    normal work; only the client-observed latency inflates. `delay_s`
    is mutable mid-run (`proxy.delay_s = 0` = transparent).

    Now a thin preset over the weedchaos fault library's ChaosProxy
    (analysis/chaos.py, docs/CHAOS.md), which generalizes this proxy
    to jitter/bandwidth/drop/blackhole/RST faults."""

    def __init__(self, target: str, delay_s: float = 0.25):
        super().__init__(target)
        self.response.latency_s = delay_s

    @property
    def delay_s(self) -> float:
        return self.response.latency_s

    @delay_s.setter
    def delay_s(self, value: float) -> None:
        self.response.latency_s = value

    @property
    def responses_delayed(self) -> int:
        return self.chunks_delayed


class DeadShard:
    """Quarantine one mounted shard of a LIVE EC volume mid-load — the
    degraded-read fault (docs/SCRUB.md): every later GET whose interval
    lands on the shard must reconstruct from survivors, exactly like a
    disk death under traffic. Uses the same rename-to-.bad quarantine
    the scrubber does, so the repair plane treats it as real damage.

    In-process servers: pass `volume_servers`; subprocess/CLI clusters:
    pass `addr` ("host:port") and the fault rides the /ec/quarantine
    operator route instead. `restore()` moves the .bad file back and
    remounts (in-process only), so suites sharing a cluster fixture can
    heal without a rebuild."""

    def __init__(self, vid: int, sid: int | None = None,
                 volume_servers=None, addr: str | None = None,
                 collection: str = ""):
        self.vid = vid
        self.collection = collection
        self.sid: int | None = sid
        self.addr = addr
        self._vs = None
        self._path: str | None = None
        if (volume_servers is None) == (addr is None):
            raise ValueError("pass exactly one of volume_servers / addr")
        if volume_servers is not None:
            for vs in volume_servers:
                ev = vs.store.find_ec_volume(vid)
                if ev is None:
                    continue
                ids = ev.shard_ids()
                if not ids:
                    continue
                if sid is None:
                    self.sid = ids[0]
                elif sid not in ids:
                    continue
                self._vs = vs
                self._path = ev.shards[self.sid].path
                break
            if self._vs is None:
                raise RuntimeError(
                    f"no server has a mounted shard of vid {vid}"
                    + (f" (wanted shard {sid})" if sid is not None else "")
                )

    def kill(self) -> int:
        """Quarantine the shard; returns the shard id killed."""
        if self._vs is not None:
            ev = self._vs.store.find_ec_volume(self.vid)
            assert ev is not None
            if not ev.quarantine_shard(self.sid, "fault: DeadShard"):
                raise RuntimeError(
                    f"shard {self.sid} of vid {self.vid} not quarantined"
                )
            return self.sid
        import json
        import urllib.request

        url = f"http://{self.addr}/ec/quarantine?volumeId={self.vid}"
        if self.sid is not None:
            url += f"&shard={self.sid}"
        with urllib.request.urlopen(url, timeout=10) as r:
            reply = json.loads(r.read())
        if not reply.get("quarantined"):
            raise RuntimeError(f"DeadShard via {self.addr}: {reply}")
        self.sid = reply["shard"]
        return self.sid

    def restore(self) -> None:
        """Undo (in-process only): move the forensic .bad copy back and
        remount, clearing the quarantine record."""
        if self._vs is None or self._path is None:
            raise RuntimeError("restore() needs in-process volume_servers")
        if os.path.exists(self._path + ".bad"):
            os.replace(self._path + ".bad", self._path)
        store = self._vs.store
        store.mount_ec_shards(self.vid, self.collection, [self.sid])


def flip_byte(path: str, offset: int, xor: int = 0xFF) -> int:
    """XOR one byte in place; returns the ORIGINAL byte value."""
    with open(path, "r+b") as f:
        f.seek(offset)
        orig = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([orig ^ xor]))
    return orig


def restore_byte(path: str, offset: int, value: int) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(bytes([value]))


def truncate_by(path: str, nbytes: int) -> int:
    """Chop `nbytes` off the file's tail; returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - nbytes)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def find_ec_shard_path(volume_servers, collection: str, vid: int, sid: int):
    """(path, serving VolumeServer) for the MOUNTED copy of a shard.
    Mount state is checked first (via the store), not mere file
    existence: the encode/spread pipeline can leave an unmounted
    leftover shard file on the encoding node, and corrupting that
    dead copy instead of the served one makes a detection test pass
    or fail on spread order. Falls back to any on-disk file when no
    server has the shard mounted; (None, None) when absent."""
    for vs in volume_servers:
        ev = vs.store.find_ec_volume(vid)
        if ev is not None and sid in ev.shards:
            return ev.shards[sid].path, vs
    name = (
        f"{collection}_{vid}.ec{sid:02d}" if collection else f"{vid}.ec{sid:02d}"
    )
    for vs in volume_servers:
        for loc in vs.store.locations:
            p = os.path.join(loc.directory, name)
            if os.path.exists(p):
                return p, vs
    return None, None


def corrupt_needle_data(volume, needle_id: int, xor: int = 0x5A) -> tuple[str, int, int]:
    """Flip one byte inside a live needle's DATA region in the .dat so
    the CRC check fails on re-read. Returns (dat_path, offset, original
    byte) for restoration.

    v2/v3 record layout: 16-byte header, then u32 data_size, then data
    — so the first data byte sits at actual_offset + 20."""
    nv = volume.nm.get(needle_id)
    assert nv is not None and nv.size != t.TOMBSTONE_FILE_SIZE, (
        f"needle {needle_id} not live"
    )
    dat_path = volume.base_name + ".dat"
    offset = nv.actual_offset + t.NEEDLE_HEADER_SIZE + 4
    orig = flip_byte(dat_path, offset, xor)
    return dat_path, offset, orig
