"""Fault-injection helpers for corruption end-to-end tests.

Bit-flips and truncations against the on-disk formats (.dat needle
records, .ec shard files, .ecx indexes) so scrub/repair tests inject
exactly the damage the subsystem claims to detect. Helpers return
enough to RESTORE the damage, because several suites share live
cluster fixtures.
"""

from __future__ import annotations

import os

from seaweedfs_tpu.storage import types as t


def flip_byte(path: str, offset: int, xor: int = 0xFF) -> int:
    """XOR one byte in place; returns the ORIGINAL byte value."""
    with open(path, "r+b") as f:
        f.seek(offset)
        orig = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([orig ^ xor]))
    return orig


def restore_byte(path: str, offset: int, value: int) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(bytes([value]))


def truncate_by(path: str, nbytes: int) -> int:
    """Chop `nbytes` off the file's tail; returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - nbytes)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def find_ec_shard_path(volume_servers, collection: str, vid: int, sid: int):
    """(path, serving VolumeServer) for the MOUNTED copy of a shard.
    Mount state is checked first (via the store), not mere file
    existence: the encode/spread pipeline can leave an unmounted
    leftover shard file on the encoding node, and corrupting that
    dead copy instead of the served one makes a detection test pass
    or fail on spread order. Falls back to any on-disk file when no
    server has the shard mounted; (None, None) when absent."""
    for vs in volume_servers:
        ev = vs.store.find_ec_volume(vid)
        if ev is not None and sid in ev.shards:
            return ev.shards[sid].path, vs
    name = (
        f"{collection}_{vid}.ec{sid:02d}" if collection else f"{vid}.ec{sid:02d}"
    )
    for vs in volume_servers:
        for loc in vs.store.locations:
            p = os.path.join(loc.directory, name)
            if os.path.exists(p):
                return p, vs
    return None, None


def corrupt_needle_data(volume, needle_id: int, xor: int = 0x5A) -> tuple[str, int, int]:
    """Flip one byte inside a live needle's DATA region in the .dat so
    the CRC check fails on re-read. Returns (dat_path, offset, original
    byte) for restoration.

    v2/v3 record layout: 16-byte header, then u32 data_size, then data
    — so the first data byte sits at actual_offset + 20."""
    nv = volume.nm.get(needle_id)
    assert nv is not None and nv.size != t.TOMBSTONE_FILE_SIZE, (
        f"needle {needle_id} not live"
    )
    dat_path = volume.base_name + ".dat"
    offset = nv.actual_offset + t.NEEDLE_HEADER_SIZE + 4
    orig = flip_byte(dat_path, offset, xor)
    return dat_path, offset, orig
