"""Fault-injection helpers for corruption end-to-end tests.

Bit-flips and truncations against the on-disk formats (.dat needle
records, .ec shard files, .ecx indexes) so scrub/repair tests inject
exactly the damage the subsystem claims to detect, plus a slow-replica
TCP proxy (QoS plane: the hedged-read A/B needs one replica reliably
slow without touching server code). Helpers return enough to RESTORE
the damage, because several suites share live cluster fixtures.
"""

from __future__ import annotations

import os
import socket
import threading
import time

from seaweedfs_tpu.storage import types as t


class SlowReplicaProxy:
    """TCP proxy that delays one replica's RESPONSES by `delay_s`.

    Point a client's replica url at `proxy.addr` instead of the real
    volume server and every byte the server sends back is held for the
    delay before forwarding — the injected-slow-replica fault the
    hedged-read A/B (bench.py qos, BENCH_r09) and the hedge tests
    drive. Requests pass through untouched, so the server does all its
    normal work; only the client-observed latency inflates. `delay_s`
    is mutable mid-run (`proxy.delay_s = 0` = transparent)."""

    def __init__(self, target: str, delay_s: float = 0.25):
        host, _, port = target.partition(":")
        self.target = (host, int(port))
        self.delay_s = delay_s
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self.responses_delayed = 0
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def addr(self) -> str:
        return "127.0.0.1:%d" % self._listener.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(self.target, timeout=10)
            except OSError:
                client.close()
                continue
            for s in (client, upstream):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
            with self._lock:
                self._conns += [client, upstream]
            threading.Thread(
                target=self._pump, args=(client, upstream, 0.0), daemon=True
            ).start()
            threading.Thread(
                target=self._pump, args=(upstream, client, None), daemon=True
            ).start()

    def _pump(self, src, dst, fixed_delay) -> None:
        # fixed_delay None = the response direction: read self.delay_s
        # per chunk so tests can retune a live proxy
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                d = self.delay_s if fixed_delay is None else fixed_delay
                if d > 0:
                    if fixed_delay is None:
                        self.responses_delayed += 1
                    time.sleep(d)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


def flip_byte(path: str, offset: int, xor: int = 0xFF) -> int:
    """XOR one byte in place; returns the ORIGINAL byte value."""
    with open(path, "r+b") as f:
        f.seek(offset)
        orig = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([orig ^ xor]))
    return orig


def restore_byte(path: str, offset: int, value: int) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(bytes([value]))


def truncate_by(path: str, nbytes: int) -> int:
    """Chop `nbytes` off the file's tail; returns the new size."""
    size = os.path.getsize(path)
    new = max(0, size - nbytes)
    with open(path, "r+b") as f:
        f.truncate(new)
    return new


def find_ec_shard_path(volume_servers, collection: str, vid: int, sid: int):
    """(path, serving VolumeServer) for the MOUNTED copy of a shard.
    Mount state is checked first (via the store), not mere file
    existence: the encode/spread pipeline can leave an unmounted
    leftover shard file on the encoding node, and corrupting that
    dead copy instead of the served one makes a detection test pass
    or fail on spread order. Falls back to any on-disk file when no
    server has the shard mounted; (None, None) when absent."""
    for vs in volume_servers:
        ev = vs.store.find_ec_volume(vid)
        if ev is not None and sid in ev.shards:
            return ev.shards[sid].path, vs
    name = (
        f"{collection}_{vid}.ec{sid:02d}" if collection else f"{vid}.ec{sid:02d}"
    )
    for vs in volume_servers:
        for loc in vs.store.locations:
            p = os.path.join(loc.directory, name)
            if os.path.exists(p):
                return p, vs
    return None, None


def corrupt_needle_data(volume, needle_id: int, xor: int = 0x5A) -> tuple[str, int, int]:
    """Flip one byte inside a live needle's DATA region in the .dat so
    the CRC check fails on re-read. Returns (dat_path, offset, original
    byte) for restoration.

    v2/v3 record layout: 16-byte header, then u32 data_size, then data
    — so the first data byte sits at actual_offset + 20."""
    nv = volume.nm.get(needle_id)
    assert nv is not None and nv.size != t.TOMBSTONE_FILE_SIZE, (
        f"needle {needle_id} not live"
    )
    dat_path = volume.base_name + ".dat"
    offset = nv.actual_offset + t.NEEDLE_HEADER_SIZE + 4
    orig = flip_byte(dat_path, offset, xor)
    return dat_path, offset, orig
