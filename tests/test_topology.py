"""Control-plane tests: topology tree, layouts, placement, EC registry,
sequencer — the reference's topology_test.go / volume_growth_test.go
strategy (synthetic heartbeats into a real Topology, no cluster).
"""

import random

import pytest

from seaweedfs_tpu.sequence import MemorySequencer
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.store import EcShardInfo, VolumeInfo
from seaweedfs_tpu.topology import Topology
from seaweedfs_tpu.topology.volume_growth import (
    find_empty_slots_for_one_volume,
    find_volume_count,
)


def make_volume_info(vid, collection="", size=1000, rp=0, read_only=False, ttl=0):
    return VolumeInfo(
        id=vid,
        size=size,
        collection=collection,
        file_count=1,
        delete_count=0,
        deleted_byte_count=0,
        read_only=read_only,
        replica_placement=rp,
        version=3,
        ttl=ttl,
    )


def build_topology(n_dcs=2, racks_per_dc=2, nodes_per_rack=3, max_volumes=8):
    topo = Topology(volume_size_limit=10_000)
    for d in range(n_dcs):
        for r in range(racks_per_dc):
            for n in range(nodes_per_rack):
                topo.register_data_node(
                    ip=f"10.{d}.{r}.{n}",
                    port=8080,
                    data_center=f"dc{d}",
                    rack=f"rack{d}-{r}",
                    max_volumes=max_volumes,
                )
    return topo


class TestTree:
    def test_counts_aggregate(self):
        topo = build_topology()
        assert topo.max_volume_count() == 2 * 2 * 3 * 8
        assert topo.volume_count() == 0
        assert topo.free_space() == 96
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1), make_volume_info(2)])
        assert topo.volume_count() == 2
        assert topo.free_space() == 94

    def test_heartbeat_sync_add_remove(self):
        topo = build_topology()
        dn = topo.data_nodes()[0]
        new, deleted = topo.sync_volumes(dn, [make_volume_info(1)])
        assert [v.id for v in new] == [1]
        new, deleted = topo.sync_volumes(dn, [make_volume_info(2)])
        assert [v.id for v in new] == [2]
        assert [v.id for v in deleted] == [1]
        assert topo.lookup("", 2) == [dn]
        assert topo.lookup("", 1) == []

    def test_unregister_node_drops_volumes(self):
        topo = build_topology()
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1)])
        topo.unregister_data_node(dn)
        assert topo.lookup("", 1) == []
        assert dn.id not in [n.id for n in topo.data_nodes()]


class TestLayout:
    def test_pick_for_write_and_lookup(self):
        topo = build_topology()
        nodes = topo.data_nodes()
        topo.sync_volumes(nodes[0], [make_volume_info(1)])
        topo.sync_volumes(nodes[1], [make_volume_info(1)])
        vid, count, locations = topo.pick_for_write("", "000", "", 1)
        assert vid == 1
        assert len(locations) == 2
        assert set(topo.lookup("", 1)) == {nodes[0], nodes[1]}

    def test_readonly_not_writable(self):
        topo = build_topology()
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1, read_only=True)])
        with pytest.raises(ValueError, match="no writable"):
            topo.pick_for_write("", "000", "", 1)

    def test_oversized_not_writable(self):
        topo = build_topology()
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1, size=20_000)])
        with pytest.raises(ValueError, match="no writable"):
            topo.pick_for_write("", "000", "", 1)

    def test_dc_affinity(self):
        topo = build_topology()
        nodes_dc0 = [n for n in topo.data_nodes() if n.get_data_center().id == "dc0"]
        nodes_dc1 = [n for n in topo.data_nodes() if n.get_data_center().id == "dc1"]
        topo.sync_volumes(nodes_dc0[0], [make_volume_info(1)])
        topo.sync_volumes(nodes_dc1[0], [make_volume_info(2)])
        for _ in range(10):
            vid, _, _ = topo.pick_for_write("", "000", "", 1, data_center="dc1")
            assert vid == 2

    def test_collections_isolated(self):
        topo = build_topology()
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1, collection="a")])
        assert topo.lookup("a", 1) == [dn]
        assert topo.lookup("b", 1) == []
        assert "a" in topo.collections()


class TestGrowth:
    def test_find_volume_count(self):
        assert find_volume_count(1) == 7
        assert find_volume_count(2) == 6
        assert find_volume_count(3) == 3
        assert find_volume_count(4) == 1

    @pytest.mark.parametrize("rp_str,expect_n", [("000", 1), ("001", 2), ("010", 2), ("100", 2), ("012", 4), ("112", 5)])
    def test_placement_satisfies_rp(self, rp_str, expect_n):
        topo = build_topology(n_dcs=3, racks_per_dc=3, nodes_per_rack=4)
        rp = ReplicaPlacement.parse(rp_str)
        rng = random.Random(0)
        for _ in range(20):
            servers = find_empty_slots_for_one_volume(topo, rp, rng=rng)
            assert len(servers) == expect_n == rp.copy_count
            # placement constraints
            dcs = {s.get_data_center().id for s in servers}
            racks = {(s.get_data_center().id, s.get_rack().id) for s in servers}
            assert len(dcs) == rp.diff_data_center_count + 1
            assert len(racks) == rp.diff_data_center_count + rp.diff_rack_count + 1
            assert len(set(s.id for s in servers)) == len(servers)

    def test_placement_fails_when_impossible(self):
        topo = build_topology(n_dcs=1, racks_per_dc=1, nodes_per_rack=2)
        with pytest.raises(ValueError):
            find_empty_slots_for_one_volume(topo, ReplicaPlacement.parse("100"))

    def test_placement_respects_capacity(self):
        topo = build_topology(n_dcs=1, racks_per_dc=1, nodes_per_rack=4, max_volumes=1)
        dn = topo.data_nodes()[0]
        topo.sync_volumes(dn, [make_volume_info(1)])  # node full
        rp = ReplicaPlacement.parse("002")
        rng = random.Random(3)
        for _ in range(10):
            servers = find_empty_slots_for_one_volume(topo, rp, rng=rng)
            assert dn not in servers


class TestEcRegistry:
    def test_register_lookup_unregister(self):
        topo = build_topology()
        dn0, dn1 = topo.data_nodes()[:2]
        topo.sync_ec_shards(dn0, [EcShardInfo(5, "", 0b0000000001111111)])
        topo.sync_ec_shards(dn1, [EcShardInfo(5, "", 0b0011111110000000)])
        locs = topo.lookup_ec_shards(5)
        assert locs is not None
        assert locs.locations[0] == [dn0]
        assert locs.locations[13] == [dn1]
        assert set(topo.lookup("", 5)) == {dn0, dn1}
        # shard set shrinks on next heartbeat
        topo.sync_ec_shards(dn0, [])
        locs = topo.lookup_ec_shards(5)
        assert locs.locations[0] == []

    def test_shard_bits_shrink_removes_stale_locations(self):
        # shard moves away but the vid stays on the node: the stale
        # location must be dropped from the shard map
        topo = build_topology()
        dn = topo.data_nodes()[0]
        topo.sync_ec_shards(dn, [EcShardInfo(5, "", 0b11)])
        topo.sync_ec_shards(dn, [EcShardInfo(5, "", 0b01)])
        locs = topo.lookup_ec_shards(5)
        assert locs.locations[0] == [dn]
        assert locs.locations[1] == []

    def test_ec_counts_in_free_space(self):
        topo = build_topology(n_dcs=1, racks_per_dc=1, nodes_per_rack=1, max_volumes=10)
        dn = topo.data_nodes()[0]
        before = topo.free_space()
        topo.sync_ec_shards(dn, [EcShardInfo(5, "", (1 << 14) - 1)])
        assert topo.free_space() == before - 1


class TestSequencer:
    def test_ranges(self):
        seq = MemorySequencer()
        assert seq.next_file_id(1) == 1
        assert seq.next_file_id(5) == 2
        assert seq.next_file_id(1) == 7

    def test_set_max_equal_advances(self):
        # a reported key EQUAL to the counter must advance past it,
        # or the next assign re-issues an id already on disk
        seq = MemorySequencer()
        assert seq.peek() == 1
        seq.set_max(1)
        assert seq.next_file_id(1) == 2

    def test_set_max(self):
        seq = MemorySequencer()
        seq.set_max(100)
        assert seq.next_file_id(1) == 101
        seq.set_max(50)  # no-op, already past
        assert seq.next_file_id(1) == 102

    def test_id_generator_adjusts_from_heartbeat(self):
        topo = Topology()
        dn = topo.register_data_node("1.1.1.1", 80)
        topo.sync_volumes(dn, [make_volume_info(41)])
        assert topo.next_volume_id() == 42
