"""weedscope plane tests (ISSUE-20, docs/TELEMETRY.md + docs/TRACING.md).

Units: the SLO burn-rate engine's multi-window math (availability
excluding client-attributable 503/504, latency from pooled bucket
increases, plane filtering), the flapping-suppression and resolve-
hysteresis state machine, bounded AlertManager history, the on_fire
edge hook, the blackbox flight recorder's tail-biased retention, the
exemplar render/parse round trip, incident-capsule durability and the
/capsule HTTP surface's path-traversal guard, and the collector's
dead-node TTL (the PR-14 NodeHealth prune, mirrored for scrape
targets).
"""

from __future__ import annotations

import json
import os
import time

from seaweedfs_tpu.telemetry import slo as slo_mod
from seaweedfs_tpu.telemetry.alerts import AlertManager, AlertRule
from seaweedfs_tpu.telemetry.ring import TargetStore

# ----------------------------------------------------------------------
# SLO engine: measurement math against a real TargetStore


def _scrape(ts: TargetStore, t: float, rows):
    """rows: [(name, {labels}, value), ...]"""
    ts.record_scrape(
        [(n, tuple(sorted(labels.items())), v) for n, labels, v in rows], t
    )


class TestSLOMeasurement:
    def test_availability_excludes_client_attributable_5xx(self):
        ts = TargetStore("n1:8080", "volume")
        fam = "weed_http_request_total"
        _scrape(ts, 100.0, [
            (fam, {"status": "200"}, 100.0),
            (fam, {"status": "503"}, 50.0),
            (fam, {"status": "500"}, 0.0),
        ])
        _scrape(ts, 160.0, [
            (fam, {"status": "200"}, 200.0),
            (fam, {"status": "503"}, 150.0),
            (fam, {"status": "500"}, 1.0),
        ])
        obj = slo_mod.SLOObjective("avail", "availability", 0.999, family=fam)
        eng = slo_mod.SLOEngine(objectives=[obj], fast_s=100.0, slow_s=100.0)
        bad, total = eng._bad_total(obj, [ts], 100.0, 170.0)
        # 503 is shed (client-attributable, docs/HEALTH.md): only the
        # one 500 burns the budget; the 100 shed requests still count
        # toward total served
        assert bad == 1.0
        assert total == 201.0

    def test_latency_counts_bad_above_threshold_bucket(self):
        ts = TargetStore("n1:8080", "volume")
        b = "weed_http_request_seconds_bucket"
        _scrape(ts, 100.0, [
            (b, {"le": "0.1"}, 10.0),
            (b, {"le": "1.0"}, 10.0),
            (b, {"le": "+Inf"}, 10.0),
        ])
        _scrape(ts, 160.0, [
            (b, {"le": "0.1"}, 10.0),
            (b, {"le": "1.0"}, 15.0),
            (b, {"le": "+Inf"}, 20.0),
        ])
        obj = slo_mod.SLOObjective(
            "lat", "latency", 0.99,
            family="weed_http_request_seconds", threshold_s=0.5,
        )
        eng = slo_mod.SLOEngine(objectives=[obj])
        # threshold 0.5 falls between buckets: judged at the 1.0 bound
        # (conservative). good = +5 at le=1.0, total = +10 → 5 bad.
        bad, total = eng._bad_total(obj, [ts], 100.0, 170.0)
        assert (bad, total) == (5.0, 10.0)

    def test_latency_plane_filter(self):
        ts = TargetStore("n1:8080", "volume")
        b = "weed_span_seconds_bucket"
        _scrape(ts, 100.0, [
            (b, {"le": "+Inf", "plane": "serve"}, 0.0),
            (b, {"le": "+Inf", "plane": "scrub"}, 0.0),
        ])
        _scrape(ts, 160.0, [
            (b, {"le": "+Inf", "plane": "serve"}, 100.0),
            (b, {"le": "+Inf", "plane": "scrub"}, 7.0),
        ])
        obj = slo_mod.SLOObjective(
            "scrub-lat", "latency", 0.95, plane="scrub",
            family="weed_span_seconds", threshold_s=3.0,
        )
        eng = slo_mod.SLOEngine(objectives=[obj])
        pooled = eng._pooled_buckets(obj, [ts], 100.0, 170.0)
        assert pooled[float("inf")] == 7.0  # serve-plane buckets excluded


# ----------------------------------------------------------------------
# SLO engine: multi-window state machine (stub targets drive exact burns)


class _StubTarget:
    """increase_sum-level stub: (bad, total) per window size, so tests
    dial in exact fast/slow burn rates without fabricating rings."""

    kind = "volume"

    def __init__(self, by_window):
        self.by_window = by_window  # {window_s: (bad, total)}

    def increase_sum(self, name, window_s, now=None, label_filter=None):
        bad, total = self.by_window[window_s]
        return bad if label_filter is not None else total

    def bucket_increases(self, family, window_s, now=None, label_filter=None):
        return {}


_AVAIL = slo_mod.SLOObjective(
    "avail", "availability", 0.9, family="weed_http_request_total"
)


def _engine():
    return slo_mod.SLOEngine(
        objectives=[_AVAIL], fast_s=60.0, slow_s=600.0,
        burn_threshold=1.0, resolve_factor=0.5,
    )


def _active(conds):
    [(rule, target, active, _v, _d)] = conds
    assert rule is slo_mod.RULE_SLO_BURN and target == "avail"
    return active


class TestSLOBurnStateMachine:
    def test_fast_only_burst_does_not_fire(self):
        eng = _engine()
        # fast window: 10 bad of 20 → burn 5x. slow window: the same 10
        # bad diluted by 10k requests → burn 0.01x. Multi-window says:
        # this burst never endangers the budget — do not page.
        tgt = _StubTarget({60.0: (10.0, 20.0), 600.0: (10.0, 10000.0)})
        assert not _active(eng.evaluate([tgt], now=1000.0))
        assert eng.payload()["Breaching"] == []

    def test_both_windows_burning_fires_and_exports_gauges(self):
        from seaweedfs_tpu.stats.metrics import (
            SLO_BUDGET_REMAINING, SLO_BURN_RATE,
        )

        eng = _engine()
        tgt = _StubTarget({60.0: (10.0, 20.0), 600.0: (300.0, 1000.0)})
        assert _active(eng.evaluate([tgt], now=1000.0))
        assert eng.payload()["Breaching"] == ["avail"]
        assert SLO_BURN_RATE.value("avail", "fast") == 5.0
        assert SLO_BURN_RATE.value("avail", "slow") == 3.0
        assert SLO_BUDGET_REMAINING.value("avail") == 0.0

    def test_resolve_hysteresis(self):
        eng = _engine()
        burning = _StubTarget({60.0: (10.0, 20.0), 600.0: (300.0, 1000.0)})
        assert _active(eng.evaluate([burning], now=1000.0))
        # cooled below the threshold but not below threshold×0.5:
        # a burn oscillating around 1.0x must not flap the alert
        warm = _StubTarget({60.0: (8.0, 100.0), 600.0: (10.0, 10000.0)})
        assert _active(eng.evaluate([warm], now=1060.0))
        # only a real cool-down (fast burn < 0.5x) resolves
        cold = _StubTarget({60.0: (1.0, 100.0), 600.0: (10.0, 10000.0)})
        assert not _active(eng.evaluate([cold], now=1120.0))
        assert eng.payload()["Breaching"] == []
        # and the warm level does NOT re-fire from the resolved state
        assert not _active(eng.evaluate([warm], now=1180.0))


# ----------------------------------------------------------------------
# AlertManager: on_fire edge hook + bounded history


class TestAlertManagerScope:
    def test_on_fire_fires_only_on_edge(self):
        rows = []
        rule = AlertRule("edge", "critical", for_s=0.0)
        mgr = AlertManager(on_fire=rows.append)
        mgr.evaluate([(rule, "t1", True, 1.0, "d")], now=10.0)
        assert len(rows) == 1 and rows[0]["Alert"] == "edge"
        # still firing: no second invocation
        mgr.evaluate([(rule, "t1", True, 2.0, "d")], now=11.0)
        assert len(rows) == 1
        mgr.evaluate([(rule, "t1", False, 0.0, "")], now=12.0)
        mgr.evaluate([(rule, "t1", True, 3.0, "d")], now=13.0)
        assert len(rows) == 2  # re-fire after resolve is a new edge

    def test_on_fire_exception_never_breaks_evaluation(self):
        rule = AlertRule("boom", for_s=0.0)

        def hook(_row):
            raise RuntimeError("capture exploded")

        mgr = AlertManager(on_fire=hook)
        mgr.evaluate([(rule, "t1", True, 1.0, "d")], now=10.0)
        assert mgr.firing()  # state machine advanced despite the hook

    def test_history_stays_bounded_under_flapping(self):
        rule = AlertRule("flappy", for_s=0.0)
        mgr = AlertManager()
        for i in range(200):
            mgr.evaluate([(rule, "t1", True, 1.0, "d")], now=float(i))
            mgr.evaluate([(rule, "t1", False, 0.0, "")], now=i + 0.5)
        assert len(mgr._history) <= 128
        assert len(mgr.payload()["History"]) <= 32
        # gauge row removed, not zeroed, once resolved
        from seaweedfs_tpu.stats.metrics import ALERT_FIRING

        assert ("flappy", "t1") not in ALERT_FIRING._values


# ----------------------------------------------------------------------
# blackbox flight recorder: tail-biased retention


class TestBlackboxRetention:
    def test_tail_bias_and_ok_sampling(self):
        from seaweedfs_tpu.trace import blackbox

        blackbox.reset()
        rec = blackbox.recorder("test", "n1")
        ok_every = blackbox.snapshot(0)["ok_every"]
        n_ok = 2 * ok_every
        for _ in range(n_ok):
            rec("GET", "", "serve", 200, 0.001, 10, "p", 0, None)
        rec("GET", "t-err", "serve", 404, 0.001, 0, "p", 0, None)
        rec("GET", "t-slow", "serve", 200, 0.5, 10, "p", 0, None)
        rec(
            "GET", "t-retry", "serve", 200, 0.001, 10, "p",
            blackbox.FLAG_RETRY, None,
        )
        snap = blackbox.snapshot(64)
        # every error/slow/flagged record survives; OKs are 1-in-N
        # (any 2N consecutive draws win exactly twice)
        assert snap["tail_recorded"] == 3
        assert snap["ok_recorded"] == 2
        by_trace = {r["trace"]: r for r in snap["tail"]}
        assert by_trace["t-err"]["status"] == 404
        assert by_trace["t-slow"]["dur_ms"] == 500.0
        assert by_trace["t-retry"]["flags"] == ["retry"]
        assert all(r["name"] == "test.GET" for r in snap["tail"])

    def test_kill_switch_drops_records(self):
        from seaweedfs_tpu.trace import blackbox

        blackbox.reset()
        rec = blackbox.recorder("test", "n1")
        blackbox.set_enabled(False)
        try:
            rec("GET", "t", "serve", 500, 1.0, 0, "p", 0, None)
            snap = blackbox.snapshot(8)
            assert snap["enabled"] is False
            assert snap["tail_recorded"] == 0
        finally:
            blackbox.set_enabled(True)

    def test_stage_dict_rides_the_record(self):
        from seaweedfs_tpu.trace import blackbox

        blackbox.reset()
        rec = blackbox.recorder("volume", "n1")
        rec(
            "GET", "tid", "serve", 404, 0.2, 0, "p", 0,
            {"parse": 0.001, "resolve": 0.002, "send": 0.003},
        )
        [row] = blackbox.snapshot(8)["tail"]
        assert set(row["stages_ms"]) == {"parse", "resolve", "send"}

    def test_request_flags(self):
        from seaweedfs_tpu.trace import blackbox

        f = blackbox.request_flags({"x-weed-retry": "1"}, 200)
        assert f == blackbox.FLAG_RETRY
        f = blackbox.request_flags({"x-weed-hedge": "1"}, 503)
        assert f == blackbox.FLAG_HEDGE | blackbox.FLAG_SHED
        assert blackbox.request_flags({}, 504) == blackbox.FLAG_DEADLINE


# ----------------------------------------------------------------------
# exemplars: render + parse round trip


class TestExemplars:
    def test_render_and_parse_round_trip(self):
        from seaweedfs_tpu.stats import metrics as metrics_mod
        from seaweedfs_tpu.telemetry.parse import parse_prometheus_text

        reg = metrics_mod.Registry()
        hist = reg.histogram("x_seconds", "h", ("k",), buckets=(0.1, 1.0))
        hist.observe(0.05, "a")
        hist.put_exemplar(0.05, "traceabc", "a")
        text = reg.render_text()
        assert '# {trace_id="traceabc"}' in text
        samples = parse_prometheus_text(text)
        buckets = {
            dict(lt)["le"]: v
            for n, lt, v in samples
            if n == "x_seconds_bucket"
        }
        # exemplar suffix must not perturb the parsed sample values
        assert buckets == {"0.1": 1.0, "1.0": 1.0, "+Inf": 1.0}

    def test_kill_switch_reverts_to_plain_exposition(self):
        from seaweedfs_tpu.stats import metrics as metrics_mod

        reg = metrics_mod.Registry()
        hist = reg.histogram("y_seconds", "h", (), buckets=(1.0,))
        hist.observe(0.5)
        hist.put_exemplar(0.5, "tid")
        metrics_mod.set_exemplars_enabled(False)
        try:
            assert "trace_id" not in reg.render_text()
        finally:
            metrics_mod.set_exemplars_enabled(True)
        assert "trace_id" in reg.render_text()


# ----------------------------------------------------------------------
# incident capsules: durability, retention, traversal guard


class TestCapsules:
    def test_capture_is_durable_and_manifest_complete(self, tmp_path):
        from seaweedfs_tpu.telemetry import capsule

        man = capsule.capture("unit test!", node="n1:80", root=str(tmp_path))
        assert man["Node"] == "n1:80" and man["Trigger"] == "manual"
        cap_dir = tmp_path / man["Id"]
        assert (cap_dir / "MANIFEST.json").exists()
        names = {f["Name"] for f in man["Files"]}
        assert {
            "blackbox.json", "traces.json", "profile.txt", "metrics.txt"
        } <= names
        for f in man["Files"]:
            if f["Ok"]:
                assert (cap_dir / f["Name"]).exists()
        # the published manifest round-trips through list + read_file
        [listed] = [
            c for c in capsule.list_capsules(root=str(tmp_path))
            if c["Id"] == man["Id"]
        ]
        assert listed == json.loads(
            capsule.read_file(man["Id"], "MANIFEST.json", root=str(tmp_path))
        )

    def test_read_file_blocks_path_traversal(self, tmp_path):
        from seaweedfs_tpu.telemetry import capsule

        man = capsule.capture("guard", root=str(tmp_path))
        root = str(tmp_path)
        assert capsule.read_file("../evil", "x", root=root) is None
        assert capsule.read_file("no/slash", "x", root=root) is None
        assert capsule.read_file(man["Id"], "../MANIFEST.json", root=root) is None
        assert capsule.read_file(man["Id"], ".hidden", root=root) is None
        assert capsule.read_file(man["Id"], "MANIFEST.json", root=root)

    def test_retention_keeps_newest_and_prunes_stale_partials(self, tmp_path):
        from seaweedfs_tpu.telemetry import capsule

        root = str(tmp_path)
        # a crash partial: id-shaped dir, no manifest, older than 1 h
        partial = tmp_path / "1000000000000-0-crashed"
        partial.mkdir()
        os.utime(partial, (time.time() - 7200, time.time() - 7200))
        ids = [
            capsule.capture(f"cap{i}", root=root)["Id"]
            for i in range(capsule._KEEP + 3)
        ]
        kept = [c["Id"] for c in capsule.list_capsules(root=root)]
        assert len(kept) == capsule._KEEP
        assert kept == ids[-capsule._KEEP:]  # newest win, oldest pruned
        assert not partial.exists()

    def test_autocapture_cooldown(self):
        from seaweedfs_tpu.telemetry import capsule

        key = "unit-cooldown-key"
        assert capsule.should_autocapture(key, now=5000.0)
        assert not capsule.should_autocapture(key, now=5001.0)
        assert capsule.should_autocapture(
            key, now=5001.0 + capsule._COOLDOWN_S
        )

    def test_coordinator_respects_kill_switch(self):
        from seaweedfs_tpu.telemetry import capsule

        calls = []
        coord = capsule.CaptureCoordinator(
            node="n1", peers_fn=lambda row: calls.append(row),
            enabled_fn=lambda: False,
        )
        coord({"Alert": "a", "Target": "t"})
        assert calls == []  # WEED_SCOPE=0: no auto-capture side effects


# ----------------------------------------------------------------------
# collector: sticky scrape targets with a dead-node TTL (satellite 1)


class _StubTopology:
    @staticmethod
    def data_nodes():
        return []


class _StubMaster:
    host, port = "127.0.0.1", 1
    is_leader = True
    repair = None
    topology = _StubTopology()

    @staticmethod
    def gateway_registrations():
        return {}


class TestDeadNodeTTL:
    def _collector(self):
        from seaweedfs_tpu.telemetry.collector import ClusterCollector

        # floor: forget_after = stale_after + 2×interval = 5 s, so the
        # staleness alert always fires before the target is forgotten
        return ClusterCollector(_StubMaster(), interval=1.0, forget_after=0.0)

    def test_forget_after_floored_above_staleness_grace(self):
        c = self._collector()
        assert c.forget_after >= c.stale_after + 2.0 * c.interval

    def test_stale_target_alerts_first_then_is_forgotten(self):
        from seaweedfs_tpu.stats.metrics import SCRAPE_STALENESS, SCRAPE_UP

        c = self._collector()
        url = "10.9.9.9:8080"
        now = time.time()
        ts = TargetStore(url, "volume")
        ts.last_success = now - (c.stale_after + 0.5)  # stale, not dead
        c.targets[url] = ts
        SCRAPE_UP.set(0.0, url)
        SCRAPE_STALENESS.set(99.0, url)
        c._discover()
        assert url in c.targets  # sticky: absent from topology but kept
        c._evaluate(list(c.targets.values()), now)
        assert any(
            a["Alert"] == "scrape_staleness" and a["Target"] == url
            for a in c.alerts.firing()
        )
        # past the TTL: forgotten, gauge rows removed (not zeroed)
        ts.last_success = now - (c.forget_after + 0.5)
        c._discover()
        assert url not in c.targets
        assert (url,) not in SCRAPE_UP._values
        assert (url,) not in SCRAPE_STALENESS._values
        # the vanished rule×target pair auto-resolves next cycle
        c._evaluate(list(c.targets.values()), now)
        assert not any(a["Target"] == url for a in c.alerts.firing())

    def test_discovered_target_never_forgotten(self):
        c = self._collector()
        url = f"{_StubMaster.host}:{_StubMaster.port}"  # always discovered
        c._discover()
        ts = c.targets[url]
        ts.first_seen = time.time() - 10_000.0  # ancient and never up
        c._discover()
        assert url in c.targets
