"""BASELINE config 5: live replication→EC warm-tier migration under
concurrent reads with ZERO read unavailability.

The availability guarantee is an ordering property of the encode
pipeline (reference volume_grpc_erasure_coding.go:25-36 + the read
fallback volume_server_handlers_read.go:30-45): EC shards are
generated, spread, and MOUNTED — and their locations registered with
the master — strictly before the source volume is deleted, so at every
instant some server can serve every key (from the volume while it
lives, from the shard set afterwards). Two supporting mechanisms:

  * immediate delta heartbeats (Store.notify_change →
    VolumeServer._hb_wake): mount/delete inventory changes reach the
    master NOW, not on the next tick — the reference's
    NewVolumesChan/NewEcShardsChan pushes
    (volume_grpc_client_to_master.go);
  * master lookup falling back to EC shard holders once the volume's
    locations are gone (topology.lookup → lookup_ec_shards).

TestMigrationAvailability hammers readers through the full ec.encode
pipeline and asserts zero failed reads. TestHarnessSensitivity proves
the harness would catch a misordered pipeline: deleting the volume
before mounting the shards makes the same readers fail.
"""

import io
import time
import urllib.request

import pytest

from seaweedfs_tpu.util.availability import (
    HammerReader,
    free_port,
    run_with_readers,
    start_cluster,
    write_keyset,
)
from seaweedfs_tpu.shell.command_env import CommandEnv
from seaweedfs_tpu.shell.commands import do_ec_encode


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    master, volume_servers = start_cluster(
        [str(tmp_path_factory.mktemp(f"mig{i}")) for i in range(3)]
    )
    yield master, volume_servers
    for vs in volume_servers:
        vs.stop()
    master.stop()


class TestMigrationAvailability:
    def test_zero_failed_reads_through_ec_encode(self, cluster):
        master, volume_servers = cluster
        vid, keys, source_url = write_keyset(master.port, "mig")
        env = CommandEnv([f"127.0.0.1:{master.port}"])

        readers = [
            # a client that always asks the master (GET /<fid> 301 →
            # current location, EC holders after the cutover)
            HammerReader(f"http://127.0.0.1:{master.port}", keys, "via-master"),
            # a client with a stale address book: keeps hitting the
            # original location, which must serve from its shard subset
            # (remote fan-in) or redirect — never fail
            HammerReader(f"http://{source_url}", keys, "direct-source"),
        ]
        run_with_readers(
            readers, lambda: do_ec_encode(env, vid, "mig", io.StringIO())
        )

        all_failures = [f for r in readers for f in r.failures]
        assert all_failures == [], all_failures[:10]
        for r in readers:
            # both readers must have actually spanned the transition
            assert r.reads >= 2 * len(keys), (r.label, r.reads)

        # volume really is gone — reads are served by the EC set alone
        assert all(vs.store.find_volume(vid) is None for vs in volume_servers)
        locs = master.topology.lookup_ec_shards(vid)
        assert locs is not None
        assert sum(1 for l in locs.locations if l) == 14


class TestHarnessSensitivity:
    def test_misordered_pipeline_breaks_reads(self, cluster):
        """Delete-before-mount (the ordering bug the pipeline exists to
        prevent) must surface as reader failures — otherwise the zero-
        failure assertion above proves nothing."""
        import grpc

        from seaweedfs_tpu.pb import rpc, volume_pb2

        master, volume_servers = cluster
        vid, keys, source_url = write_keyset(master.port, "mig2", n=20)
        source = next(
            vs for vs in volume_servers if f"127.0.0.1:{vs.port}" == source_url
        )

        def misordered():
            with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
                stub = rpc.volume_stub(ch)
                stub.VolumeMarkReadonly(
                    volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
                )
                stub.VolumeEcShardsGenerate(
                    volume_pb2.VolumeEcShardsGenerateRequest(
                        volume_id=vid, collection="mig2"
                    )
                )
            # WRONG: drop the volume from every replica before any
            # shard is mounted anywhere
            for vs in volume_servers:
                with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
                    rpc.volume_stub(ch).VolumeDelete(
                        volume_pb2.VolumeDeleteRequest(volume_id=vid)
                    )
            time.sleep(1.0)  # the unavailability window the readers see
            # recover: mount the generated shards on the source
            with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
                rpc.volume_stub(ch).VolumeEcShardsMount(
                    volume_pb2.VolumeEcShardsMountRequest(
                        volume_id=vid,
                        collection="mig2",
                        shard_ids=list(range(14)),
                    )
                )

        readers = [
            HammerReader(f"http://127.0.0.1:{master.port}", keys, "via-master")
        ]
        run_with_readers(readers, misordered, settle=1.0)

        assert readers[0].failures, (
            "misordered pipeline produced no read failures — the "
            "availability harness cannot detect ordering bugs"
        )
        # and the tail reads recovered once the shards were mounted
        with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/{next(iter(keys))}", timeout=10
        ) as r:
            assert r.status == 200


class TestS3MigrationAvailability:
    """BASELINE config 5's literal wording: '…under concurrent S3
    GETs'. The same zero-unavailability property through the full
    gateway stack — S3 → filer chunk reads → volume/EC — while every
    volume of the objects' collection runs the encode pipeline."""

    def test_s3_reads_stay_green_through_migration(
        self, cluster, tmp_path_factory
    ):
        from seaweedfs_tpu.s3api.s3api_server import S3ApiServer
        from seaweedfs_tpu.server.filer_server import FilerServer

        master, volume_servers = cluster
        fport = free_port()
        filer = FilerServer(
            [f"127.0.0.1:{master.port}"],
            port=fport,
            store="memory",
            collection="migs3",
            max_mb=1,
        )
        filer.start()
        s3port = free_port()
        s3 = S3ApiServer(filer=f"127.0.0.1:{fport}", port=s3port)
        s3.start()
        try:
            base = f"http://127.0.0.1:{s3port}"
            urllib.request.urlopen(
                urllib.request.Request(f"{base}/migbkt", method="PUT"),
                timeout=10,
            ).close()
            keys: dict[str, bytes] = {}
            for i in range(18):
                body = (f"s3 object {i} ".encode() * 931)[: 11_000 + 37 * i]
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"{base}/migbkt/obj-{i}.bin",
                        data=body,
                        method="PUT",
                    ),
                    timeout=10,
                ).close()
                keys[f"migbkt/obj-{i}.bin"] = body

            # every volume of the collection gets migrated under load
            env = CommandEnv([f"127.0.0.1:{master.port}"])
            dump = env.collect_topology()
            vids = sorted(
                {
                    v["Id"]
                    for n in dump.nodes
                    for v in n.volumes
                    if v["Collection"] == "migs3"
                }
            )
            assert vids, "no volumes grown for the S3 collection"

            def pipeline():
                for vid in vids:
                    do_ec_encode(env, vid, "migs3", io.StringIO())

            readers = [HammerReader(base, keys, "s3")]
            run_with_readers(readers, pipeline, settle=1.0)

            assert readers[0].failures == [], readers[0].failures[:10]
            assert readers[0].reads >= 2 * len(keys)
            # the volumes really are EC now
            for vid in vids:
                locs = master.topology.lookup_ec_shards(vid)
                assert locs is not None, vid
        finally:
            s3.stop()
            filer.stop()
