"""weedguard: gray-failure detection, health-scored placement, hinted
handoff, lame-duck degradation, and drain (docs/HEALTH.md).

Units for the phi-accrual detector, the node state machine (with
hysteresis and the WEED_HEALTH=0 kill switch), the disk watchdog, the
health-filtered pick_for_write, and the hint spool; weedcrash
enumerator sweeps of the hint publish (write→ack→crash→replay must
never lose an acked write, and the pre-durable ordering must be
DETECTED); and live-cluster acceptance: a write succeeds during a
single-replica outage via hinted handoff and replays byte-identical
after heal, node.drain empties a server with repair-queue evidence,
and WEED_HEALTH=0 restores the pre-health all-or-error write contract.
"""

from __future__ import annotations

import errno
import io
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.cluster import health as health_mod
from seaweedfs_tpu.cluster.health import (
    DiskWatchdog,
    HealthPlane,
    NodeHealth,
    PhiAccrual,
)
from seaweedfs_tpu.server.handoff import HintStore
from tests import chaos as wiring
from tests.chaos import free_port, wait_for


# ---------------------------------------------------------------------------
# phi-accrual detector


class TestPhiAccrual:
    def _warm(self, interval=0.2, n=20, start=100.0):
        p = PhiAccrual()
        t = start
        for _ in range(n):
            p.observe(t)
            t += interval
        return p, t

    def test_no_history_is_zero(self):
        p = PhiAccrual()
        assert p.phi(100.0) == 0.0
        p.observe(100.0)
        assert p.phi(105.0) == 0.0  # < MIN_SAMPLES intervals

    def test_on_cadence_is_low(self):
        p, t = self._warm()
        assert p.phi(t + 0.05) < 2.0

    def test_silence_grows_suspicion(self):
        p, t = self._warm(interval=0.2)
        # within ~2.5 missed beats the phi crosses the default
        # threshold (the ≤3-heartbeat-interval detection bound)
        assert p.phi(t + 3 * 0.2) > health_mod.phi_threshold()
        # and keeps growing without bound (the erfc-underflow branch)
        assert p.phi(t + 10 * 0.2) > p.phi(t + 3 * 0.2) > 0

    def test_warmed_and_gate_track_learned_cadence(self):
        # the observable warm-up barrier + detection horizon the gray-
        # failure e2e bounds itself against (a fixed-sleep warm-up and
        # a configured-beat bound both flake under rig load)
        p = PhiAccrual()
        assert not p.warmed() and p.gate_s() == 0.0
        p, t = self._warm(interval=0.2, n=20)
        assert p.warmed()
        # gate = _GATE_FACTOR x worst observed gap, not the mean
        assert p.gate_s() == pytest.approx(2.0 * 0.2, rel=1e-6)
        # one load-stretched (but not yet suspicious) beat widens it;
        # _warm's last arrival was at t - 0.2
        p.observe(t - 0.2 + 0.35)
        assert p.gate_s() == pytest.approx(2.0 * 0.35, rel=1e-6)

    def test_outage_resume_interval_not_recorded(self):
        """The beat ENDING a flagged silence (SIGCONT, rejoin after a
        crash) must not enter the cadence ring: recording the outage
        length would raise the 2×-worst-gap gate to outage scale and
        blind the NEXT gray failure for a whole ring."""
        p, t = self._warm(interval=0.2)
        gate_before = max(p._intervals)
        # a 30 s outage ends with one beat
        p.observe(t + 30.0)
        assert max(p._intervals) == gate_before  # outage not cadence
        # detection sensitivity survives: silence right after the
        # resume still reads suspicious on the learned 0.2 s cadence
        assert p.phi(t + 30.0 + 1.0) > health_mod.phi_threshold()

    def test_persistent_cadence_change_relearned(self):
        """A legitimately slower cadence (operator restarted with a
        bigger -heartbeat) must not read suspect forever: after a few
        skipped intervals the ring re-learns."""
        p, t = self._warm(interval=0.2)
        tt = t
        for _ in range(30):  # new cadence: 2 s beats
            tt += 2.0
            p.observe(tt)
        # the ring absorbed the new cadence; on-cadence silence is calm
        assert p.phi(tt + 1.0) < health_mod.phi_threshold()

    def test_jittery_cadence_needs_more_silence(self):
        # irregular beats widen the learned std: the same absolute
        # silence reads less suspicious than under a metronome
        steady, t1 = self._warm(interval=0.2)
        jittery = PhiAccrual()
        t = 100.0
        import random

        rng = random.Random(7)
        for _ in range(20):
            jittery.observe(t)
            t += 0.1 + rng.random() * 0.2
        assert jittery.phi(t + 0.6) < steady.phi(t1 + 0.6)


# ---------------------------------------------------------------------------
# node state machine


class TestNodeState:
    def _beaten(self, interval=0.2, n=20, now=1000.0):
        rec = NodeHealth("n1")
        t = now - n * interval
        for _ in range(n):
            rec.observe(t)
            t += interval
        return rec, t

    def test_healthy_on_cadence(self):
        rec, t = self._beaten()
        assert rec.state(t + 0.05) == health_mod.HEALTHY
        assert rec.assignable(t + 0.05)
        assert not rec.read_demoted(t + 0.05)

    def test_silence_goes_suspect_and_holds(self):
        rec, t = self._beaten()
        assert rec.state(t + 1.0) == health_mod.SUSPECT
        assert not rec.assignable(t + 1.0)
        assert rec.read_demoted(t + 1.0)
        # hysteresis: one clean beat right after does NOT flip back —
        # the suspicion holds for recover_s
        rec.observe(t + 1.0)
        assert rec.state(t + 1.05) == health_mod.SUSPECT
        # ...but after the hold-down with clean signals it recovers
        tt = t + 1.0
        for _ in range(40):
            tt += 0.2
            rec.observe(tt)
        assert rec.state(tt + 0.05) == health_mod.HEALTHY

    def test_error_ewma_goes_suspect(self):
        rec, t = self._beaten()
        # a burst of IO errors between two beats spikes the EWMA
        rec.observe(t + 0.2, io_errors=50, request_errors=0)
        assert rec.err_ewma > health_mod.err_ewma_threshold()
        assert rec.state(t + 0.25) == health_mod.SUSPECT
        assert "err_ewma" in ";".join(rec.suspicion_reasons(t + 0.25))

    def test_counter_reset_not_an_error_burst(self):
        rec, t = self._beaten()
        rec.observe(t + 0.2, io_errors=50)
        ewma = rec.err_ewma
        # the node restarted: counters reset to 0 — must not read as
        # another burst (or as negative)
        rec.observe(t + 0.4, io_errors=0)
        assert rec.err_ewma < ewma

    def test_lame_duck_and_draining_unassignable_but_not_demoted(self):
        rec, t = self._beaten()
        rec.observe(t + 0.2, lame_duck=True)
        assert not rec.assignable(t + 0.25)
        # reads keep flowing to a lame duck — only suspicion demotes
        assert not rec.read_demoted(t + 0.25)
        rec.observe(t + 0.4, lame_duck=False, draining=True)
        assert not rec.assignable(t + 0.45)

    def test_kill_switch_restores_pre_health(self, monkeypatch):
        rec, t = self._beaten()
        assert rec.state(t + 5.0) == health_mod.SUSPECT
        rec.lame_duck = True
        monkeypatch.setenv("WEED_HEALTH", "0")
        assert rec.state(t + 5.0) == health_mod.HEALTHY
        assert rec.assignable(t + 5.0)
        assert not rec.read_demoted(t + 5.0)

    def test_dead_beats_everything(self):
        rec, t = self._beaten()
        rec.dead = True
        assert rec.state(t) == health_mod.DEAD
        assert not rec.assignable(t)


class TestHealthPlane:
    def test_order_nodes_demotes_suspects(self):
        hp = HealthPlane()

        class DN:
            def __init__(self, url):
                self.url = url

        now = time.monotonic()
        for url in ("a:1", "b:2"):
            rec = hp._get(url)
            t = now - 8.0
            for _ in range(20):
                rec.observe(t)
                t += 0.2
        # b stays silent ~4s past its 0.2s cadence; a beats up to now
        hp._get("a:1").observe(now)
        nodes = [DN("b:2"), DN("a:1")]
        ordered = hp.order_nodes(nodes)
        assert [d.url for d in ordered] == ["a:1", "b:2"]
        assert hp.suspect("b:2") and not hp.suspect("a:1")

    def test_unknown_nodes_are_healthy(self):
        hp = HealthPlane()
        assert hp.state("never:seen") == health_mod.HEALTHY
        assert hp.assignable("never:seen")

    def test_drain_registry(self):
        hp = HealthPlane()
        hp.request_drain("x:1")
        assert hp.draining_urls() == {"x:1"}
        hp.request_drain("x:1", stop=True)
        assert hp.draining_urls() == set()


# ---------------------------------------------------------------------------
# disk watchdog


class TestDiskWatchdog:
    def test_disk_class_strikes_trip_lame_duck(self):
        wd = DiskWatchdog(strikes=3, window_s=60)
        tripped = []
        wd.on_trip = lambda: tripped.append(1)
        assert wd.note_io_error(OSError(errno.EIO, "eio"))
        assert not wd.lame_duck
        assert wd.note_io_error(OSError(errno.ENOSPC, "enospc"))
        assert wd.note_io_error(OSError(errno.EIO, "eio"))
        assert wd.lame_duck and tripped == [1]
        assert wd.io_errors == 3

    def test_non_disk_errors_ignored(self):
        from seaweedfs_tpu.util.deadline import DeadlineExceeded

        wd = DiskWatchdog(strikes=1)
        assert not wd.note_io_error(ConnectionResetError("peer"))
        assert not wd.note_io_error(DeadlineExceeded("budget"))
        assert not wd.note_io_error(OSError(errno.ENOENT, "missing"))
        assert not wd.lame_duck and wd.io_errors == 0

    def test_window_decay(self):
        wd = DiskWatchdog(strikes=3, window_s=0.05)
        wd.note_io_error(OSError(errno.EIO, "x"))
        wd.note_io_error(OSError(errno.EIO, "x"))
        time.sleep(0.08)  # the first two strikes age out
        wd.note_io_error(OSError(errno.EIO, "x"))
        assert not wd.lame_duck


# ---------------------------------------------------------------------------
# health-filtered pick_for_write


class TestHealthPick:
    def _layout(self):
        from seaweedfs_tpu.storage.store import VolumeInfo
        from seaweedfs_tpu.topology.node import DataNode
        from seaweedfs_tpu.topology.volume_layout import VolumeLayout

        lay = VolumeLayout("000", "", 1 << 30)
        nodes = {}
        for vid, url in ((1, "a:1"), (2, "b:2"), (3, "c:3")):
            dn = nodes[url] = DataNode(url)
            dn.ip, dn.port = url.split(":")[0], int(url.split(":")[1])
            lay.register_volume(
                VolumeInfo(
                    id=vid, size=0, collection="", file_count=0,
                    delete_count=0, deleted_byte_count=0, read_only=False,
                    replica_placement=0, version=3, ttl=0,
                ),
                dn,
            )
        return lay, nodes

    class _FakeHealth:
        def __init__(self, bad):
            self.bad = set(bad)

        def assignable(self, url):
            return url not in self.bad

    def test_suspect_replica_volumes_excluded(self):
        lay, nodes = self._layout()
        fake = self._FakeHealth({"b:2"})
        picked = {
            lay.pick_for_write(policy="random", health=fake)[0]
            for _ in range(50)
        }
        assert picked == {1, 3}
        picked_p2c = {
            lay.pick_for_write(policy="p2c", health=fake)[0]
            for _ in range(50)
        }
        assert picked_p2c == {1, 3}

    def test_all_tainted_falls_back_to_full_pool(self):
        lay, _ = self._layout()
        fake = self._FakeHealth({"a:1", "b:2", "c:3"})
        # availability beats precision: every volume touches a suspect,
        # so the full writable set comes back rather than an error
        picked = {
            lay.pick_for_write(policy="random", health=fake)[0]
            for _ in range(60)
        }
        assert picked == {1, 2, 3}

    def test_health_none_is_pre_health(self):
        lay, _ = self._layout()
        picked = {
            lay.pick_for_write(policy="random", health=None)[0]
            for _ in range(60)
        }
        assert picked == {1, 2, 3}


# ---------------------------------------------------------------------------
# hint spool units


class TestHintStore:
    def test_roundtrip_and_pending(self, tmp_path):
        hs = HintStore(str(tmp_path / "spool"))
        body = b"\x00binary body\xff" * 100
        assert hs.write_hint(
            "10.0.0.9:8080", "POST", "/3,aabb?type=replicate", body,
            {"Content-Type": "image/png", "Seaweed-k": "v"},
        )
        assert hs.pending() == {"10.0.0.9:8080": 1}
        (target, tdir), = hs.targets()
        assert target == "10.0.0.9:8080"
        (name,) = [
            e.name for e in os.scandir(tdir) if e.name.endswith(".hint")
        ]
        head, got = hs.read_hint(os.path.join(tdir, name))
        assert got == body
        assert head["method"] == "POST"
        assert head["path"] == "/3,aabb?type=replicate"
        assert head["headers"]["Content-Type"] == "image/png"
        hs.remove(os.path.join(tdir, name))
        assert hs.pending() == {}

    def test_replay_order_is_arrival_order(self, tmp_path):
        hs = HintStore(str(tmp_path / "spool"))
        for i in range(5):
            assert hs.write_hint(
                "t:1", "POST", f"/1,{i:04x}?type=replicate",
                b"x%d" % i, {},
            )
        (_, tdir), = hs.targets()
        names = sorted(
            e.name for e in os.scandir(tdir) if e.name.endswith(".hint")
        )
        paths = [hs.read_hint(os.path.join(tdir, n))[0]["path"] for n in names]
        assert paths == [f"/1,{i:04x}?type=replicate" for i in range(5)]

    def test_spool_cap_refuses(self, tmp_path, monkeypatch):
        monkeypatch.setenv("WEED_HANDOFF_MAX_MB", "0")
        hs = HintStore(str(tmp_path / "spool"))
        assert not hs.write_hint("t:1", "POST", "/1,aa", b"x" * 10, {})
        assert hs.pending() == {}

    def test_torn_hint_reads_none(self, tmp_path):
        hs = HintStore(str(tmp_path / "spool"))
        tdir = tmp_path / "spool" / "t_1"
        tdir.mkdir(parents=True)
        (tdir / "000-000001.hint").write_bytes(b"\x00\x00\x01")
        assert hs.read_hint(str(tdir / "000-000001.hint")) is None

    def test_replay_resigns_on_signed_clusters(self, tmp_path):
        """A hint's spooled CLIENT JWT outlives its validity during a
        long outage; the agent replaces it with a server-signed token
        at replay time (the delete-cascade convention) so the spool
        can't wedge on 401s."""
        import socket
        import threading

        from seaweedfs_tpu.server.handoff import HandoffAgent

        seen = {}
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)

        def serve():
            c, _ = lst.accept()
            data = b""
            while b"\r\n\r\n" not in data:
                data += c.recv(65536)
            head = data.split(b"\r\n\r\n", 1)[0].decode("latin-1")
            for line in head.split("\r\n")[1:]:
                k, _, v = line.partition(":")
                seen[k.strip().lower()] = v.strip()
            n = int(seen.get("content-length", "0"))
            body = data.split(b"\r\n\r\n", 1)[1]
            while len(body) < n:
                body += c.recv(65536)
            c.sendall(b"HTTP/1.1 201 Created\r\nContent-Length: 0\r\n\r\n")
            c.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        target = "127.0.0.1:%d" % lst.getsockname()[1]
        hs = HintStore(str(tmp_path / "spool"))
        assert hs.write_hint(
            target, "POST", "/5,00ff?type=replicate", b"signed body",
            {"Authorization": "BEARER stale-client-token"},
        )
        agent = HandoffAgent(
            hs, sign=lambda fid: f"BEARER fresh-for-{fid}"
        )
        assert agent.run_once() == 1
        t.join(timeout=5)
        assert seen.get("authorization") == "BEARER fresh-for-5,00ff"
        assert hs.pending() == {}
        lst.close()

    def test_live_target_4xx_drops_instead_of_wedging(self, tmp_path):
        """A target that is UP but refuses a hint with a 4xx (the
        volume moved off it, auth revoked) must not block the queue:
        the rejected hint is dropped loudly and later hints for the
        same target still deliver."""
        import socket
        import threading

        from seaweedfs_tpu.server.handoff import HandoffAgent

        statuses = [b"404 Not Found", b"201 Created"]
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)

        def serve():
            for st in statuses:
                c, _ = lst.accept()
                data = b""
                while b"\r\n\r\n" not in data:
                    data += c.recv(65536)
                head = data.split(b"\r\n\r\n", 1)[0].decode("latin-1")
                n = 0
                for line in head.split("\r\n")[1:]:
                    k, _, v = line.partition(":")
                    if k.strip().lower() == "content-length":
                        n = int(v.strip())
                body = data.split(b"\r\n\r\n", 1)[1]
                while len(body) < n:
                    body += c.recv(65536)
                c.sendall(
                    b"HTTP/1.1 " + st + b"\r\nContent-Length: 0\r\n\r\n"
                )
                c.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        target = "127.0.0.1:%d" % lst.getsockname()[1]
        hs = HintStore(str(tmp_path / "spool"))
        assert hs.write_hint(target, "POST", "/9,dead?type=replicate",
                             b"moved away", {})
        assert hs.write_hint(target, "POST", "/9,beef?type=replicate",
                             b"still deliverable", {})
        agent = HandoffAgent(hs)
        # one pass: hint 1 rejected (dropped), hint 2 delivered
        assert agent.run_once() == 1
        t.join(timeout=5)
        assert hs.pending() == {}
        lst.close()

    def test_handoff_disabled_by_kill_switches(self, monkeypatch):
        from seaweedfs_tpu.server import handoff

        assert handoff.handoff_enabled()
        monkeypatch.setenv("WEED_HANDOFF", "0")
        assert not handoff.handoff_enabled()
        monkeypatch.delenv("WEED_HANDOFF")
        monkeypatch.setenv("WEED_HEALTH", "0")
        assert not handoff.handoff_enabled()


# ---------------------------------------------------------------------------
# weedcrash enumerator sweeps of the hint lifecycle


class TestHintCrashSweeps:
    def test_durable_hint_publish_clean(self):
        from seaweedfs_tpu.analysis import crash

        rep = crash.run_handoff_hint(budget=96)
        assert rep.states_tested >= 12
        assert rep.violations == []

    def test_unsynced_hint_publish_detected(self):
        """Regression proof the durable.publish is load-bearing: the
        same hint written with a bare write+rename must yield
        rename-before-data states with a torn hint."""
        from seaweedfs_tpu.analysis import crash

        rep = crash.run_handoff_hint(budget=96, durable=False)
        assert rep.violations, (
            "the unsynced hint publish should be catchable — either "
            "the enumerator went blind or HintStore stopped writing "
            "through the recorded os layer"
        )

    def test_delivery_unlink_sticks(self):
        from seaweedfs_tpu.analysis import crash

        rep = crash.run_handoff_delivery(budget=64)
        assert rep.violations == []


# ---------------------------------------------------------------------------
# live-cluster acceptance


def _http(url, data=None, method="GET", timeout=10, headers=None):
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


class TestHintedHandoffE2E:
    def test_write_survives_replica_outage_and_replays(
        self, tmp_path_factory
    ):
        """Acceptance: with one replica refusing connections, a
        replicated write still succeeds (hint spooled durably on the
        primary); after heal the handoff agent replays it and the
        replica serves the exact bytes."""
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(
            tmp_path_factory, maddr, "ha", rack="r0"
        )
        vs_b, pair = wiring.proxied_volume_server(
            tmp_path_factory, maddr, "hb", rack="r1"
        )
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            # a healthy replicated write first, so the volume exists
            # with both replicas registered
            a = json.loads(
                _http(f"http://{maddr}/dir/assign?replication=010")[1]
            )
            assert not a.get("error"), a
            payload0 = b"healthy replicated write " * 20
            _http(f"http://{a['url']}/{a['fid']}", data=payload0,
                  method="POST")
            vid = a["fid"].split(",")[0]

            # now the replica "host" goes down: connections refused
            pair.http.refuse = True
            pair.grpc.refuse = True

            a2 = json.loads(
                _http(f"http://{maddr}/dir/assign?replication=010")[1]
            )
            assert not a2.get("error"), a2
            payload = b"write during outage \x00\xfe" * 64
            # drive the PRIMARY side explicitly (vs_a) so the fan-out
            # toward the dead replica is what the hint absorbs
            t0 = time.time()
            status, body = _http(
                f"http://127.0.0.1:{vs_a.port}/{a2['fid']}",
                data=payload, method="POST", timeout=30,
            )
            assert status == 201, body
            # the ack required a durable hint, not a replica round-trip
            assert vs_a.hints.pending(), "no hint spooled for the outage"
            assert time.time() - t0 < 15

            # read-back from the healthy primary: the acked write lives
            status, got = _http(f"http://127.0.0.1:{vs_a.port}/{a2['fid']}")
            assert status == 200 and got == payload

            # heal → the agent replays → the REPLICA serves the bytes
            pair.http.refuse = False
            pair.grpc.refuse = False
            assert wait_for(lambda: not vs_a.hints.pending(), 20), (
                "hint never replayed after heal"
            )

            def replica_has_it():
                try:
                    s, g = _http(
                        f"http://127.0.0.1:{vs_b.port}/{a2['fid']}",
                        timeout=5,
                    )
                    return s == 200 and g == payload
                except (OSError, urllib.error.HTTPError):
                    return False

            assert wait_for(replica_has_it, 20), (
                "replica not byte-identical after handoff replay"
            )
            assert vs_a.handoff.replayed >= 1
            assert int(vid) >= 1  # vid parsed (the first write landed)
        finally:
            pair.stop()
            vs_b.stop()
            vs_a.stop()
            master.stop()

    def test_health_off_restores_all_or_error(
        self, tmp_path_factory, monkeypatch
    ):
        """WEED_HEALTH=0 regression: the same outage fails the write
        like pre-health code did (no hint, 500 to the client)."""
        monkeypatch.setenv("WEED_HEALTH", "0")
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(
            tmp_path_factory, maddr, "hka", rack="r0"
        )
        vs_b, pair = wiring.proxied_volume_server(
            tmp_path_factory, maddr, "hkb", rack="r1"
        )
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            a = json.loads(
                _http(f"http://{maddr}/dir/assign?replication=010")[1]
            )
            assert not a.get("error"), a
            _http(f"http://{a['url']}/{a['fid']}", data=b"seed",
                  method="POST")
            pair.http.refuse = True
            pair.grpc.refuse = True
            a2 = json.loads(
                _http(f"http://{maddr}/dir/assign?replication=010")[1]
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                _http(
                    f"http://127.0.0.1:{vs_a.port}/{a2['fid']}",
                    data=b"must fail", method="POST", timeout=30,
                )
            assert ei.value.code == 500
            assert not vs_a.hints.pending()
        finally:
            pair.stop()
            vs_b.stop()
            vs_a.stop()
            master.stop()


class TestLameDuckE2E:
    def test_lame_duck_sheds_writes_serves_reads(self, tmp_path_factory):
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs = wiring.start_volume_server(tmp_path_factory, maddr, "ld")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 1)
            a = json.loads(_http(f"http://{maddr}/dir/assign")[1])
            _http(f"http://{a['url']}/{a['fid']}", data=b"pre-duck",
                  method="POST")
            # three EIO strikes flip the watchdog
            for _ in range(3):
                vs.watchdog.note_io_error(OSError(errno.EIO, "dying disk"))
            assert vs.watchdog.lame_duck
            # writes shed with 503 + Retry-After...
            a2 = json.loads(_http(f"http://{maddr}/dir/assign")[1])
            with pytest.raises(urllib.error.HTTPError) as ei:
                _http(f"http://{vs.host}:{vs.port}/{a2['fid']}",
                      data=b"x", method="POST")
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After")
            # ...reads keep flowing
            status, got = _http(f"http://{a['url']}/{a['fid']}")
            assert status == 200 and got == b"pre-duck"
            # the flag rides the heartbeat; the master stops assigning
            assert wait_for(
                lambda: not master.health.assignable(f"{vs.host}:{vs.port}"),
                10,
            )
            payload = json.loads(_http(f"http://{maddr}/cluster/health")[1])
            row = payload["NodeHealth"]["Nodes"][f"{vs.host}:{vs.port}"]
            assert row["LameDuck"] is True
            # /status surfaces it locally too
            st = json.loads(_http(f"http://{vs.host}:{vs.port}/status")[1])
            assert st["LameDuck"] is True and st["IoErrors"] >= 3
        finally:
            vs.stop()
            master.stop()


class TestLookupDemotionE2E:
    def test_suspect_marked_and_ordered_last(self, tmp_path_factory):
        """The master's lookup responses (HTTP + gRPC) order suspect
        replicas last and carry the `suspect` mark — the cluster-wide
        demotion clients and the eager-hedge lever read."""
        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(
            tmp_path_factory, maddr, "lka", rack="r0"
        )
        vs_b = wiring.start_volume_server(
            tmp_path_factory, maddr, "lkb", rack="r1"
        )
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            a = json.loads(
                _http(f"http://{maddr}/dir/assign?replication=010")[1]
            )
            assert not a.get("error"), a
            _http(f"http://{a['url']}/{a['fid']}", data=b"x", method="POST")
            vid = a["fid"].split(",")[0]
            b_url = f"{vs_b.host}:{vs_b.port}"
            # force suspicion on B (the hysteresis hold is the lever
            # the state machine itself exposes)
            master.health._get(b_url)._suspect_until = (
                time.monotonic() + 60
            )
            lk = json.loads(
                _http(f"http://{maddr}/dir/lookup?volumeId={vid}")[1]
            )
            assert [l["suspect"] for l in lk["locations"]] == [False, True]
            assert lk["locations"][-1]["url"] == b_url
            # gRPC carries the same verdict (what filer/stream reads)
            op._lookup_cache.clear()
            res = op.lookup(maddr, vid)
            assert [l["suspect"] for l in res.locations] == [False, True]
            # ...and the suspect-bearing result is cached SHORT, so the
            # verdict refreshes on heartbeat timescales
            key = (maddr, vid)
            entry = op._lookup_cache[key]
            assert entry.expires - time.time() < 30
        finally:
            vs_b.stop()
            vs_a.stop()
            master.stop()


class TestDrainE2E:
    def test_node_drain_empties_server_with_evidence(
        self, tmp_path_factory
    ):
        """Acceptance: node.drain marks the node, the RepairScheduler
        moves its volumes off, the shell prints repair-queue evidence,
        and every blob stays readable."""
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import run_command

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0,
            repair_interval=0.3, repair_grace=0.1,
        )
        master.repair.cooldown = 1.0
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(tmp_path_factory, maddr, "da")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 1)
            blobs = {}
            for i in range(6):
                a = json.loads(_http(f"http://{maddr}/dir/assign")[1])
                assert not a.get("error"), a
                payload = f"drain-me-{i:03d} ".encode() * 30
                _http(f"http://{a['url']}/{a['fid']}", data=payload,
                      method="POST")
                blobs[a["fid"]] = payload
            a_url = f"{vs_a.host}:{vs_a.port}"
            dn_a = next(
                d for d in master.topology.data_nodes() if d.url == a_url
            )
            assert dn_a.volumes, "no volumes landed on A"

            # B joins as the drain target
            vs_b = wiring.start_volume_server(tmp_path_factory, maddr, "db")
            try:
                assert wait_for(
                    lambda: len(master.topology.data_nodes()) == 2
                )
                env = CommandEnv([maddr])
                out = io.StringIO()
                run_command(env, f"node.drain -node {a_url} -wait 60", out)
                text = out.getvalue()
                assert "draining" in text
                assert "moved: drain_move" in text, text
                assert "is empty" in text, text
                # the node really is empty (master view)
                assert not dn_a.volumes
                # assignment no longer targets A
                for _ in range(5):
                    a = json.loads(_http(f"http://{maddr}/dir/assign")[1])
                    assert a["url"] != a_url
                # every blob survived the move, byte-identical (the
                # layout learns the moved locations from the nodes'
                # next beats — poll briefly)
                def urls_of(vid):
                    lk = json.loads(
                        _http(
                            f"http://{maddr}/dir/lookup?volumeId={vid}"
                        )[1]
                    )
                    return [l["url"] for l in lk["locations"]]

                for fid, want in blobs.items():
                    vid = fid.split(",")[0]
                    assert wait_for(
                        lambda: a_url not in urls_of(vid), 15
                    ), (fid, urls_of(vid))
                    status, got = _http(f"http://{urls_of(vid)[0]}/{fid}")
                    assert status == 200 and got == want, fid
                # repair-queue evidence exists on the master surface too
                rq = json.loads(_http(f"http://{maddr}/repair/queue")[1])
                assert any(
                    h["Kind"] == "drain_move" for h in rq.get("History", [])
                )
            finally:
                vs_b.stop()
        finally:
            vs_a.stop()
            master.stop()


class TestDrainReplicatedE2E:
    def test_surplus_replica_dropped_blocked_without_capacity(
        self, tmp_path_factory
    ):
        """Replicated volumes under drain: a copy whose placement is
        already satisfied by OTHER holders is dropped (that IS the
        move); one still needed blocks loudly instead of breaking
        placement."""
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import run_command

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0,
            repair_interval=0.3, repair_grace=0.1,
        )
        master.repair.cooldown = 1.0
        master.repair.backoff_base = 0.5
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(tmp_path_factory, maddr, "ra")
        vs_b = wiring.start_volume_server(tmp_path_factory, maddr, "rb")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            a = json.loads(_http(f"http://{maddr}/dir/assign")[1])
            assert not a.get("error"), a
            payload = b"surplus copy " * 30
            _http(f"http://{a['url']}/{a['fid']}", data=payload,
                  method="POST")
            vid = int(a["fid"].split(",")[0])
            src_url = a["url"]
            other = next(
                d.url
                for d in master.topology.data_nodes()
                if d.url != src_url
            )
            env = CommandEnv([maddr])
            # duplicate the volume onto the other node: placement wants
            # 1 copy, so the original becomes surplus
            out = io.StringIO()
            run_command(env, f"volume.copy -volumeId {vid} "
                             f"-from {src_url} -to {other}", out)
            assert wait_for(
                lambda: len(master.topology.lookup("", vid)) == 2, 15
            )
            out = io.StringIO()
            run_command(env, f"node.drain -node {src_url} -wait 60", out)
            # the surplus copy was DROPPED (no spare node exists to
            # move it to), the drain completed, bytes survive on the
            # other holder
            assert "is empty" in out.getvalue(), out.getvalue()
            status, got = _http(f"http://{other}/{a['fid']}")
            assert status == 200 and got == payload
        finally:
            vs_b.stop()
            vs_a.stop()
            master.stop()


class TestDrainEcE2E:
    def test_drain_moves_ec_shards_off(self, tmp_path_factory):
        """drain_ec: every EC shard the draining node holds moves to a
        target (copy+mount then unmount+delete), and degraded reads of
        the keyset stay byte-identical afterwards."""
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0,
            repair_interval=0.3, repair_grace=0.1,
        )
        master.repair.cooldown = 1.0
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(tmp_path_factory, maddr, "ea")
        vs_b = wiring.start_volume_server(tmp_path_factory, maddr, "eb")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            vid, keys = wiring.seed_ec_volume(master, "drainec")
            assert wait_for(
                lambda: wiring.registered_shards(master, vid) == 14, 30
            )
            a_url = f"{vs_a.host}:{vs_a.port}"
            dn_a = next(
                d for d in master.topology.data_nodes() if d.url == a_url
            )
            assert dn_a.ec_shards, "A holds no ec shards"
            _http(f"http://{maddr}/node/drain?node={a_url}")
            assert wait_for(lambda: not dn_a.ec_shards, 60), (
                master.repair.queue_snapshot()
            )
            # every shard is mounted somewhere (B) and the data reads
            # back byte-identical through the degraded/normal path
            assert wait_for(
                lambda: wiring.registered_shards(master, vid) == 14, 30
            )
            for fid, want in keys.items():
                got = wiring.read_blob([maddr], fid, collection="drainec")
                assert got == want, fid
            rq = json.loads(_http(f"http://{maddr}/repair/queue")[1])
            assert any(
                h["Kind"] == "drain_ec" for h in rq.get("History", [])
            )
        finally:
            vs_b.stop()
            vs_a.stop()
            master.stop()


class TestVolumeDrainMethod:
    def test_drain_announces_sheds_and_exits(self, tmp_path_factory):
        """VolumeServer.drain(): the draining flag rides a forced beat
        (master excludes the node), new writes shed 503, and the server
        stops cleanly."""
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs = wiring.start_volume_server(tmp_path_factory, maddr, "dm")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 1)
            a = json.loads(_http(f"http://{maddr}/dir/assign")[1])
            _http(f"http://{a['url']}/{a['fid']}", data=b"pre-drain",
                  method="POST")
            url = f"{vs.host}:{vs.port}"
            import threading

            t = threading.Thread(target=lambda: vs.drain(timeout=10))
            t.start()
            assert wait_for(
                lambda: not master.health.assignable(url), 10
            ), "master never saw the draining flag"
            t.join(timeout=20)
            assert not t.is_alive()
            # deregistered: the node left the topology
            assert wait_for(
                lambda: all(
                    d.url != url for d in master.topology.data_nodes()
                ),
                10,
            )
        finally:
            try:
                vs.stop()
            except Exception:  # noqa: BLE001 — already stopped by drain
                pass
            master.stop()
