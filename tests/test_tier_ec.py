"""EC volume tiering (seaweedfs_tpu/tier/, docs/TIERING.md): lifecycle
rules, the store-level tier-out/tier-in engine against the local-dir
backend fake, crash/restart discovery through the durable ``.evf``
sidecar, CRC verification against the ``.ecc`` scrub sidecar on
recall, and chaos-backend degradation (an erroring backend must
degrade reads, never quarantine local state).
"""

from __future__ import annotations

import os
import random

import pytest

from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.ec.ec_volume import NotEnoughShards
from seaweedfs_tpu.ec.ecc_sidecar import write_sidecar
from seaweedfs_tpu.stats.metrics import (
    TIER_REMOTE_READ_ERRORS,
    TIER_REMOTE_READS,
)
from seaweedfs_tpu.storage import backend as bk
from seaweedfs_tpu.storage.backend_chaos import BackendFault, ChaosBackendStorage
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.tier import TierRules, tier_enabled
from seaweedfs_tpu.tier.ec_tier import (
    tier_in_ec,
    tier_out_ec,
    tier_status,
    tiered_volume_count,
)
from seaweedfs_tpu.util.crc import crc32c

VID = 7


def _file_crc(path):
    with open(path, "rb") as f:
        return crc32c(f.read())


def _ec_store(tmp_path, n_needles=30, vid=VID, seed=11, with_ecc=True):
    """Sealed EC volume on disk (no .dat/.idx), loaded into a Store —
    the test_ec_degraded fixture pattern plus the .ecc sidecar the
    tier-in CRC gate verifies against."""
    d = str(tmp_path / "vols")
    os.makedirs(d, exist_ok=True)
    v = Volume(d, vid)
    rng = random.Random(seed)
    payload = {}
    for k in range(1, n_needles + 1):
        data = bytes(rng.randbytes(rng.randint(500, 4000)))
        payload[k] = data
        v.write_needle(Needle(cookie=0x12345678, id=k, data=data))
    v.close()
    base = os.path.join(d, str(vid))
    ec_files.write_ec_files(base, rs=new_encoder(backend="cpu"))
    ec_files.write_sorted_file_from_idx(base)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    if with_ecc:
        crcs = {
            sid: _file_crc(base + ec_files.to_ext(sid)) for sid in range(14)
        }
        write_sidecar(base, crcs)
    store = Store([d], ec_backend="cpu")
    assert store.find_ec_volume(vid) is not None
    return store, payload, base


def _dir_backend(tmp_path, instance_id):
    """Register a local-dir backend fake under a test-unique instance
    id (BACKEND_STORAGES is process-global)."""
    bdir = str(tmp_path / f"backend_{instance_id}")
    os.makedirs(bdir, exist_ok=True)
    bk.ensure_builtin_factories()
    bk.load_backend_config({"dir": {instance_id: {"enabled": True, "dir": bdir}}})
    return f"dir.{instance_id}", bdir


# ---------------------------------------------------------------------------
class TestTierRules:
    def test_hysteresis(self):
        r = TierRules(min_age_s=100.0, cold_reads_per_s=0.1, hot_reads_per_s=1.0)
        # young or warm → stay put
        assert r.decide(age_s=50.0, reads_per_s=0.0, tiered=False) is None
        assert r.decide(age_s=500.0, reads_per_s=0.5, tiered=False) is None
        # old AND cold → out
        assert r.decide(age_s=500.0, reads_per_s=0.05, tiered=False) == "out"
        # tiered stays tiered through the dead band…
        assert r.decide(age_s=500.0, reads_per_s=0.5, tiered=True) is None
        # …and only recalls once genuinely hot
        assert r.decide(age_s=500.0, reads_per_s=2.0, tiered=True) == "in"

    def test_no_backend_means_no_tier_out(self):
        r = TierRules(backend="", min_age_s=0.0, cold_reads_per_s=10.0)
        # decide() is pure policy; the scheduler refuses to act without
        # a backend — mirror that contract here via from_env default
        assert r.backend == ""

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("WEED_TIER_BACKEND", "dir.cold")
        monkeypatch.setenv("WEED_TIER_MIN_AGE_S", "42")
        monkeypatch.setenv("WEED_TIER_COLD_RPS", "0.5")
        monkeypatch.setenv("WEED_TIER_HOT_RPS", "3")
        r = TierRules.from_env()
        assert r.backend == "dir.cold"
        assert r.min_age_s == 42.0
        assert r.cold_reads_per_s == 0.5
        assert r.hot_reads_per_s == 3.0
        assert r.to_dict()["Backend"] == "dir.cold"

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("WEED_TIER", "0")
        assert not tier_enabled()
        monkeypatch.delenv("WEED_TIER")
        assert tier_enabled()


# ---------------------------------------------------------------------------
class TestEcTierRoundTrip:
    def test_out_then_in_byte_identical(self, tmp_path):
        store, payload, base = _ec_store(tmp_path)
        name, bdir = _dir_backend(tmp_path, "rt1")
        before = {
            sid: _file_crc(base + ec_files.to_ext(sid)) for sid in range(14)
        }

        res = tier_out_ec(store, VID, name)
        assert res["Shards"] == list(range(14))
        assert res["Bytes"] > 0
        ev = store.find_ec_volume(VID)
        assert ev.remote is not None
        assert ev.shards == {}  # local shard files gone…
        assert not any(
            os.path.exists(base + ec_files.to_ext(s)) for s in range(14)
        )
        assert os.path.exists(base + ".evf")  # …but the commit record
        assert os.path.exists(base + ".ecx")  # and the index stayed
        assert tiered_volume_count(store) == 1
        st = tier_status(store)[str(VID)]
        assert st["Tiered"] and st["Backend"] == name
        assert st["LocalShards"] == [] and st["RemoteShards"] == list(range(14))
        # the heartbeat keeps advertising every shard
        assert ev.serving_shard_ids() == list(range(14))

        # reads now stream sub-ranges from the backend
        r0 = TIER_REMOTE_READS.value()
        for k, data in payload.items():
            assert bytes(ev.read_needle(k).data) == data
        assert TIER_REMOTE_READS.value() > r0

        res = tier_in_ec(store, VID)
        assert sorted(res["Shards"]) == list(range(14))
        assert ev.remote is None
        assert not os.path.exists(base + ".evf")
        for sid in range(14):
            assert _file_crc(base + ec_files.to_ext(sid)) == before[sid]
        # remote keys were reclaimed
        assert [f for f in os.listdir(bdir) if not f.endswith(".part")] == []
        for k, data in payload.items():
            assert bytes(ev.read_needle(k).data) == data

    def test_short_circuits_and_unknown_backend(self, tmp_path):
        store, _, _ = _ec_store(tmp_path)
        name, _ = _dir_backend(tmp_path, "sc1")
        with pytest.raises(ValueError, match="not configured"):
            tier_out_ec(store, VID, "dir.no-such-instance")
        with pytest.raises(ValueError, match="not found"):
            tier_out_ec(store, 999, name)
        assert tier_in_ec(store, VID) == {"VolumeId": VID, "NotTiered": True}
        tier_out_ec(store, VID, name)
        assert tier_out_ec(store, VID, name) == {
            "VolumeId": VID,
            "AlreadyTiered": True,
        }

    def test_restart_discovers_tiered_volume(self, tmp_path):
        store, payload, base = _ec_store(tmp_path)
        name, _ = _dir_backend(tmp_path, "rs1")
        tier_out_ec(store, VID, name)
        # a fresh Store over the same directory (process restart) must
        # adopt the .evf and keep serving from the backend
        store2 = Store([os.path.dirname(base)], ec_backend="cpu")
        ev2 = store2.find_ec_volume(VID)
        assert ev2 is not None and ev2.remote is not None
        assert ev2.remote.backend_name == name
        for k, data in payload.items():
            assert bytes(ev2.read_needle(k).data) == data
        # and recall works from the adopted attachment too
        tier_in_ec(store2, VID)
        assert store2.find_ec_volume(VID).remote is None

    def test_tier_in_rejects_corrupt_backend_copy(self, tmp_path):
        store, _, base = _ec_store(tmp_path)
        name, bdir = _dir_backend(tmp_path, "crc1")
        tier_out_ec(store, VID, name)
        ev = store.find_ec_volume(VID)
        # rot one remote object behind the backend's back
        key = ev.remote.shards[3]["key"]
        path = os.path.join(bdir, key)
        with open(path, "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(IOError, match="CRC mismatch"):
            tier_in_ec(store, VID)
        # the attachment survives the failed recall (remote copy is
        # still the only copy of the healthy shards) and no .tierin
        # temp files leak
        assert ev.remote is not None
        assert os.path.exists(base + ".evf")
        assert not any(
            f.endswith(".tierin")
            for f in os.listdir(os.path.dirname(base))
        )


# ---------------------------------------------------------------------------
class TestChaosBackend:
    def test_erroring_backend_degrades_then_heals(self, tmp_path):
        store, payload, _ = _ec_store(tmp_path)
        name, _ = _dir_backend(tmp_path, "chaos1")
        tier_out_ec(store, VID, name)
        ev = store.find_ec_volume(VID)
        inner = bk.get_backend(name)
        chaos = ChaosBackendStorage(
            inner, faults=[BackendFault("eio", ops=("read",))]
        )
        bk.register_backend(chaos)  # shim takes over the name
        try:
            k = next(iter(payload))
            e0 = TIER_REMOTE_READ_ERRORS.value()
            # zero local shards + no peer fetcher + EIO backend: the
            # read degrades to NotEnoughShards — it must NOT quarantine
            # or drop the volume
            with pytest.raises(NotEnoughShards):
                ev.read_needle(k)
            assert TIER_REMOTE_READ_ERRORS.value() > e0
            assert chaos.raised > 0
            assert store.find_ec_volume(VID) is ev  # still mounted
            assert ev.remote is not None  # attachment untouched
            chaos.heal()
            assert bytes(ev.read_needle(k).data) == payload[k]
        finally:
            bk.register_backend(inner)

    def test_slow_backend_still_serves(self, tmp_path):
        store, payload, _ = _ec_store(tmp_path, n_needles=5)
        name, _ = _dir_backend(tmp_path, "chaos2")
        tier_out_ec(store, VID, name)
        ev = store.find_ec_volume(VID)
        inner = bk.get_backend(name)
        bk.register_backend(
            ChaosBackendStorage(
                inner,
                faults=[BackendFault("slow", ops=("read",), delay_s=0.02)],
            )
        )
        try:
            for k, data in payload.items():
                assert bytes(ev.read_needle(k).data) == data
        finally:
            bk.register_backend(inner)
