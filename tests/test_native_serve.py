"""The event-driven serving core (native/serve.c + util/native_serve):
C-loop-vs-threaded byte identity, the zero-copy GET fast path, Range
semantics through both arms, the keep-alive housekeeping knobs, the
kill switch, and the SO_REUSEPORT bind fix.

Identity is tested the way the serve fuzzer tests it: one volume
store, two live servers (one on the epoll loop, one pinned threaded),
the same bytes down both sockets, every response byte diffed.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import pytest

from seaweedfs_tpu.analysis import fuzz_serve
from seaweedfs_tpu.util import native_serve

pytestmark = pytest.mark.skipif(
    not native_serve.available(),
    reason="no C toolchain / non-Linux: native serve loop unavailable",
)


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    p = fuzz_serve.ServePair(str(tmp_path_factory.mktemp("servepair")))
    yield p
    p.close()


def _roundtrip(port: int, payload: bytes, deadline: float = 5.0) -> bytes:
    return fuzz_serve.drive(port, {"fragments": [payload]}, deadline)


def _both(pair, payload: bytes) -> tuple[bytes, bytes]:
    return (
        _roundtrip(pair.c_port, payload),
        _roundtrip(pair.py_port, payload),
    )


class TestByteIdentity:
    def test_plain_get_fast_path_hit(self, pair):
        """The C arm must actually serve this from the resolver (not
        via handoff): probe by swapping in a counting resolver."""
        hits = []
        srv = pair.servers[0]
        orig = srv.fast_resolver

        def counting(path, rng, head_only):
            plan = orig(path, rng, head_only)
            hits.append(plan is not None)
            return plan

        srv.fast_resolver = counting
        try:
            req = f"GET /{pair.fids['small']} HTTP/1.1\r\n\r\n".encode()
            c, py = _both(pair, req)
        finally:
            srv.fast_resolver = orig
        assert c == py
        assert b"200 OK" in c and b"ETag" in c
        assert hits == [True]

    @pytest.mark.parametrize(
        "shape", ["small", "tiny", "empty", "big", "edge64k", "named",
                  "deleted", "missing", "badcookie"]
    )
    def test_get_identity_per_shape(self, pair, shape):
        req = f"GET /{pair.fids[shape]} HTTP/1.1\r\n\r\n".encode()
        c, py = _both(pair, req)
        assert c == py

    def test_head_identity(self, pair):
        req = f"HEAD /{pair.fids['big']} HTTP/1.1\r\n\r\n".encode()
        c, py = _both(pair, req)
        assert c == py
        assert b"Content-Length: 100000" in c and len(c) < 400

    def test_pipelined_identity(self, pair):
        req = (
            f"GET /{pair.fids['small']} HTTP/1.1\r\n\r\n"
            f"GET /{pair.fids['missing']} HTTP/1.1\r\n\r\n"
            f"GET /{pair.fids['big']} HTTP/1.1\r\nRange: bytes=0-9\r\n\r\n"
        ).encode()
        c, py = _both(pair, req)
        assert c == py
        assert c.count(b"HTTP/1.1 ") == 3

    def test_fragmented_head_identity(self, pair):
        raw = f"GET /{pair.fids['small']} HTTP/1.1\r\nRange: bytes=1-5\r\n\r\n".encode()
        frags = [raw[i : i + 7] for i in range(0, len(raw), 7)]
        c = fuzz_serve.drive(pair.c_port, {"fragments": frags})
        py = fuzz_serve.drive(pair.py_port, {"fragments": frags})
        assert c == py and b"206" in c

    def test_http10_connection_close_identity(self, pair):
        req = f"GET /{pair.fids['tiny']} HTTP/1.0\r\n\r\n".encode()
        c, py = _both(pair, req)
        assert c == py
        assert b"Connection: close" in c


class TestRangeCorrectness:
    """Satellite: util/http_range.parse_range semantics exercised
    end-to-end through BOTH serving paths (suffix, out-of-bounds→416,
    multi-byte offsets, open-ended), byte-identical."""

    @pytest.mark.parametrize(
        "rng",
        [
            "bytes=0-0",          # first byte
            "bytes=100-199",      # interior run
            "bytes=-100",         # suffix
            "bytes=-1",           # one-byte suffix
            "bytes=-999999",      # suffix larger than the body: whole body
            "bytes=699-",         # open-ended to EOF
            "bytes=650-100000",   # end clamped to EOF
            "bytes=700-",         # start == total: 416
            "bytes=999999-",      # far out of bounds: 416
            "bytes=5-2",          # inverted: 416
            "bytes=abc",          # malformed: 416
            "bytes=",             # empty spec: 416
            "bytes=0-99,200-299", # multi-range: first range only
            "bits=0-1",           # non-bytes unit: full 200
        ],
    )
    def test_range_identity(self, pair, rng):
        req = (
            f"GET /{pair.fids['small']} HTTP/1.1\r\nRange: {rng}\r\n\r\n"
        ).encode()
        c, py = _both(pair, req)
        assert c == py

    def test_suffix_range_bytes(self, pair):
        req = f"GET /{pair.fids['small']} HTTP/1.1\r\nRange: bytes=-100\r\n\r\n".encode()
        c, _ = _both(pair, req)
        head, _, body = c.partition(b"\r\n\r\n")
        assert b"206" in head.split(b"\r\n")[0]
        assert b"Content-Range: bytes 600-699/700" in head
        assert len(body) == 100

    def test_out_of_bounds_416_contract(self, pair):
        req = f"GET /{pair.fids['small']} HTTP/1.1\r\nRange: bytes=700-\r\n\r\n".encode()
        c, py = _both(pair, req)
        assert c == py
        assert c.startswith(b"HTTP/1.1 416 ")
        assert b"Content-Range: bytes */700" in c

    def test_multi_byte_offset_slices_match_store(self, pair):
        """The sendfile window must hit the exact data bytes: pull
        three disjoint slices of the 100 KB needle and splice them
        against the full body."""
        full = _roundtrip(
            pair.c_port, f"GET /{pair.fids['big']} HTTP/1.1\r\n\r\n".encode()
        ).partition(b"\r\n\r\n")[2]
        assert len(full) == 100_000
        for start, end in [(0, 0), (65_535, 65_537), (99_998, 99_999)]:
            req = (
                f"GET /{pair.fids['big']} HTTP/1.1\r\n"
                f"Range: bytes={start}-{end}\r\n\r\n"
            ).encode()
            c, py = _both(pair, req)
            assert c == py
            body = c.partition(b"\r\n\r\n")[2]
            assert body == full[start : end + 1]


class TestCorpusAndFuzz:
    def test_serve_corpus_is_seeded(self):
        assert len(_corpus_entries()) >= 12, (
            "tests/corpus/serve/ lost entries; re-seed with "
            "`python -m seaweedfs_tpu.analysis.fuzz_serve --seed-corpus`"
        )

    @pytest.mark.parametrize("name", sorted(
        p for p in os.listdir(
            os.path.join(os.path.dirname(__file__), "corpus", "serve")
        ) if p.endswith(".json")
    ) if os.path.isdir(
        os.path.join(os.path.dirname(__file__), "corpus", "serve")
    ) else [])
    def test_corpus_entry_identity(self, pair, name):
        path = os.path.join(
            os.path.dirname(__file__), "corpus", "serve", name
        )
        with open(path, encoding="utf-8") as f:
            case = fuzz_serve.case_from_json(f.read())
        divergence = fuzz_serve.run_case(pair, case)
        assert divergence is None, f"{name}: {divergence}"

    def test_fresh_fuzz_round(self, tmp_path):
        report = fuzz_serve.run(
            iterations=20, seed=4321, corpus_dir=str(tmp_path / "corpus")
        )
        assert report.iterations == 20
        assert not report.divergences, report.divergences


def _corpus_entries() -> list[str]:
    d = os.path.join(os.path.dirname(__file__), "corpus", "serve")
    if not os.path.isdir(d):
        return []
    return [p for p in os.listdir(d) if p.endswith(".json")]


class TestKnobs:
    @pytest.mark.parametrize("arm", ["c", "py"])
    def test_idle_timeout_closes_connection(self, tmp_path, arm):
        p = fuzz_serve.ServePair(str(tmp_path / arm), serve_idle_ms=300)
        try:
            port = p.c_port if arm == "c" else p.py_port
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            req = f"GET /{p.fids['tiny']} HTTP/1.1\r\n\r\n".encode()
            s.sendall(req)
            time.sleep(0.1)
            first = s.recv(65536)
            assert b"200 OK" in first
            # idle past the knob: the server must close, not hold the fd
            s.settimeout(5)
            t0 = time.monotonic()
            assert s.recv(64) == b""
            assert time.monotonic() - t0 < 4
            s.close()
        finally:
            p.close()

    @pytest.mark.parametrize("arm", ["c", "py"])
    def test_max_reqs_closes_with_connection_close(self, tmp_path, arm):
        p = fuzz_serve.ServePair(str(tmp_path / arm), serve_max_reqs=2)
        try:
            port = p.c_port if arm == "c" else p.py_port
            req = f"GET /{p.fids['tiny']} HTTP/1.1\r\n\r\n".encode()
            out = fuzz_serve.drive(port, {"fragments": [req * 3]})
            # request 1 plain, request 2 carries Connection: close,
            # request 3 is never served
            assert out.count(b"HTTP/1.1 200 OK") == 2
            assert out.count(b"Connection: close") == 1
        finally:
            p.close()

    @pytest.mark.parametrize("arm", ["c", "py"])
    def test_idle_timeout_spares_slow_draining_download(self, tmp_path, arm):
        """Regression (review finding): -serveIdleMs is an IDLE bound,
        not a total-transfer deadline — a client draining a large body
        slower than body/idle_ms must still receive every byte. The
        blob must be big enough to outsize the kernel's socket buffers
        or the server never enters the partial-write state."""
        import random

        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        from seaweedfs_tpu.storage.needle import Needle

        total = 8 << 20
        p = fuzz_serve.ServePair(str(tmp_path / arm), serve_idle_ms=400)
        try:
            v = p.vs.store.find_volume(1)
            n = Needle(
                cookie=0x99999999, id=50,
                data=random.Random(9).randbytes(total),
            )
            n.last_modified = 1_700_000_050
            n.set_has_last_modified_date()
            v.write_needle(n)
            fid = f"1,{format_needle_id_cookie(50, 0x99999999)}"
            port = p.c_port if arm == "c" else p.py_port
            s = socket.socket()
            # small windows BEFORE connect, so the server cannot park
            # the whole body in kernel buffers and skip the slow drain
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 32768)
            s.settimeout(10)
            s.connect(("127.0.0.1", port))
            s.sendall(f"GET /{fid} HTTP/1.1\r\n\r\n".encode())
            got = 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    chunk = s.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                got += len(chunk)
                time.sleep(0.02)  # ~3 MB/s: idle knob expires 8x over
            s.close()
            # headers ride in the first chunk; bound the body total
            assert got >= total, (
                f"slow download truncated at {got}/{total}+head [{arm}]"
            )
        finally:
            p.close()

    def test_max_reqs_identical_bytes_both_arms(self, tmp_path):
        p = fuzz_serve.ServePair(str(tmp_path), serve_max_reqs=2)
        try:
            req = f"GET /{p.fids['small']} HTTP/1.1\r\n\r\n".encode() * 4
            c = fuzz_serve.drive(p.c_port, {"fragments": [req]})
            py = fuzz_serve.drive(p.py_port, {"fragments": [req]})
            assert c == py
        finally:
            p.close()


class TestKillSwitch:
    def test_native_serve_env_kill_switch(self, tmp_path, monkeypatch):
        """WEED_NATIVE_SERVE=0 must land every server on the threaded
        path (try_serve_forever declines)."""
        monkeypatch.setattr(native_serve, "NATIVE_SERVE_ENABLED", False)
        p = fuzz_serve.ServePair(str(tmp_path))
        try:
            assert getattr(p.servers[0], "_serve_wake_w", None) is None
            req = f"GET /{p.fids['small']} HTTP/1.1\r\n\r\n".encode()
            out = fuzz_serve.drive(p.c_port, {"fragments": [req]})
            assert b"200 OK" in out and out.partition(b"\r\n\r\n")[2]
        finally:
            p.close()

    def test_double_shutdown_is_idempotent(self, tmp_path):
        """Regression: stop()ing a native server twice (normal in
        teardown paths — a failover test stops the leader, then the
        fixture stops every master) must not fall through to
        socketserver.shutdown(), which waits forever on an
        __is_shut_down event the stdlib loop (which never ran) will
        never set."""
        p = fuzz_serve.ServePair(str(tmp_path))
        try:
            srv = p.servers[0]
            srv.shutdown()
            done = threading.Event()

            def second():
                srv.shutdown()
                done.set()

            threading.Thread(target=second, daemon=True).start()
            assert done.wait(5), "second shutdown() deadlocked"
        finally:
            p.close()

    def test_native_arm_is_actually_native(self, pair):
        """The positive control for the kill-switch test: the C arm
        carries the loop's wake pipe, the threaded arm does not."""
        assert getattr(pair.servers[0], "_serve_wake_w", None) is not None
        assert getattr(pair.servers[1], "_serve_wake_w", None) is None


class TestReusePort:
    def test_two_listeners_share_one_port(self):
        """Regression for the 3.10 allow_reuse_port no-op: two
        ReusePortWeedHTTPServer binds on one port must BOTH come up
        (socketserver only honors the class attr on 3.11+; server_bind
        sets SO_REUSEPORT explicitly)."""
        from seaweedfs_tpu.util.httpd import FastHandler, ReusePortWeedHTTPServer

        class H(FastHandler):
            def do_GET(self):
                self.fast_reply(200, str(os.getpid()).encode())

        a = ReusePortWeedHTTPServer(("127.0.0.1", 0), H)
        port = a.server_address[1]
        b = ReusePortWeedHTTPServer(("127.0.0.1", port), H)
        for s in (a, b):
            threading.Thread(target=s.serve_forever, daemon=True).start()
        time.sleep(0.1)
        out = _roundtrip(port, b"GET / HTTP/1.1\r\n\r\n")
        assert b"200 OK" in out
        for s in (a, b):
            s.shutdown()
            s.server_close()


class TestExpectValidationOrder:
    """Satellite regression: 100 Continue must not be sent before the
    request validates (bad Content-Length, unknown method)."""

    def _exchange(self, pair, payload: bytes) -> bytes:
        return _roundtrip(pair.py_port, payload)

    def test_bad_content_length_rejects_without_100(self, pair):
        out = self._exchange(
            pair,
            b"POST /1,00000000 HTTP/1.1\r\nExpect: 100-continue\r\n"
            b"Content-Length: abc\r\n\r\n",
        )
        assert b"100 Continue" not in out
        assert b"400" in out.split(b"\r\n", 1)[0]

    def test_unknown_method_rejects_without_100(self, pair):
        out = self._exchange(
            pair,
            b"BREW /x HTTP/1.1\r\nExpect: 100-continue\r\n"
            b"Content-Length: 4\r\n\r\n",
        )
        assert b"100 Continue" not in out
        assert b"405" in out.split(b"\r\n", 1)[0]

    def test_valid_expect_still_gets_100(self, pair):
        s = socket.create_connection(("127.0.0.1", pair.py_port), timeout=5)
        try:
            s.sendall(
                b"GET /status HTTP/1.1\r\nExpect: 100-continue\r\n"
                b"Content-Length: 0\r\n\r\n"
            )
            buf = b""
            end = time.monotonic() + 5
            while b"\r\n\r\n" not in buf and time.monotonic() < end:
                buf += s.recv(4096)
            assert buf.startswith(b"HTTP/1.1 100 Continue\r\n\r\n")
        finally:
            s.close()

    def test_both_arms_identical_on_expect_abuse(self, pair):
        payload = (
            b"POST /1,00000000 HTTP/1.1\r\nExpect: 100-continue\r\n"
            b"Content-Length: oops\r\n\r\n"
        )
        c = _roundtrip(pair.c_port, payload)
        py = _roundtrip(pair.py_port, payload)
        assert c == py


class TestBlackboxIdentity:
    """Satellite: wide events from the C fast path must be
    indistinguishable from the threaded arm's — same record name, same
    stage fields, same status — so capsules read identically whichever
    arm served the request (docs/TRACING.md flight recorder)."""

    @pytest.fixture(autouse=True)
    def _fresh_recorder(self):
        # other tests toggle the tracer/recorder globals; identity
        # needs both planes on and full-fidelity sampling
        from seaweedfs_tpu.trace import blackbox, tracer

        blackbox.reset()
        blackbox.set_enabled(True)
        tracer.set_enabled(True)
        tracer.set_sample_every(1)
        yield

    def _records(self, want):
        """Poll the flight recorder (fast-path drain is asynchronous)."""
        from seaweedfs_tpu.trace import blackbox

        end = time.monotonic() + 5.0
        while time.monotonic() < end:
            snap = blackbox.snapshot(256)
            rows = [r for r in snap["tail"] + snap["ok"] if want(r)]
            if rows:
                return rows
            time.sleep(0.05)
        return []

    def _get(self, port: int, fid: str) -> bytes:
        return _roundtrip(
            port, f"GET /{fid} HTTP/1.1\r\n\r\n".encode("ascii")
        )

    @pytest.mark.parametrize("arm", ["c", "py"])
    def test_ok_record_has_identical_stage_fields(self, pair, arm):
        from seaweedfs_tpu.trace import blackbox

        blackbox.reset()
        port = pair.c_port if arm == "c" else pair.py_port
        ok_every = blackbox.snapshot(0)["ok_every"]
        for i in range(4 * ok_every):
            # unique query per request: a C-loop plan-cache hit is
            # served without re-entering Python, so repeated GETs of
            # one path would record only the first resolution
            out = self._get(port, f"{pair.fids['small']}?i={i}")
            assert b"200 OK" in out.split(b"\r\n", 1)[0]
        rows = self._records(
            lambda r: r["name"] == "volume.GET" and r["status"] == 200
        )
        assert rows, f"no volume.GET records drained on {arm} arm"
        staged = [r for r in rows if r.get("stages_ms")]
        assert staged, f"no staged records on {arm} arm"
        for r in staged:
            assert set(r["stages_ms"]) == set(native_serve.SERVE_STAGES)
            assert r["plane"] == "serve"
            assert r["bytes"] > 0

    def test_error_kept_in_tail_on_both_arms(self, pair):
        from seaweedfs_tpu.trace import blackbox

        for port in (pair.c_port, pair.py_port):
            blackbox.reset()
            out = self._get(port, pair.fids["missing"])
            assert b"404" in out.split(b"\r\n", 1)[0]
            rows = self._records(
                lambda r: r["name"] == "volume.GET" and r["status"] == 404
            )
            # errors are never sampled away: the tail ring keeps them,
            # and a 404 wide-event stages identically on both arms
            assert rows
            assert all(r in blackbox.snapshot(256)["tail"] for r in rows)
            staged = [r for r in rows if r.get("stages_ms")]
            assert staged
            for r in staged:
                assert set(r["stages_ms"]) == set(native_serve.SERVE_STAGES)
                assert r["bytes"] > 0
