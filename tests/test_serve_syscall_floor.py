"""Syscall-floor serving edge: the PR-15 satellite matrix.

Three contracts pinned here, all against live sockets:

1. Conditional-GET identity — every If-None-Match form (exact, weak,
   list, `*`, no-match, malformed) produces byte-identical responses
   from the C epoll loop and the threaded mini loop, If-None-Match
   beats Range, and flag-bearing needles (name/mime) get correct
   Content-Type/Content-Disposition from BOTH arms — with the C arm
   proven to have served natively (served/not_modified counters move,
   handoffs do not).

2. fd/offset-cache invalidation — overwrites and vacuum fd-swaps
   bump the generation counter, so a GET hammering the C fast path
   through a concurrent compaction never serves stale bytes.

3. Shared-memory admission — one mmap'd GCRA bucket arbitrates every
   attached process: cold-burst exactness, sustained rate within ±10%
   under a fully-skewed (single-sibling) charge pattern, a second
   process does NOT get its own burst, and the C shed reply is
   byte-identical to the Python gate's after normalizing the
   time-dependent Retry-After value.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import tempfile
import threading
import time
from contextlib import contextmanager

import pytest

from seaweedfs_tpu.analysis import fuzz_serve
from seaweedfs_tpu.util import native_serve

SMALL_ETAG = "067c9745"  # deterministic ETag of ServePair's `small`


@pytest.fixture(scope="module")
def pair():
    with tempfile.TemporaryDirectory(prefix="weedsyscallfloor") as workdir:
        p = fuzz_serve.ServePair(workdir)
        try:
            if not p.native_ok:
                pytest.skip("native serving loop unavailable on this host")
            yield p
        finally:
            p.close()


def _req(path: str, *headers: str, method: str = "GET") -> bytes:
    head = f"{method} /{path} HTTP/1.1\r\n"
    head += "".join(h + "\r\n" for h in headers)
    return (head + "\r\n").encode()


def _stats() -> dict:
    s = native_serve.serve_stats()
    return {
        k: s.get(k, 0)
        for k in ("served", "not_modified", "handoffs", "cache_hits", "shed")
    }


def _both(pair, payload: bytes) -> tuple[bytes, bytes]:
    case = {"fragments": [payload]}
    return (
        fuzz_serve.drive(pair.c_port, case),
        fuzz_serve.drive(pair.py_port, case),
    )


# ---------------------------------------------------------------------------
# 1. conditional-GET identity matrix


class TestConditionalIdentity:
    @pytest.mark.parametrize(
        "name,headers,status",
        [
            ("exact", [f'If-None-Match: "{SMALL_ETAG}"'], 304),
            ("weak", [f'If-None-Match: W/"{SMALL_ETAG}"'], 304),
            ("list", [f'If-None-Match: "a", "b", "{SMALL_ETAG}"'], 304),
            ("star", ["If-None-Match: *"], 304),
            ("nomatch", ['If-None-Match: "zz"'], 200),
            ("empty", ["If-None-Match: "], 200),
            ("malformed", [f'If-None-Match: "{SMALL_ETAG}'], 200),
            ("bare_token", [f"If-None-Match: {SMALL_ETAG}"], 200),
            (
                "inm_beats_range",
                ["Range: bytes=0-9", f'If-None-Match: "{SMALL_ETAG}"'],
                304,
            ),
            ("range_only", ["Range: bytes=0-9"], 206),
        ],
    )
    def test_inm_matrix_stays_in_c(self, pair, name, headers, status):
        before = _stats()
        c, py = _both(pair, _req(pair.fids["small"], *headers))
        after = _stats()
        assert c == py, f"{name}: arms diverge"
        assert c.startswith(f"HTTP/1.1 {status} ".encode()), c[:40]
        assert after["handoffs"] == before["handoffs"], (
            f"{name}: C arm handed off instead of serving natively"
        )
        moved = ("not_modified",) if status == 304 else ("served",)
        for key in moved:
            assert after[key] > before[key], f"{name}: {key} did not move"
        if status == 304:
            # a 304 never carries a body
            assert c.partition(b"\r\n\r\n")[2] == b""
            assert b"Content-Length: 0" in c

    def test_head_with_matching_inm(self, pair):
        before = _stats()
        c, py = _both(
            pair,
            _req(
                pair.fids["small"],
                f'If-None-Match: "{SMALL_ETAG}"',
                method="HEAD",
            ),
        )
        after = _stats()
        assert c == py
        assert c.startswith(b"HTTP/1.1 304 ")
        assert after["handoffs"] == before["handoffs"]

    def test_named_needle_served_from_c_with_disposition(self, pair):
        before = _stats()
        c, py = _both(pair, _req(pair.fids["named"]))
        after = _stats()
        assert c == py
        head = c.partition(b"\r\n\r\n")[0]
        assert b'Content-Disposition: inline; filename="f.bin"' in head
        assert b"Content-Type: application/octet-stream" in head
        assert c.endswith(b"named blob")
        assert after["handoffs"] == before["handoffs"]
        assert after["served"] > before["served"]

    def test_mime_needle_served_from_c_with_content_type(self, pair):
        before = _stats()
        c, py = _both(pair, _req(pair.fids["mime"]))
        after = _stats()
        assert c == py
        assert b"Content-Type: text/html" in c.partition(b"\r\n\r\n")[0]
        assert after["handoffs"] == before["handoffs"]

    def test_flagged_needle_conditional_stays_in_c(self, pair):
        before = _stats()
        c, py = _both(pair, _req(pair.fids["named"], "If-None-Match: *"))
        after = _stats()
        assert c == py
        assert c.startswith(b"HTTP/1.1 304 ")
        assert after["not_modified"] > before["not_modified"]
        assert after["handoffs"] == before["handoffs"]

    def test_conditional_gets_hit_the_plan_cache(self, pair):
        payload = _req(pair.fids["small"], f'If-None-Match: "{SMALL_ETAG}"')
        fuzz_serve.drive(pair.c_port, {"fragments": [payload]})
        before = _stats()
        out = fuzz_serve.drive(pair.c_port, {"fragments": [payload]})
        after = _stats()
        assert out.startswith(b"HTTP/1.1 304 ")
        assert after["cache_hits"] > before["cache_hits"], (
            "second conditional GET should reuse the cached plan"
        )

    def test_pipelined_mixed_conditionals(self, pair):
        stream = (
            _req(pair.fids["small"], f'If-None-Match: "{SMALL_ETAG}"')
            + _req(pair.fids["small"])
            + _req(pair.fids["named"], "If-None-Match: *")
            + _req(pair.fids["mime"])
            + _req(pair.fids["small"], 'If-None-Match: "zz"',
                   "Connection: close")
        )
        before = _stats()
        c, py = _both(pair, stream)
        after = _stats()
        assert c == py
        assert c.count(b"HTTP/1.1 304 ") == 2
        assert c.count(b"HTTP/1.1 200 ") == 3
        assert after["handoffs"] == before["handoffs"]


# ---------------------------------------------------------------------------
# 2. fd/offset-cache invalidation


class TestFdCacheInvalidation:
    def test_overwrite_invalidates_cached_plan(self, pair):
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        from seaweedfs_tpu.storage.needle import Needle

        v = pair.vs.store.find_volume(1)
        n = Needle(cookie=0xCAFE01, id=60, data=b"first body")
        v.write_needle(n)
        fid = f"1,{format_needle_id_cookie(60, 0xCAFE01)}"
        payload = _req(fid)
        c1, py1 = _both(pair, payload)
        assert c1 == py1 and c1.endswith(b"first body")
        n2 = Needle(cookie=0xCAFE01, id=60, data=b"second body, longer")
        v.write_needle(n2)
        c2, py2 = _both(pair, payload)
        assert c2 == py2
        assert c2.endswith(b"second body, longer"), (
            "C arm served a stale cached plan after overwrite"
        )

    def test_vacuum_fd_swap_invalidates(self, pair):
        payload = _req(pair.fids["small"])
        c1, _ = _both(pair, payload)
        v = pair.vs.store.find_volume(1)
        gen_before = native_serve.generation()
        v.compact()
        v.commit_compact()
        assert native_serve.generation() > gen_before
        c2, py2 = _both(pair, payload)
        assert c2 == py2
        assert c2 == c1, "same needle must serve identically across vacuum"

    def test_concurrent_vacuum_never_serves_stale(self, pair):
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        from seaweedfs_tpu.storage.needle import Needle

        v = pair.vs.store.find_volume(1)
        body = os.urandom(4096)
        v.write_needle(Needle(cookie=0xCAFE02, id=61, data=body))
        # a tombstone ahead of id 61 so compaction shifts its offset
        v.write_needle(Needle(cookie=0xCAFE03, id=62, data=b"x" * 2048))
        v.delete_needle(Needle(cookie=0xCAFE03, id=62))
        fid = f"1,{format_needle_id_cookie(61, 0xCAFE02)}"
        payload = _req(fid)
        stop = threading.Event()
        errors: list[bytes] = []

        def hammer():
            while not stop.is_set():
                out = fuzz_serve.drive(
                    pair.c_port, {"fragments": [payload]}
                )
                if not out.startswith(b"HTTP/1.1 200 ") or not out.endswith(
                    body
                ):
                    errors.append(out[:200])
                    stop.set()
                    return

        t = threading.Thread(target=hammer)
        t.start()
        try:
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline and not stop.is_set():
                v.compact()
                v.commit_compact()
                time.sleep(0.02)
        finally:
            stop.set()
            t.join()
        assert not errors, (
            f"stale/failed reads during concurrent vacuum: {errors[:2]}"
        )


# ---------------------------------------------------------------------------
# 3. shared-memory admission


@contextmanager
def _shm_bucket(path: str, rate: float, burst: float, retry: float = 0.5):
    """Attach-once is process-global: tear down whatever mapping an
    earlier test (or controller) left behind, attach fresh, detach
    after so later tests see a clean slate."""
    native_serve.admission_shm_detach()
    assert native_serve.admission_shm_attach(path, rate, burst, retry)
    try:
        yield
    finally:
        native_serve.admission_shm_detach()


class TestSharedAdmission:
    def test_cold_burst_exactness_and_windowed_rate(self, pair, tmp_path):
        rate, burst = 50.0, 10.0
        with _shm_bucket(str(tmp_path / "adm.tb"), rate, burst):
            admit = native_serve.admission_shm_admit
            cold = sum(1 for _ in range(40) if admit("tenant-a") == 0.0)
            assert cold == int(burst), (
                f"cold bucket admitted {cold}, want exactly {burst:.0f}"
            )
            # fully-skewed sustained load: every charge from this one
            # sibling; the GLOBAL rate must hold within ±10%
            t0 = time.monotonic()
            admitted = polls = 0
            while time.monotonic() - t0 < 1.0:
                if admit("tenant-a") == 0.0:
                    admitted += 1
                polls += 1
                time.sleep(0.0005)
            elapsed = time.monotonic() - t0
            expect = rate * elapsed
            # high side is the contract — the GLOBAL rate cap holds
            assert admitted <= 1.1 * expect + 1, (
                f"admitted {admitted} over {elapsed:.2f}s, "
                f"cap is {expect:.1f} +10%"
            )
            # low side degrades with poll granularity: a token frees
            # every 1/rate seconds but is only CLAIMED at the next
            # poll, so the achievable rate is 1/(1/rate + gap).
            # Sanitizer builds stretch the per-poll cost; deriving the
            # bound from the measured gap keeps the assertion exact on
            # fast builds and honest on instrumented ones.
            gap = elapsed / max(polls, 1)
            reachable = elapsed / (1.0 / rate + gap)
            assert admitted >= 0.9 * min(expect, reachable) - 1, (
                f"admitted {admitted} over {elapsed:.2f}s "
                f"({polls} polls), expected >= 90% of "
                f"{min(expect, reachable):.1f}"
            )
            # a different tenant still gets its own full burst
            other = sum(1 for _ in range(40) if admit("tenant-b") == 0.0)
            assert other == int(burst)

    def test_second_process_shares_the_bucket(self, pair, tmp_path):
        shm = str(tmp_path / "adm.tb")
        rate, burst = 5.0, 30.0
        with _shm_bucket(shm, rate, burst):
            admit = native_serve.admission_shm_admit
            t0 = time.monotonic()
            local = sum(1 for _ in range(60) if admit("tenant") == 0.0)
            assert local == int(burst)
            # a sibling attaching the same file must NOT get a fresh
            # burst: its admits are bounded by refill over its lifetime
            child = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import sys\n"
                    "from seaweedfs_tpu.util import native_serve as ns\n"
                    "assert ns.admission_shm_attach("
                    f"{shm!r}, {rate}, {burst}, 0.5)\n"
                    "print(sum(1 for _ in range(60)"
                    " if ns.admission_shm_admit('tenant') == 0.0))\n",
                ],
                cwd=os.path.dirname(os.path.dirname(__file__)),
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                capture_output=True,
                text=True,
                timeout=120,
            )
            assert child.returncode == 0, child.stderr[-2000:]
            child_admits = int(child.stdout.strip())
            elapsed = time.monotonic() - t0
            budget = burst + rate * elapsed
            total = local + child_admits
            assert total <= budget + 1, (
                f"{total} admits exceed the shared budget {budget:.1f} "
                f"(child got its own burst?)"
            )
            assert child_admits < burst, (
                "child process was granted a full fresh burst — the "
                "bucket is not shared"
            )

    def test_c_shed_reply_matches_python_gate(self, pair, tmp_path):
        from seaweedfs_tpu.qos.admission import AdmissionController
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.util.httpd import WeedHTTPServer

        native_serve.admission_shm_detach()
        vol_dir = str(tmp_path / "vols")
        os.makedirs(vol_dir)
        vs = VolumeServer([vol_dir], port=0, scrub_interval=0)
        servers = []
        try:
            vs.store.add_volume(1, "", "000", "")
            v = vs.store.find_volume(1)
            v.write_needle(Needle(cookie=0x1, id=1, data=b"hello"))
            fid = f"1,{format_needle_id_cookie(1, 0x1)}"
            # rate ~0: one burst token, then everything sheds with a
            # deterministic huge Retry-After
            adm = AdmissionController(
                rate=0.000001,
                burst=1.0,
                label="t",
                retry_after_s=1.0,
                shm_path=str(tmp_path / "adm.tb"),
            )
            assert adm.shared, "shm attach failed"
            handler = vs._http_handler_class()
            resolver = vs._make_fast_resolver()
            ports = []
            # admission must be installed BEFORE serve_forever: the C
            # loop latches it at loop start (mid-run flips need restart)
            for native in (True, False):
                srv = WeedHTTPServer(("127.0.0.1", 0), handler)
                srv.trace_name = "volume"
                srv.trace_node = "t"
                srv.fast_resolver = resolver
                srv.native_serve = native
                srv.admission = adm
                threading.Thread(
                    target=srv.serve_forever, daemon=True
                ).start()
                servers.append(srv)
                ports.append(srv.server_address[1])
            time.sleep(0.2)
            c_port, py_port = ports
            req = _req(fid)
            before = _stats()
            out_c = fuzz_serve.drive(c_port, {"fragments": [req * 3]})
            after = _stats()
            assert out_c.count(b"HTTP/1.1 200 ") == 1
            assert out_c.count(b"HTTP/1.1 503 ") == 2
            assert after["shed"] - before["shed"] == 2, (
                "C loop should shed natively, not hand off"
            )
            assert after["handoffs"] == before["handoffs"]
            out_py = fuzz_serve.drive(py_port, {"fragments": [req]})
            assert out_py.count(b"HTTP/1.1 503 ") == 1
            # Retry-After carries the GCRA wait — time-dependent digits,
            # normalize before comparing the shed bytes
            norm = lambda b: re.sub(  # noqa: E731
                rb"Retry-After: [0-9.]+", b"Retry-After: X", b
            )
            shed_c = norm(out_c[out_c.index(b"HTTP/1.1 503 "):])
            shed_py = norm(out_py)
            assert shed_c.startswith(shed_py), (
                f"shed replies diverge:\nC : {shed_c[:220]!r}\n"
                f"PY: {shed_py[:220]!r}"
            )
            assert b'{"error": "admission control: over per-client budget"}' \
                in shed_py
        finally:
            for srv in servers:
                srv.shutdown()
                srv.server_close()
            vs.store.close()
            native_serve.admission_shm_detach()

    def test_controller_falls_back_without_shm(self, tmp_path):
        from seaweedfs_tpu.qos.admission import AdmissionController

        native_serve.admission_shm_detach()
        adm = AdmissionController(rate=100.0, burst=10.0, procs=4, label="t")
        assert not adm.shared
        assert adm.rate == pytest.approx(25.0)  # legacy rate/N split
        shared = AdmissionController(
            rate=100.0,
            burst=10.0,
            procs=4,
            label="t",
            shm_path=str(tmp_path / "adm.tb"),
        )
        try:
            assert shared.shared
            assert shared.rate == pytest.approx(100.0)  # global, no /N
            assert shared.status()["Shared"] is True
        finally:
            native_serve.admission_shm_detach()
