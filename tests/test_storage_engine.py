"""Storage-engine tests: needle map, Volume write/read/delete/vacuum,
DiskLocation scan, Store dispatch, EcVolume degraded reads.

Modeled on the reference's volume_vacuum_test.go (write real needles
into a temp volume, delete some, compact, verify) and store_ec read
paths.
"""

import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.ec.ec_volume import EcVolume, NotEnoughShards
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import CompactNeedleMap, SortedNeedleMap
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NeedleNotFound,
    Volume,
    VolumeReadOnly,
)


def make_needle(nid, data=None, cookie=0x12345678):
    return Needle(cookie=cookie, id=nid, data=data if data is not None else f"data-{nid}".encode())


class TestCompactNeedleMap:
    def test_put_get_delete(self, tmp_path):
        nm = CompactNeedleMap.load(str(tmp_path / "v.idx"))
        nm.put(5, 100, 50)
        nm.put(9, 200, 60)
        assert nm.get(5).offset == 100
        assert nm.get(5).size == 50
        assert nm.file_count == 2
        assert nm.content_size() == 110
        freed = nm.delete(5, 300)
        assert freed == 50
        assert nm.get(5).size == t.TOMBSTONE_FILE_SIZE
        assert nm.get(404) is None

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "v.idx")
        nm = CompactNeedleMap.load(path)
        for k in range(1, 100):
            nm.put(k, k * 10, k)
        nm.delete(50, 9999)
        nm.close()

        nm2 = CompactNeedleMap.load(path)
        assert len(nm2) == 99
        assert nm2.get(50).size == t.TOMBSTONE_FILE_SIZE
        assert nm2.get(99).offset == 990
        assert nm2.max_file_key == 99
        assert nm2.deletion_count >= 1

    def test_overwrite_counts_old_as_deleted(self, tmp_path):
        nm = CompactNeedleMap.load(str(tmp_path / "v.idx"))
        nm.put(1, 10, 100)
        nm.put(1, 20, 120)
        assert nm.deletion_byte_count == 100
        assert nm.get(1).offset == 20


class TestVolume:
    def test_write_read_roundtrip(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        n = make_needle(42, b"hello volume")
        offset, size, unchanged = v.write_needle(n)
        assert not unchanged
        m = v.read_needle(42)
        assert m.data == b"hello volume"
        assert m.cookie == 0x12345678
        v.close()

    def test_duplicate_write_unchanged(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        v.write_needle(make_needle(1, b"same"))
        _, _, unchanged = v.write_needle(make_needle(1, b"same"))
        assert unchanged
        _, _, unchanged = v.write_needle(make_needle(1, b"different"))
        assert not unchanged
        v.close()

    def test_cookie_checks(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        v.write_needle(make_needle(1, b"x", cookie=0xAAAA))
        with pytest.raises(CookieMismatch):
            v.write_needle(make_needle(1, b"y", cookie=0xBBBB))
        with pytest.raises(CookieMismatch):
            v.read_needle(1, cookie=0xBBBB)
        assert v.read_needle(1, cookie=0xAAAA).data == b"x"
        v.close()

    def test_delete(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        v.write_needle(make_needle(7, b"doomed"))
        freed = v.delete_needle(make_needle(7))
        assert freed > 0
        with pytest.raises(NeedleNotFound):
            v.read_needle(7)
        # double delete is a no-op
        assert v.delete_needle(make_needle(7)) == 0
        v.close()

    def test_reload_preserves_state(self, tmp_path):
        v = Volume(str(tmp_path), 3, collection="col")
        for k in range(1, 20):
            v.write_needle(make_needle(k))
        v.delete_needle(make_needle(5))
        last_ns = v.last_append_at_ns
        v.close()

        v2 = Volume(str(tmp_path), 3, collection="col", create=False)
        assert v2.read_needle(10).data == b"data-10"
        with pytest.raises(NeedleNotFound):
            v2.read_needle(5)
        assert v2.last_append_at_ns == last_ns
        assert v2.file_count() == 19
        v2.close()

    def test_append_at_ns_monotonic(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        stamps = []
        for k in range(1, 10):
            v.write_needle(make_needle(k))
            stamps.append(v.last_append_at_ns)
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == len(stamps)
        v.close()

    def test_readonly_blocks_writes(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        v.read_only = True
        with pytest.raises(VolumeReadOnly):
            v.write_needle(make_needle(1))
        with pytest.raises(VolumeReadOnly):
            v.delete_needle(make_needle(1))
        v.close()

    def test_corrupt_tail_detected_on_load(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        v.write_needle(make_needle(1, b"will truncate"))
        v.close()
        # truncate the .dat mid-record: load must fail integrity check
        dat = str(tmp_path / "1.dat")
        size = os.path.getsize(dat)
        with open(dat, "r+b") as f:
            f.truncate(size - 8)
        with pytest.raises(ValueError):
            Volume(str(tmp_path), 1, create=False)


class TestVacuum:
    def test_compact_reclaims_deleted(self, tmp_path):
        # volume_vacuum_test.go's shape: write, delete some, compact,
        # verify the survivors and the shrunk file.
        v = Volume(str(tmp_path), 2)
        rng = random.Random(0)
        payload = {}
        for k in range(1, 101):
            data = bytes(rng.randbytes(rng.randint(10, 500)))
            payload[k] = data
            v.write_needle(make_needle(k, data))
        doomed = set(rng.sample(range(1, 101), 30))
        for k in doomed:
            v.delete_needle(make_needle(k))

        size_before = v.data_file_size()
        assert v.garbage_level() > 0
        v.compact()
        v.commit_compact()

        assert v.data_file_size() < size_before
        assert v.super_block.compaction_revision == 1
        for k in range(1, 101):
            if k in doomed:
                with pytest.raises(NeedleNotFound):
                    v.read_needle(k)
            else:
                assert v.read_needle(k).data == payload[k]
        assert v.deleted_count() == 0 or v.garbage_level() == 0.0
        v.close()

    def test_compact_then_reload(self, tmp_path):
        v = Volume(str(tmp_path), 2)
        for k in range(1, 11):
            v.write_needle(make_needle(k))
        v.delete_needle(make_needle(3))
        v.compact()
        v.commit_compact()
        v.close()
        v2 = Volume(str(tmp_path), 2, create=False)
        assert v2.file_count() == 9
        assert v2.read_needle(10).data == b"data-10"
        v2.close()

    def test_cleanup_removes_scratch(self, tmp_path):
        v = Volume(str(tmp_path), 2)
        v.write_needle(make_needle(1))
        v.compact()
        assert os.path.exists(str(tmp_path / "2.cpd"))
        v.cleanup_compact()
        assert not os.path.exists(str(tmp_path / "2.cpd"))
        v.close()


class TestStore:
    def test_add_write_read_delete(self, tmp_path):
        store = Store([str(tmp_path / "d1"), str(tmp_path / "d2")])
        store.add_volume(1)
        store.add_volume(2, collection="pics", replica_placement="001")
        size, unchanged = store.write_needle(1, make_needle(5, b"five"))
        assert not unchanged
        assert store.read_needle(1, 5).data == b"five"
        store.delete_needle(1, make_needle(5))
        with pytest.raises(NeedleNotFound):
            store.read_needle(1, 5)
        assert store.has_volume(2)
        assert store.delete_volume(2)
        assert not store.has_volume(2)
        store.close()

    def test_reload_scans_directories(self, tmp_path):
        store = Store([str(tmp_path)])
        store.add_volume(7, collection="c")
        store.write_needle(7, make_needle(1, b"persisted"))
        store.close()

        store2 = Store([str(tmp_path)])
        assert store2.read_needle(7, 1).data == b"persisted"
        store2.close()

    def test_heartbeat(self, tmp_path):
        store = Store([str(tmp_path)])
        store.add_volume(1)
        store.write_needle(1, make_needle(99, b"z"))
        hb = store.collect_heartbeat()
        assert hb.max_file_key == 99
        assert len(hb.volumes) == 1
        assert hb.volumes[0].file_count == 1
        store.close()


@pytest.fixture()
def ec_volume_dir(tmp_path):
    """A real volume written through the engine, sealed and EC-encoded
    with production block sizes (small volume ⇒ small-block tier)."""
    v = Volume(str(tmp_path), 9)
    payload = {}
    rng = random.Random(1)
    for k in range(1, 60):
        data = bytes(rng.randbytes(rng.randint(100, 3000)))
        payload[k] = data
        v.write_needle(make_needle(k, data))
    v.delete_needle(make_needle(13))
    del payload[13]
    v.close()

    base = str(tmp_path / "9")
    ec_files.write_ec_files(base, rs=new_encoder())
    ec_files.write_sorted_file_from_idx(base)
    return tmp_path, payload


class TestEcVolume:
    def test_full_local_read(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        ev = EcVolume.load(str(tmp_path), 9)
        assert ev.shard_ids() == list(range(14))
        for k, data in payload.items():
            assert ev.read_needle(k).data == data
        with pytest.raises(NeedleNotFound):
            ev.read_needle(13)  # deleted pre-encode
        ev.close()

    def test_degraded_read_with_reconstruction(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        ev = EcVolume.load(str(tmp_path), 9)
        # lose 4 shards including data shards
        for sid in (0, 1, 11, 12):
            ev.unmount_shard(sid)
            os.remove(str(tmp_path / "9") + ec_files.to_ext(sid))
        for k, data in payload.items():
            assert ev.read_needle(k).data == data, f"needle {k}"
        ev.close()

    def test_too_many_lost_raises(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        ev = EcVolume.load(str(tmp_path), 9)
        for sid in (0, 1, 2, 3, 4):
            ev.unmount_shard(sid)
            os.remove(str(tmp_path / "9") + ec_files.to_ext(sid))
        with pytest.raises(NotEnoughShards):
            for k in payload:
                ev.read_needle(k)
        ev.close()

    def test_remote_fetch_seam(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        # keep only shards 5..9 locally; serve 0..4 via the fetch callback
        # (simulating remote shard reads, store_ec.go:279)
        stash = {}
        for sid in range(14):
            path = str(tmp_path / "9") + ec_files.to_ext(sid)
            if sid < 5 or sid >= 10:
                stash[sid] = open(path, "rb").read()
                os.remove(path)
        ev = EcVolume.load(str(tmp_path), 9)

        fetches = []

        def fetch(sid, off, size):
            if sid in stash:
                fetches.append(sid)
                chunk = stash[sid][off : off + size]
                return chunk + bytes(size - len(chunk))
            return None

        for k, data in payload.items():
            assert ev.read_needle(k, fetch=fetch).data == data
        assert fetches, "remote seam must have been exercised"
        ev.close()

    def test_ec_delete_journal(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        ev = EcVolume.load(str(tmp_path), 9)
        victim = next(iter(payload))
        ev.delete_needle(victim)
        with pytest.raises(NeedleNotFound):
            ev.read_needle(victim)
        # journal holds the id; .ecx entry is tombstoned in place
        ecj = open(str(tmp_path / "9") + ".ecj", "rb").read()
        assert t.bytes_to_needle_id(ecj[:8]) == victim
        m = SortedNeedleMap.load(str(tmp_path / "9") + ".ecx")
        assert int(m.sizes[m.entry_index(victim)]) == t.TOMBSTONE_FILE_SIZE
        # idempotent
        ev.delete_needle(victim)
        assert len(open(str(tmp_path / "9") + ".ecj", "rb").read()) == 8
        ev.close()

    def test_disk_location_discovers_ec(self, ec_volume_dir):
        tmp_path, payload = ec_volume_dir
        os.remove(str(tmp_path / "9.dat"))
        os.remove(str(tmp_path / "9.idx"))
        store = Store([str(tmp_path)])
        k = next(iter(payload))
        assert store.read_needle(9, k).data == payload[k]
        hb = store.collect_heartbeat()
        assert len(hb.ec_shards) == 1
        assert hb.ec_shards[0].ec_index_bits == (1 << 14) - 1
        store.close()


class TestConcurrentVacuum:
    """Compaction must not lose writes that land between compact() and
    commit_compact() — the makeupDiff catch-up (volume_vacuum.go:78-157)."""

    def test_writes_during_compaction_survive_commit(self, tmp_path):
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), 11)
        for i in range(1, 6):
            v.write_needle(Needle(cookie=i, id=i, data=f"pre {i}".encode() * 50))
        v.delete_needle(Needle(cookie=2, id=2))  # garbage to reclaim

        v.compact()
        # writes landing AFTER the snapshot, BEFORE the commit:
        v.write_needle(Needle(cookie=100, id=100, data=b"mid-compaction write"))
        v.write_needle(Needle(cookie=3, id=3, data=b"overwritten!"))  # update
        v.delete_needle(Needle(cookie=4, id=4))  # delete a compacted needle
        v.commit_compact()
        v.cleanup_compact()

        assert bytes(v.read_needle(100, cookie=100).data) == b"mid-compaction write"
        assert bytes(v.read_needle(3, cookie=3).data) == b"overwritten!"
        assert bytes(v.read_needle(1, cookie=1).data) == b"pre 1" * 50
        import pytest as _pytest

        from seaweedfs_tpu.storage.volume import NeedleNotFound

        with _pytest.raises(NeedleNotFound):
            v.read_needle(2)
        with _pytest.raises(NeedleNotFound):
            v.read_needle(4)
        v.close()

        # reload from disk: the committed files are self-consistent
        v2 = Volume(str(tmp_path), 11, create=False)
        assert bytes(v2.read_needle(100, cookie=100).data) == b"mid-compaction write"
        assert bytes(v2.read_needle(3, cookie=3).data) == b"overwritten!"
        with _pytest.raises(NeedleNotFound):
            v2.read_needle(4)
        v2.close()

    def test_compact_does_not_block_writes(self, tmp_path):
        """compact() must run without the volume write lock held for
        the duration of the copy (only the snapshot takes it)."""
        import threading
        import time as _time

        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), 12)
        for i in range(1, 200):
            v.write_needle(Needle(cookie=i, id=i, data=b"z" * 2000))

        write_done = threading.Event()
        errors = []

        def writer():
            try:
                v.write_needle(
                    Needle(cookie=999, id=999, data=b"concurrent write")
                )
                write_done.set()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        # slow the copy enough to overlap: compact uses its own read
        # fd (not _read_at), so pace it via the record-size helper it
        # calls once per copied needle
        from seaweedfs_tpu.storage import volume as volume_mod

        orig = volume_mod.get_actual_size
        started = threading.Event()

        def slow_size(size, version):
            started.set()
            _time.sleep(0.002)
            return orig(size, version)

        volume_mod.get_actual_size = slow_size
        try:
            t = threading.Thread(target=v.compact)
            t.start()
            assert started.wait(5)
            w = threading.Thread(target=writer)
            w.start()
            # the write must complete while the compaction copy runs
            assert write_done.wait(5), "write blocked behind compact()"
            t.join()
        finally:
            volume_mod.get_actual_size = orig
        v.commit_compact()
        v.cleanup_compact()
        assert not errors
        assert bytes(v.read_needle(999, cookie=999).data) == b"concurrent write"
        v.close()


class TestNeedleMapBulk:
    """Scaled mirror of the reference's compact_map_perf_test.go
    1M-entry harness: bulk load, lookups, overwrite/delete accounting,
    and idx-replay equivalence at 100k entries (kept small for CI)."""

    N = 100_000

    def test_bulk_load_and_replay(self, tmp_path):
        import random

        from seaweedfs_tpu.storage.needle_map import CompactNeedleMap

        idx = str(tmp_path / "bulk.idx")
        nm = CompactNeedleMap.load(idx)
        rng = random.Random(7)
        keys = list(range(1, self.N + 1))
        for k in keys:
            nm.put(k, k * 2, 100 + (k % 50))
        # overwrite 5%, delete 5%
        for k in rng.sample(keys, self.N // 20):
            nm.put(k, k * 3, 999)
        deleted = rng.sample(keys, self.N // 20)
        for k in deleted:
            nm.delete(k, 0)
        assert len(nm) == self.N
        assert nm.max_file_key == self.N
        nm.close()

        # replaying the .idx reproduces the same visible state
        nm2 = CompactNeedleMap.load(idx)
        for k in rng.sample(keys, 200):
            a, b = nm.get(k), nm2.get(k)
            assert (a is None) == (b is None)
            if a is not None:
                assert (a.offset, a.size) == (b.offset, b.size)
        import seaweedfs_tpu.storage.types as t

        for k in rng.sample(deleted, 50):
            v = nm2.get(k)
            assert v is not None and v.size == t.TOMBSTONE_FILE_SIZE
        nm2.close()


class TestTtlExpiry:
    def test_expired_needle_reads_as_not_found(self, tmp_path, monkeypatch):
        """A needle whose TTL has elapsed 404s on read while a fresh one
        keeps serving (volume_read_write.go TTL gate)."""
        import time as _time

        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.ttl import TTL
        from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume

        v = Volume(str(tmp_path), 21, ttl=TTL.parse("1m"))
        n = Needle(cookie=1, id=1, data=b"short lived")
        n.ttl = TTL.parse("1m")
        n.set_has_ttl()
        n.last_modified = int(_time.time())
        n.set_has_last_modified_date()
        v.write_needle(n)

        # fresh: serves
        assert bytes(v.read_needle(1, cookie=1).data) == b"short lived"

        # jump 2 minutes into the future
        real_time = _time.time
        monkeypatch.setattr(
            "seaweedfs_tpu.storage.volume.time.time",
            lambda: real_time() + 120,
        )
        import pytest as _pytest

        with _pytest.raises(NeedleNotFound):
            v.read_needle(1, cookie=1)
        v.close()


class TestFaultInjection:
    """Fault-injection coverage the reference lacks (SURVEY §5 notes it
    has none): disk truncation on EC shards, torn .dat tails, and a
    random-operation model check of every needle-map implementation."""

    def test_truncated_shard_self_heals_through_reconstruction(
        self, ec_volume_dir
    ):
        tmp_path, payload = ec_volume_dir
        # The tiny fixture's data lives entirely in shard 0's first
        # small block (dat < 1 MB row), so truncate BELOW the data
        # extent — reads in the lost region must reconstruct, not
        # serve zero-fill (silent corruption) and not fail. Shards
        # 1-3 are truncated too: they get picked as survivors during
        # reconstruction and must be detected + skipped there.
        for s in (0, 1, 2, 3):
            p = str(tmp_path / "9") + ec_files.to_ext(s)
            with open(p, "r+b") as f:
                f.truncate(1024)
        ev = EcVolume.load(str(tmp_path), 9)
        for k, data in payload.items():
            assert ev.read_needle(k).data == data, f"needle {k}"
        # corrupt shards are quarantined (unmounted) on first detection,
        # so later reads route through the normal lost-shard path and
        # dat_file_size() can never derive geometry from a short file
        assert all(s not in ev.shard_ids() for s in (0, 1, 2, 3))
        ev.close()

    def test_too_many_truncated_shards_fail_loudly(self, ec_volume_dir):
        import os

        tmp_path, payload = ec_volume_dir
        # 5 corrupt shards > 4 parity: unreadable regions must raise
        # (NotEnoughShards / CorruptNeedle), never return wrong bytes
        for s in range(5):
            p = str(tmp_path / "9") + ec_files.to_ext(s)
            with open(p, "r+b") as f:
                f.truncate(10)
        ev = EcVolume.load(str(tmp_path), 9)
        from seaweedfs_tpu.storage.needle import CorruptNeedle

        failures = 0
        for k, data in payload.items():
            try:
                got = ev.read_needle(k).data
                assert got == data, f"needle {k}: wrong bytes returned"
            except (NotEnoughShards, CorruptNeedle):
                failures += 1
        assert failures > 0, "truncating 5 shards of a tiny volume hit nothing"
        ev.close()

    def test_torn_dat_tail_recovers_on_reload(self, tmp_path):
        """Crash mid-append: bytes landed in .dat with no idx entry.
        Reload must keep all indexed needles and keep accepting writes."""
        v = Volume(str(tmp_path), 3)
        for k in range(1, 6):
            v.write_needle(make_needle(k, f"payload-{k}".encode()))
        v.close()
        with open(tmp_path / "3.dat", "ab") as f:
            f.write(b"\xde\xad\xbe\xef" * 7)  # torn partial append

        v2 = Volume(str(tmp_path), 3)
        for k in range(1, 6):
            assert bytes(v2.read_needle(k).data) == f"payload-{k}".encode()
        v2.write_needle(make_needle(99, b"after-recovery"))
        assert bytes(v2.read_needle(99).data) == b"after-recovery"
        v2.close()
        # and it survives another reload
        v3 = Volume(str(tmp_path), 3)
        assert bytes(v3.read_needle(99).data) == b"after-recovery"
        v3.close()

    @pytest.mark.parametrize("kind", ["memory", "db"])
    def test_needle_map_random_ops_match_model(self, tmp_path, kind):
        """Random put/overwrite/delete stream vs a plain-dict model,
        including a save/reload cycle mid-stream. (SortedNeedleMap is a
        read-only view over a sorted file, exercised by the EC tests.)"""
        from seaweedfs_tpu.storage import needle_map as nm

        rng = random.Random(7)

        def new_map(idx_path):
            # .load replays the .idx (the crash-recovery path under test)
            if kind == "db":
                return nm.DbNeedleMap.load(idx_path)
            return nm.CompactNeedleMap.load(idx_path)

        idx_path = str(tmp_path / "m.idx")
        m = new_map(idx_path)
        model: dict[int, tuple[int, int]] = {}
        for step in range(800):
            op_pick = rng.random()
            key = rng.randint(1, 120)
            if op_pick < 0.6:
                off, size = rng.randint(1, 1 << 20), rng.randint(1, 1 << 16)
                m.put(key, off, size)
                model[key] = (off, size)
            elif key in model:
                m.delete(key, model[key][0])
                del model[key]
            if step == 400:  # crash/reload mid-stream
                m.close()
                m = new_map(idx_path)
        for key in range(1, 130):
            got = m.get(key)
            if key in model:
                assert got is not None and (got.offset, got.size) == model[key], key
            else:
                assert got is None or got.size == t.TOMBSTONE_FILE_SIZE, key
        m.close()
