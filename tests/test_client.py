"""Client SDK tests: masterclient KeepConnected map, operation
assign/upload/lookup/delete/submit (incl. chunk manifest fan-in), batch
delete — all against an in-process master + volume servers.

Mirrors the behaviors of weed/wdclient/ and weed/operation/ (reference
has no tests there; we add them per SURVEY §4 implication).
"""

import time

import pytest

from seaweedfs_tpu.client import MasterClient
from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from tests.test_cluster import free_port, http_get


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    master_port = free_port()
    master = MasterServer(port=master_port, volume_size_limit_mb=64)
    master.start()
    volume_servers = []
    for i in range(2):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"cvs{i}"))],
            port=free_port(),
            master=f"127.0.0.1:{master_port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        vs.start()
        volume_servers.append(vs)
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topology.data_nodes()) < 2:
        time.sleep(0.05)
    yield master, volume_servers
    for vs in volume_servers:
        vs.stop()
    master.stop()


@pytest.fixture()
def master_addr(cluster):
    master, _ = cluster
    return f"127.0.0.1:{master.port}"


class TestOperation:
    def test_assign_upload_download_roundtrip(self, master_addr):
        ar = op.assign(master_addr)
        assert "," in ar.fid and ar.url
        blob = b"hello operation sdk" * 50
        ur = op.upload(f"{ar.url}/{ar.fid}", blob, filename="x.bin")
        assert ur.error == ""
        assert ur.size > 0
        data, headers = op.download(f"{ar.url}/{ar.fid}")
        assert data == blob

    def test_lookup_and_cache(self, master_addr):
        ar = op.assign(master_addr)
        vid = ar.fid.split(",")[0]
        res = op.lookup(master_addr, vid)
        assert not res.error
        assert any(loc["url"] == ar.url for loc in res.locations)
        # cached path returns the same object
        res2 = op.lookup(master_addr, vid)
        assert res2 is res

    def test_lookup_file_id(self, master_addr):
        ar = op.assign(master_addr)
        op.upload(f"{ar.url}/{ar.fid}", b"abc")
        url = op.lookup_file_id(master_addr, ar.fid)
        data, _ = op.download(url)
        assert data == b"abc"

    def test_delete_files_batch(self, master_addr):
        fids = []
        for _ in range(5):
            ar = op.assign(master_addr)
            op.upload(f"{ar.url}/{ar.fid}", b"to-delete")
            fids.append(ar.fid)
        results = op.delete_files(master_addr, fids + ["bogus"])
        by_fid = {r["fid"]: r for r in results}
        for fid in fids:
            assert by_fid[fid]["status"] in (200, 202), by_fid[fid]
        assert by_fid["bogus"]["status"] == 400
        for fid in fids:
            with pytest.raises(Exception):
                op.download(op.lookup_file_id(master_addr, fid))

    def test_submit_small(self, master_addr):
        r = op.submit_file(master_addr, "small.txt", b"tiny", mime="text/plain")
        assert r.error == ""
        data, _ = op.download(r.file_url)
        assert data == b"tiny"

    def test_submit_chunked_manifest(self, master_addr):
        # 1 MiB payload, 256 KiB chunks → 4 chunk fids + manifest needle
        blob = bytes(range(256)) * 4096
        r = op.submit_file(master_addr, "big.bin", blob, max_mb=0)
        # force chunking with a tiny max by calling the chunk path directly
        r = op.submit_file(master_addr, "big.bin", blob, mime="application/x-test")
        assert r.error == ""

        # chunked: monkey the chunk size via max_mb=1 on a >1MiB payload
        blob2 = blob + blob  # 2 MiB
        r2 = op.submit_file(master_addr, "big2.bin", blob2, max_mb=1)
        assert r2.error == ""
        status, data = http_get(f"http://{r2.file_url}")
        assert status == 200
        assert data == blob2

    def test_chunk_manifest_cascade_delete(self, master_addr):
        import json
        import urllib.error

        blob = b"z" * (2 * 1024 * 1024 + 17)
        r = op.submit_file(master_addr, "casc.bin", blob, max_mb=1)
        assert r.error == ""
        # read the raw manifest needle (bypassing fan-in is not possible
        # over HTTP, so re-fetch chunk list by re-deriving it: the chunks
        # are the only other fids in the volume — instead, rebuild the
        # manifest client-side the same way submit_file did)
        # simpler: fetch via lookup of each chunk after capturing them
        # from a fresh chunked submit
        chunks = []
        orig_upload = op.upload

        def spy_upload(url, data, **kw):
            res = orig_upload(url, data, **kw)
            if kw.get("is_chunk_manifest"):
                for c in json.loads(data)["chunks"]:
                    chunks.append(c["fid"])
            return res

        op.upload = spy_upload
        try:
            r = op.submit_file(master_addr, "casc2.bin", blob, max_mb=1)
        finally:
            op.upload = orig_upload
        assert r.error == "" and len(chunks) >= 2

        op.delete(r.file_url)
        # manifest gone
        with pytest.raises(urllib.error.HTTPError):
            http_get(f"http://{r.file_url}")
        # every chunk cascade-deleted
        for fid in chunks:
            with pytest.raises(urllib.error.HTTPError):
                http_get(f"http://{op.lookup_file_id(master_addr, fid)}")


class TestMasterClient:
    def test_keepconnected_map_and_lookup(self, cluster, master_addr):
        master, _ = cluster
        # populate at least one volume
        ar = op.assign(master_addr)
        op.upload(f"{ar.url}/{ar.fid}", b"mc")
        mc = MasterClient("test-client", [master_addr])
        mc.start()
        try:
            assert mc.wait_until_connected(10)
            vid = int(ar.fid.split(",")[0])
            deadline = time.time() + 5
            while time.time() < deadline and not mc.vid_map.lookup(vid):
                time.sleep(0.05)
            urls = mc.lookup_file_id(ar.fid)
            assert urls
            data, _ = op.download(urls[0].removeprefix("http://"))
            assert data == b"mc"
        finally:
            mc.stop()

    def test_unary_refresh_fallback(self, cluster, master_addr):
        master, _ = cluster
        ar = op.assign(master_addr)
        op.upload(f"{ar.url}/{ar.fid}", b"rf")
        mc = MasterClient("lazy-client", [master_addr])
        # no start(): stream never connects, lookup must fall back to
        # the unary LookupVolume path
        mc.current_master = master_addr
        urls = mc.lookup_file_id(ar.fid)
        assert urls


class TestPooledHttp:
    """The keep-alive client transport (operation.http_call): reuse,
    redirect following, and error-status connection hygiene."""

    @pytest.fixture()
    def little_server(self):
        import threading
        from http.server import BaseHTTPRequestHandler

        from seaweedfs_tpu.util.httpd import WeedHTTPServer

        hits = []

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                hits.append(self.path)
                if self.path == "/hop":
                    self.send_response(302)
                    self.send_header("Location", "/land")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = b"ok:" + self.path.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                # reject WITHOUT draining the body — the hostile case
                self.send_response(401)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        srv = WeedHTTPServer(("127.0.0.1", 0), H)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield f"127.0.0.1:{srv.server_address[1]}", hits
        srv.shutdown()

    def test_redirect_followed(self, little_server):
        from seaweedfs_tpu.client.operation import http_call

        addr, hits = little_server
        status, headers, body = http_call("GET", f"{addr}/hop")
        assert status == 200
        assert body == b"ok:/land"
        assert hits == ["/hop", "/land"]

    def test_connection_reused_across_calls(self, little_server):
        from seaweedfs_tpu.client import operation as op

        addr, hits = little_server
        op.http_call("GET", f"{addr}/a")
        conns = getattr(op._http_pool, "conns", {})
        first = conns.get(addr)
        assert first is not None
        op.http_call("GET", f"{addr}/b")
        assert conns.get(addr) is first, "connection was not reused"

    def test_error_status_drops_pooled_connection(self, little_server):
        """A 4xx reply may leave an undrained request body on the wire;
        reusing that connection would parse body bytes as the next
        request line (manifested as bogus 501s)."""
        from seaweedfs_tpu.client import operation as op

        addr, hits = little_server
        status, _, _ = op.http_call("POST", f"{addr}/up", body=b"Z" * 4096)
        assert status == 401
        conns = getattr(op._http_pool, "conns", {})
        assert addr not in conns, "connection kept after error status"
        # and the next call works on a fresh connection
        status, _, body = op.http_call("GET", f"{addr}/after")
        assert status == 200 and body == b"ok:/after"
