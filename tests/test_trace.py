"""Tracing plane (docs/TRACING.md): ring-buffer semantics, header
propagation across real cross-process-shaped hops (gateway → filer →
volume → replica fan-out, EC remote reads over gRPC metadata), the
slow-trace threshold, the wlog request-id prefix, and the operator
endpoints. All servers share this process, so the per-process span ring
doubles as the cross-hop assertion surface — every hop's span lands in
the same ring, distinguishable by its node label."""

from __future__ import annotations

import json
import logging
import tempfile
import threading
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu import trace
from seaweedfs_tpu.trace import tracer as tracer_mod
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.availability import free_port, start_cluster


@pytest.fixture(autouse=True)
def _fresh_ring():
    trace.reset()
    trace.set_enabled(True)
    trace.set_slow_threshold_ms(0)
    yield
    trace.reset()
    trace.set_slow_threshold_ms(0)


def _spans_for(trace_id: str) -> list[dict]:
    return [
        s
        for s in trace.debug_payload(tracer_mod._RING_SIZE)["recent"]
        if s["trace"] == trace_id
    ]


# ----------------------------------------------------------------------
# unit tier


class TestHeader:
    def test_round_trip(self):
        with trace.span("a", plane="scrub") as sp:
            hdr = trace.header_value()
            assert hdr == f"{sp.trace_id}:{sp.span_id}:scrub"
            assert trace.parse_header(hdr) == (
                sp.trace_id, sp.span_id, "scrub"
            )

    @pytest.mark.parametrize(
        "bad",
        [
            "", "justone", "a:b", "a:b:c:d",
            "x" * 200,  # over length cap
            (":" + "p" * 33 + ":serve"),  # empty trace id
            ("t" * 33 + "::serve"),  # oversized trace id
            # non-hex ids rejected: a wire id lands inside wlog's
            # %-format prefix, so '%s' must never survive the parse
            "%s%s%s%s:0badc0de:serve",
            "abcd:%s:serve",
            "xyz!:0badc0de:serve",
        ],
    )
    def test_malformed_rejected(self, bad):
        assert trace.parse_header(bad) is None

    def test_unknown_plane_normalizes_to_serve(self):
        assert trace.parse_header("aa:bb:weird") == ("aa", "bb", "serve")

    def test_inherits_header_when_no_ambient_span(self):
        with trace.span("child", header="cafe01:beef02:repair") as sp:
            assert sp.trace_id == "cafe01"
            assert sp.parent_id == "beef02"
            assert sp.plane == "repair"

    def test_ambient_span_wins_over_header(self):
        with trace.span("outer") as outer:
            with trace.span("inner", header="cafe01:beef02:scrub") as sp:
                assert sp.trace_id == outer.trace_id
                assert sp.parent_id == outer.span_id

    def test_disabled_is_null_span(self):
        trace.set_enabled(False)
        sp = trace.span("x")
        assert not sp
        with sp:
            sp.add_stages({"a": 1.0})
            sp.annotate("k", "v")
        assert trace.header_value() is None
        assert trace.grpc_metadata() is None
        assert trace.debug_payload(8)["recorded"] == 0


class TestRing:
    def test_overflow_keeps_newest(self):
        size = tracer_mod._RING_SIZE
        for i in range(size + 50):
            with trace.span(f"s{i}"):
                pass
        payload = trace.debug_payload(size)
        assert payload["recorded"] == size + 50
        assert payload["dropped"] == 50
        assert len(payload["recent"]) == size
        # newest first
        assert payload["recent"][0]["name"] == f"s{size + 49}"
        # the overwritten oldest are gone
        names = {s["name"] for s in payload["recent"]}
        assert "s0" not in names and "s49" not in names

    def test_concurrent_appends_never_lose_count(self):
        n_threads, per_thread = 8, 500

        def hammer(k):
            for i in range(per_thread):
                with trace.span(f"t{k}.{i}"):
                    pass

        threads = [
            threading.Thread(target=hammer, args=(k,))
            for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        payload = trace.debug_payload(0)
        assert payload["recorded"] == n_threads * per_thread
        assert payload["inflight"] == 0
        # every surviving slot holds a fully-finished span
        full = trace.debug_payload(tracer_mod._RING_SIZE)
        assert len(full["recent"]) == min(
            tracer_mod._RING_SIZE, n_threads * per_thread
        )

    def test_slowest_table_tracks_root_spans(self):
        for ms, name in ((0.0, "fast"), (0.03, "slow")):
            with trace.span(name):
                if ms:
                    time.sleep(ms)
        slowest = trace.debug_payload(0)["slowest"]
        assert slowest and slowest[0]["name"] == "slow"

    def test_inflight_visible_while_open(self):
        with trace.span("open-one"):
            inflight = trace.inflight_payload()["inflight"]
            assert any(s["name"] == "open-one" for s in inflight)
        assert trace.inflight_payload()["inflight"] == []


class TestSlowTrace:
    def test_threshold_logs_through_wlog_with_trace_id(self):
        wlog._ensure_configured()
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        h = Capture()
        wlog._logger.addHandler(h)
        try:
            trace.set_slow_threshold_ms(1.0)
            with trace.span("slow.op") as sp:
                time.sleep(0.01)
                tid = sp.trace_id
            trace.set_slow_threshold_ms(0)
            with trace.span("fast.op"):
                pass
        finally:
            wlog._logger.removeHandler(h)
        slow_lines = [r for r in records if "slow trace" in r]
        assert len(slow_lines) == 1
        assert tid in slow_lines[0]
        assert "slow.op" in slow_lines[0]

    def test_cli_flag_unset_keeps_env_threshold(self):
        """An unset -traceSlowMs must not clobber a threshold set via
        WEED_TRACE_SLOW_MS; an explicit 0 must still disable it."""
        from types import SimpleNamespace

        from seaweedfs_tpu.command.servers import _apply_trace_flags

        trace.set_slow_threshold_ms(123.0)
        try:
            _apply_trace_flags(
                SimpleNamespace(traceSlowMs=None, traceSample=0)
            )
            assert trace.slow_threshold_ms() == 123.0
            _apply_trace_flags(
                SimpleNamespace(traceSlowMs=0.0, traceSample=0)
            )
            assert trace.slow_threshold_ms() == 0.0
        finally:
            trace.set_slow_threshold_ms(0.0)


class TestWlogRequestId:
    def test_lines_inside_span_carry_trace_id(self):
        wlog._ensure_configured()
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        h = Capture()
        wlog._logger.addHandler(h)
        try:
            with trace.span("rid.test") as sp:
                wlog.info("inside %d", 42)
                tid = sp.trace_id
            wlog.info("outside")
        finally:
            wlog._logger.removeHandler(h)
        inside = [r for r in records if "inside" in r]
        outside = [r for r in records if "outside" in r]
        assert inside and inside[0].startswith(f"[{tid}] ")
        assert outside and not outside[0].startswith("[")

    def test_set_vmodule_enables_tracer_module(self):
        assert not tracer_mod._vlog_enabled(2)
        wlog.set_vmodule("tracer=2")
        try:
            assert tracer_mod._vlog_enabled(2)
            assert not tracer_mod._vlog_enabled(3)
        finally:
            wlog.set_vmodule("")


# ----------------------------------------------------------------------
# cross-hop tier (in-process cluster; every hop's span shares the ring)


@pytest.fixture(scope="class")
def traced_cluster(tmp_path_factory):
    """master + 2 volume servers (rack0/rack1) + filer (replication 010)
    + S3 gateway, all in-process."""
    from seaweedfs_tpu.s3api.s3api_server import S3ApiServer
    from seaweedfs_tpu.server.filer_server import FilerServer

    dirs = [
        str(tmp_path_factory.mktemp("vol0")),
        str(tmp_path_factory.mktemp("vol1")),
    ]
    master, servers = start_cluster(dirs)
    filer = FilerServer(
        [f"127.0.0.1:{master.port}"],
        port=free_port(),
        replication="010",
    )
    filer.start()
    s3 = S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=free_port())
    s3.start()
    yield master, servers, filer, s3
    s3.stop()
    filer.stop()
    master.stop()
    for vs in servers:
        vs.stop()


class TestCrossHop:
    def test_s3_put_shares_one_trace_through_replica_fanout(
        self, traced_cluster
    ):
        master, servers, filer, s3 = traced_cluster
        trace.reset()
        base = f"http://127.0.0.1:{s3.port}"
        urllib.request.urlopen(
            urllib.request.Request(f"{base}/tracebkt", method="PUT"),
            timeout=30,
        ).close()
        # stamp a client-side trace header so the trace id is known
        req = urllib.request.Request(
            f"{base}/tracebkt/obj.bin",
            data=b"\x00\x01s3-trace-payload\xff" * 64,
            method="PUT",
        )
        req.add_header("X-Weed-Trace", "feedfeedfeedfeed:0badc0de:serve")
        urllib.request.urlopen(req, timeout=60).close()

        # EVERY hop's span closes (and lands in its node's ring) only
        # AFTER that hop's response bytes went out, so the client can
        # observe the final reply a scheduling quantum before ANY of
        # the handler threads runs span_close — the filer/volume
        # threads included, not just the outermost gateway (under
        # full-suite GIL load the filer.post close lost this race even
        # with the s3.put-only poll). Poll until the complete expected
        # span set is present, then assert its shape.
        deadline = time.time() + 5.0
        while True:
            spans = _spans_for("feedfeedfeedfeed")
            names = [s["name"] for s in spans]
            complete = (
                "s3.put" in names
                and "filer.post" in names
                and names.count("volume.post") >= 2
            )
            if complete or time.time() > deadline:
                break
            time.sleep(0.01)
        by_name: dict[str, list[dict]] = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        # gateway → filer → volume hops all on ONE trace id
        assert "s3.put" in by_name, spans
        assert "filer.post" in by_name, spans
        posts = by_name.get("volume.post", [])
        # first hop + replica fan-out (type=replicate) = 2 volume hops
        assert len(posts) == 2, spans
        s3_span = by_name["s3.put"][0]
        assert s3_span["parent"] == "0badc0de"
        filer_span = by_name["filer.post"][0]
        assert filer_span["parent"] == s3_span["span"]
        first_hop = [p for p in posts if p["parent"] != filer_span["span"]]
        # the filer's upload targets one volume server; that hop's span
        # parents the replica hop
        direct = [p for p in posts if p["parent"] == filer_span["span"]]
        assert len(direct) == 1, posts
        replica = [p for p in posts if p["parent"] == direct[0]["span"]]
        assert len(replica) == 1, posts
        assert first_hop[0] is replica[0]
        # both hops carry the full write-path stage set
        from seaweedfs_tpu.server import write_path

        for p in posts:
            assert set(p["stages_ms"]) == set(write_path.WRITE_STAGES)
        # distinct nodes served the two hops
        assert direct[0]["node"] != replica[0]["node"]

    def test_debug_endpoints_on_every_server(self, traced_cluster):
        master, servers, filer, s3 = traced_cluster
        ports = [master.port, filer.port, s3.port] + [
            vs.port for vs in servers
        ]
        for port in ports:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces?n=1", timeout=10
            ) as r:
                payload = json.loads(r.read())
            assert payload["enabled"] is True
            assert payload["ring_size"] > 0
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/requests", timeout=10
            ) as r:
                assert "inflight" in json.loads(r.read())

    def test_gateway_metrics_exposed_with_status_labels(self, traced_cluster):
        master, servers, filer, s3 = traced_cluster
        # at least one S3 request has been served by the earlier tests;
        # issue one more deterministically
        urllib.request.urlopen(
            f"http://127.0.0.1:{s3.port}/debug/traces?n=0", timeout=10
        ).close()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{s3.port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert "weed_http_request_total" in text
        assert 'server="s3"' in text
        assert 'status="200"' in text
        assert "weed_http_request_seconds" in text
        assert "weed_span_seconds" in text


class TestDebugGate:
    def test_auth_fronted_gateway_hides_debug(self, traced_cluster):
        """With IAM identities configured, /debug/* and /metrics on the
        S3 gateway are served only to loopback peers; everyone else
        falls through to the authenticated bucket routing."""
        from seaweedfs_tpu.s3api import auth as s3auth

        master, servers, filer, s3 = traced_cluster
        gate = s3._http_server.debug_gate

        class H:
            pass

        local, remote = H(), H()
        local.client_address = ("127.0.0.1", 40000)
        remote.client_address = ("203.0.113.9", 40000)
        # open gateway (no identities): everyone may read the surface
        assert gate(local) and gate(remote)
        old_iam = s3.iam
        s3.iam = s3auth.IdentityAccessManagement(
            [s3auth.Identity("op", "AK", "SK")]
        )
        try:
            assert gate(local)  # loopback operator keeps access
            assert not gate(remote)
        finally:
            s3.iam = old_iam

    def test_gate_denial_falls_through_to_handler(self, traced_cluster):
        master, servers, filer, s3 = traced_cluster
        srv = s3._http_server
        old_gate = srv.debug_gate
        srv.debug_gate = lambda h: False
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{s3.port}/debug/traces", timeout=10
                )
            # bucket routing answered (no bucket named "debug"), not
            # the trace JSON payload
            assert ei.value.code in (403, 404)
        finally:
            srv.debug_gate = old_gate


class TestShellCommands:
    def test_trace_status_and_dump(self, traced_cluster):
        master, servers, filer, s3 = traced_cluster
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import run_command

        # ensure at least one traced request exists
        urllib.request.urlopen(
            f"http://127.0.0.1:{servers[0].port}/status", timeout=10
        ).close()
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        status = run_command(env, "trace.status")
        assert "tracing on" in status
        assert f"127.0.0.1:{master.port}" in status
        dump = run_command(env, "trace.dump -n 16")
        assert "trace " in dump
        assert "status=" in dump

    def test_trace_dump_filters_by_trace_id(self, traced_cluster):
        master, servers, filer, s3 = traced_cluster
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import run_command

        req = urllib.request.Request(
            f"http://127.0.0.1:{servers[0].port}/status"
        )
        req.add_header("X-Weed-Trace", "deadbeefdeadbeef:aa00aa00:serve")
        urllib.request.urlopen(req, timeout=10).close()
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        dump = run_command(env, "trace.dump -traceId deadbeefdeadbeef")
        assert "trace deadbeefdeadbeef:" in dump
        assert "volume.get" in dump

    def test_span_ids_unique_across_processes(self):
        """Span ids ride the per-process random base: a bare counter
        would make every daemon's first span `00000001` and cross-node
        trace.dump merges would overwrite spans from different nodes."""
        from seaweedfs_tpu.trace import tracer

        with trace.span("t.unique", plane="serve") as sp:
            pass
        raw = int(sp.span_id, 16) ^ tracer._span_id_base
        # un-XORing the base must recover a small counter value
        assert 0 < raw < 1 << 20, (sp.span_id, raw)

    def test_trace_dump_merges_colliding_span_ids(self, monkeypatch):
        """Two daemons whose span counters collide (both minted
        '00000001') must both survive the trace.dump merge — keyed by
        (node, span), not span id alone."""
        from seaweedfs_tpu.shell import commands as shell_commands
        from seaweedfs_tpu.shell.commands import run_command

        payloads = {
            "n1:1": {
                "recent": [{
                    "trace": "ab" * 8, "span": "00000001", "parent": "",
                    "name": "filer.post", "plane": "serve", "node": "n1:1",
                    "start": 1.0, "dur_ms": 5.0, "status": 201, "bytes": 9,
                }],
            },
            "n2:2": {
                "recent": [{
                    "trace": "ab" * 8, "span": "00000001",
                    "parent": "00000001", "name": "volume.post",
                    "plane": "serve", "node": "n2:2", "start": 2.0,
                    "dur_ms": 3.0, "status": 201, "bytes": 9,
                }],
            },
        }
        monkeypatch.setattr(
            shell_commands, "_trace_nodes", lambda env: list(payloads)
        )
        monkeypatch.setattr(
            shell_commands,
            "_http_json",
            lambda url: payloads[url.split("//")[1].split("/")[0]],
        )
        dump = run_command(object(), "trace.dump")
        assert "filer.post" in dump
        assert "volume.post" in dump


class TestEcRemoteReadParenting:
    def test_shard_read_span_parents_under_caller(self, tmp_path):
        """VolumeEcShardRead rides gRPC invocation metadata: the
        server-side span must share the caller's trace id and parent
        under the caller's span."""
        import grpc as _grpc

        from seaweedfs_tpu.pb import rpc, volume_pb2 as pb
        from seaweedfs_tpu.server.volume_server import VolumeServer

        from tests.test_scrub import _local_ec_store  # reuse the fixture

        store, _payload = _local_ec_store(tmp_path)
        store.close()
        vs = VolumeServer([str(tmp_path)], port=free_port())
        vs.start()
        try:
            with trace.span("test.ec_read") as caller:
                with rpc.dial(f"127.0.0.1:{vs.grpc_port}") as ch:
                    data = b"".join(
                        r.data
                        for r in rpc.volume_stub(ch).VolumeEcShardRead(
                            pb.VolumeEcShardReadRequest(
                                volume_id=9, shard_id=0, offset=0, size=1024
                            ),
                            timeout=10,
                        )
                    )
                assert len(data) == 1024
                tid, caller_span = caller.trace_id, caller.span_id
        finally:
            vs.stop()
        reads = [
            s
            for s in _spans_for(tid)
            if s["name"] == "volume.ec_shard_read"
        ]
        assert len(reads) == 1, _spans_for(tid)
        assert reads[0]["parent"] == caller_span
        assert reads[0]["plane"] == "serve"

    def test_scrub_plane_tag_propagates(self, tmp_path):
        """A shard read driven from inside a plane=scrub span arrives
        tagged scrub on the serving node's ring."""
        from seaweedfs_tpu.pb import rpc, volume_pb2 as pb
        from seaweedfs_tpu.server.volume_server import VolumeServer

        from tests.test_scrub import _local_ec_store

        store, _payload = _local_ec_store(tmp_path)
        store.close()
        vs = VolumeServer([str(tmp_path)], port=free_port())
        vs.start()
        try:
            with trace.span("scrub.volume", plane="scrub") as caller:
                with rpc.dial(f"127.0.0.1:{vs.grpc_port}") as ch:
                    list(
                        rpc.volume_stub(ch).VolumeEcShardRead(
                            pb.VolumeEcShardReadRequest(
                                volume_id=9, shard_id=1, offset=0, size=64
                            ),
                            timeout=10,
                        )
                    )
                tid = caller.trace_id
        finally:
            vs.stop()
        reads = [
            s
            for s in _spans_for(tid)
            if s["name"] == "volume.ec_shard_read"
        ]
        assert reads and reads[0]["plane"] == "scrub"


class TestPushLoopHealth:
    def test_dead_gateway_visible_on_metrics(self):
        from seaweedfs_tpu.stats.metrics import (
            DEFAULT_REGISTRY,
            PUSH_FAILURES,
            PUSH_UP,
            start_push_loop,
        )

        stop = threading.Event()
        port = free_port()  # nothing listens here
        before = PUSH_FAILURES.value("t-dead")
        t = start_push_loop(
            f"http://127.0.0.1:{port}",
            job="t-dead",
            interval_sec=30,
            stop_event=stop,
        )
        deadline = time.time() + 10
        while (
            PUSH_FAILURES.value("t-dead") == before
            and time.time() < deadline
        ):
            time.sleep(0.05)
        stop.set()
        t.join(timeout=5)
        assert PUSH_FAILURES.value("t-dead") > before
        assert PUSH_UP.value("t-dead") == 0.0
        text = DEFAULT_REGISTRY.render_text()
        assert "weed_metrics_push_up" in text
        assert "weed_metrics_push_failures_total" in text
