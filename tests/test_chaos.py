"""weedchaos: fault library units + the cluster scenario suite
(docs/CHAOS.md).

The scenario quartet the chaos plane ships with — leader kill during a
write fan, partition during ec.rebuild, EIO on the read path, lossy EC
gathers — each executed against REAL servers over real sockets with
the invariant checkers auditing: no acked write lost, no double-apply,
re-convergence within a bound. Plus the deadline plane's acceptance
tests: expired `X-Weed-Deadline` is 504-fast-rejected at every daemon
before any work, and `http_call`'s whole-request wall bound defeats a
trickling server.
"""

from __future__ import annotations

import errno
import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.analysis import chaos as chaos_mod
from seaweedfs_tpu.analysis.chaos import (
    ChaosProxy,
    DiskChaos,
    DiskFault,
    Fault,
    ProcChaos,
    Scenario,
    bounded_amplification,
    converges,
    no_acked_write_lost,
    no_double_apply,
    parse_disk_spec,
    run_scenario,
)
from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.client import retry as retry_mod
from seaweedfs_tpu.util import deadline as dl_mod
from tests import chaos as wiring
from tests.chaos import free_port, wait_for
from tests.faults import DeadShard


# ---------------------------------------------------------------------------
# deadline plane units


class TestDeadlineUnit:
    def test_cap_derives_remaining(self):
        d = dl_mod.Deadline.after(10.0)
        assert 9.0 < d.cap(30.0) <= 10.0  # remaining wins
        assert d.cap(0.5) == 0.5  # explicit per-op cap wins when smaller

    def test_cap_raises_when_spent(self):
        d = dl_mod.Deadline.after(-0.1)
        assert d.expired
        with pytest.raises(dl_mod.DeadlineExceeded):
            d.cap(5.0)

    def test_deadline_exceeded_is_a_timeout(self):
        # transport handlers classify TimeoutError as "do not blindly
        # replay"; an exhausted budget must ride the same arm
        assert issubclass(dl_mod.DeadlineExceeded, TimeoutError)
        assert issubclass(dl_mod.DeadlineExceeded, OSError)

    def test_header_roundtrip(self):
        d = dl_mod.Deadline.after(2.0)
        back = dl_mod.from_header(d.header_value())
        assert abs(back.remaining() - d.remaining()) < 0.05

    def test_negative_header_parses_expired(self):
        d = dl_mod.from_header("-120.0")
        assert d is not None and d.expired

    def test_garbage_header_is_none(self):
        assert dl_mod.from_header("soon") is None
        assert dl_mod.from_header("") is None

    def test_scope_nests_and_restores(self):
        outer = dl_mod.Deadline.after(5.0)
        inner = dl_mod.Deadline.after(1.0)
        assert dl_mod.current() is None
        with dl_mod.scope(outer):
            assert dl_mod.current() is outer
            with dl_mod.scope(inner):
                assert dl_mod.current() is inner
            assert dl_mod.current() is outer
        assert dl_mod.current() is None

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("WEED_DEADLINE", "0")
        with dl_mod.scope(dl_mod.Deadline.after(1.0)):
            assert dl_mod.effective() is None
            h: dict = {}
            dl_mod.stamp(h)
            assert dl_mod.DEADLINE_HEADER not in h


# ---------------------------------------------------------------------------
# unified retry policy units


class TestRetryUnit:
    def _policy(self, **kw):
        kw.setdefault("budget", None)
        kw.setdefault("backoff_ms", 1)
        kw.setdefault("backoff_max_ms", 2)
        return retry_mod.RetryPolicy(**kw)

    def test_attempt_cap(self):
        calls = []
        p = self._policy(attempts=3)
        with pytest.raises(OSError):
            p.run(lambda a: calls.append(a) or (_ for _ in ()).throw(OSError("x")))
        assert calls == [0, 1, 2]

    def test_success_after_retry(self):
        state = {"n": 0}

        def fn(attempt):
            state["n"] += 1
            if state["n"] < 3:
                raise OSError("flaky")
            return "ok"

        assert self._policy(attempts=4).run(fn) == "ok"

    def test_non_retryable_type_raises_immediately(self):
        calls = []
        p = self._policy(attempts=5, retry_on=(ConnectionError,))
        with pytest.raises(ValueError):
            p.run(lambda a: calls.append(a) or (_ for _ in ()).throw(ValueError()))
        assert calls == [0]

    def test_non_idempotent_applied_never_replays(self):
        calls = []
        p = self._policy(attempts=5)
        with pytest.raises(OSError):
            p.run(
                lambda a: calls.append(a) or (_ for _ in ()).throw(OSError()),
                idempotent=False,
                applied=lambda e: True,  # the request may have landed
            )
        assert calls == [0]

    def test_deadline_gates_retries(self):
        import random as _random

        calls = []
        # seeded jitter: draws ~6.7 ms then ~42 ms against a 20 ms
        # budget, so exactly ONE retry fits and the next is gated —
        # deterministic (unseeded, the uniform[0,50] chain fit a third
        # call ~8% of runs and flaked the suite)
        p = self._policy(
            attempts=10, backoff_ms=50, backoff_max_ms=50,
            rng=_random.Random(1),
        )
        with pytest.raises(OSError):
            p.run(
                lambda a: calls.append(a) or (_ for _ in ()).throw(OSError()),
                deadline=dl_mod.Deadline.after(0.02),
            )
        assert len(calls) == 2  # retry 1 fit the budget, retry 2 was gated

    def test_budget_dries_up_then_probes(self):
        budget = retry_mod.RetryBudget(ratio=0.0001, min_reserve=1.0)
        assert budget.try_spend(now=100.0)  # the reserve token
        assert budget.try_spend(now=100.1)  # dry → first probe granted
        assert not budget.try_spend(now=100.2)  # probe not due yet
        assert budget.denied == 1
        # the probe trickle resumes one interval later
        assert budget.try_spend(now=100.1 + budget.probe_interval_s)
        assert not budget.try_spend(now=100.2 + budget.probe_interval_s)

    def test_budget_credits_from_requests(self):
        budget = retry_mod.RetryBudget(ratio=0.5, min_reserve=0.0)
        assert budget.try_spend(now=9.0)  # empty bucket → the 1/s probe
        budget.note_request(4)  # 2 tokens
        assert budget.try_spend(now=9.5)
        assert budget.try_spend(now=9.5)
        assert not budget.try_spend(now=9.5)  # dry again, probe not due

    def test_full_jitter_bounded_by_ceiling(self):
        p = self._policy(attempts=5, backoff_ms=100, backoff_max_ms=150)
        for attempt, ceiling in ((1, 0.1), (2, 0.15), (3, 0.15)):
            for _ in range(20):
                w = p.backoff_for(attempt)
                assert 0.0 <= w <= ceiling

    def test_master_failover_retries_across_rounds(self):
        """Satellite regression: a leaderless window spanning one full
        rotation used to surface the raw connection error; the policy
        now retries rounds (bounded, jittered) until the new leader
        answers."""
        state = {"rounds": 0}

        def fn(master):
            state["rounds"] += 1
            if state["rounds"] <= 4:  # 2 full rotations of 2 masters
                raise ConnectionRefusedError("leader died")
            return f"ok-{master}"

        policy = retry_mod.RetryPolicy(
            attempts=4, backoff_ms=1, backoff_max_ms=2,
            retry_on=(op.AllMastersFailed,), budget=None,
        )
        result, idx = op.with_master_failover(["m1", "m2"], fn, policy=policy)
        assert result == "ok-m1" and idx == 0
        assert state["rounds"] == 5


# ---------------------------------------------------------------------------
# ChaosProxy units


def _echo_server():
    """A tiny server echoing each received chunk back, for proxy tests."""
    lst = socket.socket()
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(8)

    def serve():
        while True:
            try:
                c, _ = lst.accept()
            except OSError:
                return
            def pump(conn):
                try:
                    while True:
                        d = conn.recv(65536)
                        if not d:
                            return
                        conn.sendall(d)
                except OSError:
                    pass
                finally:
                    conn.close()
            threading.Thread(target=pump, args=(c,), daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    return lst, "127.0.0.1:%d" % lst.getsockname()[1]


class TestChaosProxyUnit:
    def test_latency_and_runtime_mutation(self):
        lst, target = _echo_server()
        proxy = ChaosProxy(target)
        try:
            proxy.response.latency_s = 0.15
            s = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
            t0 = time.monotonic()
            s.sendall(b"ping")
            assert s.recv(16) == b"ping"
            assert time.monotonic() - t0 >= 0.14
            proxy.response.latency_s = 0.0  # live retune
            t0 = time.monotonic()
            s.sendall(b"fast")
            assert s.recv(16) == b"fast"
            assert time.monotonic() - t0 < 0.1
            assert proxy.chunks_delayed >= 1
            s.close()
        finally:
            proxy.stop()
            lst.close()

    def test_partition_parks_then_heals(self):
        lst, target = _echo_server()
        proxy = ChaosProxy(target)
        try:
            s = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
            s.sendall(b"before")
            assert s.recv(16) == b"before"
            proxy.partition()
            assert proxy.partitioned
            s.sendall(b"during")
            s.settimeout(0.3)
            with pytest.raises(TimeoutError):
                s.recv(16)  # parked, not dropped
            proxy.heal()
            s.settimeout(5)
            assert s.recv(16) == b"during"  # delivered after heal
            s.close()
        finally:
            proxy.stop()
            lst.close()

    def test_drop_kills_connection(self):
        lst, target = _echo_server()
        proxy = ChaosProxy(target, seed=7)
        try:
            proxy.request.drop_p = 1.0
            s = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
            s.sendall(b"doomed")
            s.settimeout(2)
            # dropped → RST/EOF, never an echo
            try:
                got = s.recv(16)
            except OSError:
                got = b""
            assert got == b""
            assert proxy.conns_dropped >= 1
            s.close()
        finally:
            proxy.stop()
            lst.close()

    def test_rst_mid_stream(self):
        lst, target = _echo_server()
        proxy = ChaosProxy(target)
        try:
            proxy.response.rst_after_bytes = 4
            s = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
            s.sendall(b"12345678")
            s.settimeout(2)
            got = b""
            try:
                while True:
                    d = s.recv(16)
                    if not d:
                        break
                    got += d
            except OSError:
                pass  # the RST
            assert len(got) <= 4
            assert proxy.conns_rst >= 1
            s.close()
        finally:
            proxy.stop()
            lst.close()


# ---------------------------------------------------------------------------
# DiskChaos units


class TestDiskChaosUnit:
    def test_eio_on_matching_read(self, tmp_path):
        victim = tmp_path / "data.bin"
        victim.write_bytes(b"x" * 1024)
        with DiskChaos([DiskFault("eio", str(tmp_path))]):
            f = open(victim, "rb")
            with pytest.raises(OSError) as ei:
                os.pread(f.fileno(), 16, 0)
            assert ei.value.errno == errno.EIO
            f.close()
        # uninstalled: reads work again
        f = open(victim, "rb")
        assert os.pread(f.fileno(), 4, 0) == b"xxxx"
        f.close()

    def test_non_matching_paths_untouched(self, tmp_path):
        victim = tmp_path / "a" / "data.bin"
        victim.parent.mkdir()
        victim.write_bytes(b"y" * 64)
        with DiskChaos([DiskFault("eio", str(tmp_path / "other"))]):
            f = open(victim, "rb")
            assert os.pread(f.fileno(), 2, 0) == b"yy"
            f.close()

    def test_enospc_on_write(self, tmp_path):
        victim = tmp_path / "w.bin"
        with DiskChaos(
            [DiskFault("enospc", str(tmp_path), ops=("write",))]
        ):
            fd = os.open(victim, os.O_CREAT | os.O_WRONLY)
            with pytest.raises(OSError) as ei:
                os.pwrite(fd, b"data", 0)
            assert ei.value.errno == errno.ENOSPC
            os.close(fd)

    def test_short_read(self, tmp_path):
        victim = tmp_path / "s.bin"
        victim.write_bytes(b"z" * 100)
        with DiskChaos(
            [DiskFault("short", str(tmp_path), short_by=3)]
        ):
            fd = os.open(victim, os.O_RDONLY)
            assert len(os.pread(fd, 10, 0)) == 7
            os.close(fd)

    def test_max_hits_and_counter(self, tmp_path):
        victim = tmp_path / "h.bin"
        victim.write_bytes(b"q" * 16)
        fault = DiskFault("eio", str(tmp_path), max_hits=1)
        with DiskChaos([fault]):
            fd = os.open(victim, os.O_RDONLY)
            with pytest.raises(OSError):
                os.pread(fd, 4, 0)
            assert os.pread(fd, 4, 0) == b"qqqq"  # budget spent
            os.close(fd)
        assert fault.hits == 1

    def test_parse_env_spec(self):
        faults = parse_disk_spec(
            "eio:/data/v1;slow:/data/v2:read,write;garbage;short:"
        )
        assert len(faults) == 2
        assert faults[0].mode == "eio" and faults[0].ops == ("read",)
        assert faults[1].ops == ("read", "write")

    def test_uninstall_restores_os(self, tmp_path):
        import builtins

        real_pread, real_open = os.pread, builtins.open
        dc = DiskChaos([DiskFault("eio", str(tmp_path))]).install()
        assert os.pread is not real_pread
        dc.uninstall()
        assert os.pread is real_pread and builtins.open is real_open


# ---------------------------------------------------------------------------
# scenario runner units


class TestScenarioRunner:
    def test_faults_fire_in_order_and_report(self):
        fired = []
        sc = Scenario(
            "unit",
            faults=[
                Fault(0.05, lambda: fired.append("b"), name="second"),
                Fault(0.0, lambda: fired.append("a"), name="first"),
            ],
            duration_s=2.0,
        )
        report = run_scenario(sc, lambda: {"acked": {}})
        assert fired == ["a", "b"]
        assert [name for _, name in report["events"]] == ["first", "second"]
        assert report["ok"] is True

    def test_invariant_failure_raises_named(self):
        sc = Scenario("bad", faults=[], duration_s=1.0)

        def workload():
            return {"acked": {"f1": b"expect"}}

        inv = no_acked_write_lost(lambda fid: b"CORRUPTED")
        with pytest.raises(chaos_mod.InvariantFailed) as ei:
            run_scenario(sc, workload, [inv])
        assert "no_acked_write_lost" in str(ei.value)

    def test_amplification_math(self):
        inv = bounded_amplification(factor=1.15)
        report = {"requests_sent": 120, "acked": {f"f{i}": b"" for i in range(100)}, "failed": 0}
        r = inv(report)
        assert not r.ok and report["amplification"] == 1.2
        report2 = {"requests_sent": 110, "acked": {f"f{i}": b"" for i in range(100)}, "failed": 0}
        assert inv(report2).ok


# ---------------------------------------------------------------------------
# deadline plane e2e: 504 fast-reject at every daemon, wall bound


@pytest.fixture(scope="module")
def mini_cluster(tmp_path_factory):
    """1 master + 2 volume servers, in-process, for the deadline and
    lossy-gather suites."""
    from seaweedfs_tpu.server.master_server import MasterServer

    master = MasterServer(
        port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
    )
    master.start()
    servers = [
        wiring.start_volume_server(
            tmp_path_factory, f"127.0.0.1:{master.port}", f"mini{i}"
        )
        for i in range(2)
    ]
    assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _get_status(url: str, headers: dict) -> tuple[int, bytes]:
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestDeadline504E2E:
    def test_expired_deadline_rejected_at_every_daemon(self, mini_cluster):
        """Acceptance: a request entering ANY daemon with an expired
        X-Weed-Deadline is 504-fast-rejected before touching disk —
        evidenced by the span (status 504, expired-at-entry annotation,
        no work stages) and the rejection counter."""
        from seaweedfs_tpu.stats.metrics import DEADLINE_REJECTED

        master, servers = mini_cluster
        masters = [f"127.0.0.1:{master.port}"]
        fid = wiring.put_blob(masters, b"deadline payload " * 100)
        url, _ = op.with_master_failover(
            masters, lambda m: op.lookup_file_id(m, fid)
        )

        before = DEADLINE_REJECTED.value("volume")
        # healthy read first: the blob IS servable
        status, body = _get_status(f"http://{url}", {})
        assert status == 200 and body == b"deadline payload " * 100

        # expired budget → 504 at the volume server, blob untouched,
        # span evidence captured via the forced trace header
        status, body = _get_status(
            f"http://{url}",
            {
                "X-Weed-Deadline": "-250.0",
                "X-Weed-Trace": "deadbeefdeadbeef:cafecafecafecafe:serve",
            },
        )
        assert status == 504
        assert b"deadline" in body
        assert DEADLINE_REJECTED.value("volume") > before

        # ...and at the master
        status, body = _get_status(
            f"http://127.0.0.1:{master.port}/dir/assign",
            {"X-Weed-Deadline": "-5.0"},
        )
        assert status == 504

        # span evidence: a 504 span with the annotation and no stages
        vol = next(v for v in servers if f"127.0.0.1:{v.port}" == url.split("/")[0])
        with urllib.request.urlopen(
            f"http://127.0.0.1:{vol.port}/debug/traces?n=64", timeout=10
        ) as r:
            doc = json.loads(r.read())
        reject_spans = [
            s
            for s in doc.get("recent", [])
            if s.get("status") == 504
            and s.get("annot", {}).get("deadline") == "expired-at-entry"
        ]
        assert reject_spans, doc.get("recent", [])[:5]
        assert not reject_spans[-1].get("stages_ms")

    def test_expired_deadline_rejected_on_grpc(self, mini_cluster):
        import grpc

        from seaweedfs_tpu.pb import rpc as rpc_mod
        from seaweedfs_tpu.pb import volume_pb2

        master, servers = mini_cluster
        vs = servers[0]
        with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
            stub = rpc_mod.volume_stub(ch)
            with pytest.raises(grpc.RpcError) as ei:
                stub.VolumeSyncStatus(
                    volume_pb2.VolumeSyncStatusRequest(volume_id=1),
                    metadata=((dl_mod.DEADLINE_HEADER, "-100.0"),),
                )
            assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED

    def test_deadline_propagates_client_to_handler(self, mini_cluster):
        """A client deadline rides the hop header into the serving
        funnel, which installs it as the handler's ambient deadline —
        the seam every internal hop inherits from."""
        master, _ = mini_cluster
        with dl_mod.scope(dl_mod.Deadline.after(5.0)):
            status, _, body = op.http_call(
                "GET", f"127.0.0.1:{master.port}/dir/status", timeout=5
            )
        assert status == 200

    def test_stub_caps_timeout_from_ambient_deadline(self, mini_cluster):
        """An expired ambient deadline stops a gRPC hop before dialing."""
        master, _ = mini_cluster
        from seaweedfs_tpu.pb import master_pb2, rpc as rpc_mod

        ch = rpc_mod.cached_channel(f"127.0.0.1:{master.grpc_port}")
        with dl_mod.scope(dl_mod.Deadline(time.monotonic() - 1.0)):
            with pytest.raises(dl_mod.DeadlineExceeded):
                rpc_mod.master_stub(ch).LookupVolume(
                    master_pb2.LookupVolumeRequest(vids=["1"])
                )


class TestHttpCallWallBound:
    """Satellite: the per-socket-op timeout must not let a trickling
    server hold a caller forever."""

    def _trickle_server(self, byte_interval_s=0.15, total=64):
        lst = socket.socket()
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)

        def serve():
            while True:
                try:
                    c, _ = lst.accept()
                except OSError:
                    return
                def drip(conn):
                    try:
                        conn.recv(65536)
                        conn.sendall(
                            b"HTTP/1.1 200 OK\r\nContent-Length: %d\r\n\r\n"
                            % total
                        )
                        for _ in range(total):
                            conn.sendall(b"x")
                            time.sleep(byte_interval_s)
                    except OSError:
                        pass
                    finally:
                        conn.close()
                threading.Thread(target=drip, args=(c,), daemon=True).start()

        threading.Thread(target=serve, daemon=True).start()
        return lst, "127.0.0.1:%d" % lst.getsockname()[1]

    def test_wall_bound_beats_trickle(self):
        # 64 bytes at 1 byte / 150 ms = 9.6 s of trickle; each recv
        # returns within the 0.3 s op timeout so per-op timeouts never
        # fire — only the whole-request wall (0.3 × 4 = 1.2 s) stops it
        lst, addr = self._trickle_server()
        try:
            t0 = time.monotonic()
            with pytest.raises((TimeoutError, OSError)):
                op.http_call("GET", f"{addr}/trickle", timeout=0.3)
            elapsed = time.monotonic() - t0
            assert elapsed < 4.0, f"wall bound did not fire ({elapsed:.1f}s)"
        finally:
            lst.close()

    def test_explicit_deadline_bounds_whole_call(self):
        lst, addr = self._trickle_server()
        try:
            t0 = time.monotonic()
            with pytest.raises((dl_mod.DeadlineExceeded, OSError)):
                op.http_call(
                    "GET",
                    f"{addr}/trickle",
                    timeout=5,
                    deadline=dl_mod.Deadline.after(0.5),
                )
            assert time.monotonic() - t0 < 2.5
        finally:
            lst.close()


# ---------------------------------------------------------------------------
# scenario: leader kill during a concurrent write fan


class TestLeaderKillScenario:
    def test_leader_kill_write_fan(self, tmp_path_factory, monkeypatch):
        """Kill the raft leader mid-write-fan: writers re-resolve via
        the retry policy, zero acked writes lost, no double-apply, and
        the survivors re-converge on a single leader within bound."""
        # determinism under the election storm: let the in-test retry
        # budget refill freely (the amplification bound is audited by
        # the bench chaos config against a blackholed replica instead)
        monkeypatch.setenv("WEED_RETRY_BUDGET_RATIO", "1.0")
        masters = wiring.start_ha_masters(tmp_path_factory, 3)
        addrs = wiring.master_addrs(masters)
        vs = wiring.start_volume_server(
            tmp_path_factory, ",".join(addrs), "lk"
        )
        killed: list = []
        try:
            leader = next(m for m in masters if m.is_leader)
            assert wait_for(
                lambda: len(leader.topology.data_nodes()) == 1
            ), "volume server never registered"

            policy = retry_mod.RetryPolicy(
                attempts=8,
                backoff_ms=100,
                backoff_max_ms=800,
                retry_on=(op.AllMastersFailed,),
                label="chaos-leader-kill",
            )

            def kill_leader():
                killed.append(chaos_mod.kill_raft_leader(masters))

            survivors = lambda: [m for m in masters if m not in killed]  # noqa: E731

            def probe():
                live = survivors()
                if sum(1 for m in live if m.is_leader) != 1:
                    return False
                new_leader = next(m for m in live if m.is_leader)
                return len(new_leader.topology.data_nodes()) == 1

            report = run_scenario(
                Scenario(
                    "leader-kill-write-fan",
                    faults=[Fault(0.4, kill_leader, name="SIGKILL leader")],
                    duration_s=45.0,
                ),
                workload=lambda: wiring.write_fan(
                    addrs, n_writers=3, n_writes=25, policy=policy
                ),
                invariants=[
                    # convergence FIRST: the read-back audit must run
                    # against the re-elected cluster, not the election
                    converges(probe, bound_s=20.0, name="reconverged"),
                    no_acked_write_lost(
                        lambda fid: wiring.read_blob(
                            [f"127.0.0.1:{m.port}" for m in survivors()], fid
                        )
                    ),
                    no_double_apply(),
                ],
            )
            assert report["ok"], report["invariants"]
            assert killed and killed[0] is not None, "no leader was killed"
            # the kill landed mid-fan and writers still completed: the
            # re-resolve satellite's regression bar
            assert len(report["acked"]) == 75, (
                f"failed={report['failed']} — writers did not survive "
                f"the election window"
            )
            assert report["reconverged_s"] <= 20.0
        finally:
            vs.stop()
            for m in masters:
                if m not in killed:
                    try:
                        m.stop()
                    except Exception:
                        pass


# ---------------------------------------------------------------------------
# scenario: partition a survivor holder during ec.rebuild


class TestPartitionDuringRebuild:
    def test_rebuild_backs_off_then_completes_after_heal(
        self, tmp_path_factory
    ):
        """Quarantine a shard while the node holding the other half of
        the survivors is partitioned: the repair scheduler's attempt
        fails WITHIN its deadline budget (not a parked slot), backs
        off exponentially, and completes after heal — with every key
        byte-identical and the repair queue drained."""
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(),
            volume_size_limit_mb=64,
            vacuum_interval=0,
            repair_interval=0.4,
            repair_grace=0.3,
        )
        # bounded budgets for the fault window: one rebuild attempt may
        # spend 3 s (the deadline caps its parked gathers), retries
        # back off from 1 s
        master.repair.backoff_base = 1.0
        master.repair.backoff_max = 4.0
        master.repair.cooldown = 2.0
        master.repair.repair_deadline_s = 3.0
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(tmp_path_factory, maddr, "pa")
        vs_b, pair = wiring.proxied_volume_server(tmp_path_factory, maddr, "pb")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            vid, keys = wiring.seed_ec_volume(master, "pchaos")
            assert wait_for(
                lambda: wiring.registered_shards(master, vid) == 14, 30
            ), "EC spread never registered"
            wait_for(lambda: not master.repair.tasks, 30)

            ev_a = vs_a.store.find_ec_volume(vid)
            assert ev_a is not None and ev_a.shard_ids(), "A holds no shards"
            # A alone must not be able to rebuild (k=10): with the
            # spread balancing 2 nodes this holds structurally
            assert len(ev_a.shard_ids()) <= 10

            # partition B, then kill a shard on A → repair needs B
            pair.partition()
            dead = DeadShard(vid, volume_servers=[vs_a], collection="pchaos")
            sid = dead.kill()

            def task_attempted():
                t = master.repair.tasks.get(("ec_rebuild", vid))
                return t is not None and t.attempts >= 1 and t.last_error

            assert wait_for(task_attempted, 30), (
                "no bounded failed rebuild attempt under partition: "
                f"{master.repair.queue_snapshot()}"
            )

            # heal → backoff lapses → rebuild completes
            pair.heal()
            assert wait_for(
                lambda: any(
                    h["Kind"] == "ec_rebuild" and h["VolumeId"] == vid
                    for h in master.repair.history
                ),
                45,
            ), f"rebuild never completed after heal: {master.repair.queue_snapshot()}"
            assert wait_for(
                lambda: wiring.registered_shards(master, vid) == 14, 30
            ), "cluster never reconverged to 14 shards"
            assert wait_for(lambda: not master.repair.tasks, 30), (
                "repair queue did not drain"
            )

            # no acked write lost through the whole episode
            for fid, want in keys.items():
                got = wiring.read_blob([maddr], fid, collection="pchaos")
                assert got == want, f"{fid} corrupt after heal"
            assert sid in (
                set(range(14))
            )
        finally:
            pair.stop()
            vs_b.stop()
            vs_a.stop()
            master.stop()


# ---------------------------------------------------------------------------
# scenario: EIO on the EC read path → quarantine, never a crash


class TestEIOOnRead:
    def test_eio_shard_quarantined_reads_survive(self, tmp_path):
        """A failing medium (full-size shard, EIO on every pread) must
        degrade reads to reconstruction AND quarantine the shard after
        the strike budget — the serving path never crashes and every
        byte stays correct."""
        from tests.test_ec_degraded import _local_ec_store

        vid, sid = 9, 0  # _local_ec_store default vid; shard 0 dies
        victim_path = os.path.join(str(tmp_path), f"{vid}.ec{sid:02d}")
        # the shim tracks fds opened WHILE installed (the Recorder
        # model), so the store — which opens every shard at mount —
        # is created inside the fault context
        with DiskChaos([DiskFault("eio", victim_path)]) as dc:
            store, needles = _local_ec_store(tmp_path, n_needles=40)
            try:
                ev = store.find_ec_volume(vid)
                assert sid in ev.shard_ids()
                results = []
                # two passes: ~1/10 of interval reads land on the dying
                # shard, and each one strikes it once — the second pass
                # pushes it past the 3-strike quarantine threshold
                for _pass in range(2):
                    for nid, data in needles.items():
                        n = store.read_needle(vid, nid)
                        results.append((nid, bytes(n.data) == data))
                assert all(ok for _, ok in results), [
                    nid for nid, ok in results if not ok
                ]
                assert dc.faults[0].hits > 0, "the EIO fault never fired"
                # the strikes quarantined the dying shard → the repair
                # plane will regenerate it (no crash, no silent decay)
                assert sid in ev.quarantined, ev.quarantined
                assert "read errors" in ev.quarantined[sid]
            finally:
                store.close()

    def test_eio_via_env_knob_spec(self, tmp_path, monkeypatch):
        """The WEED_CHAOS_DISK env path used for subprocess clusters
        installs the same shim (idempotent)."""
        monkeypatch.setenv("WEED_CHAOS_DISK", f"eio:{tmp_path}")
        monkeypatch.setattr(chaos_mod, "_ENV_DISK", None)
        shim = chaos_mod.install_disk_chaos_from_env()
        try:
            assert shim is not None
            assert chaos_mod.install_disk_chaos_from_env() is shim  # idempotent
            victim = tmp_path / "v.bin"
            victim.write_bytes(b"abc")
            fd = os.open(victim, os.O_RDONLY)
            with pytest.raises(OSError):
                os.pread(fd, 3, 0)
            os.close(fd)
        finally:
            shim.uninstall()
            monkeypatch.setattr(chaos_mod, "_ENV_DISK", None)


# ---------------------------------------------------------------------------
# scenario: SIGSTOP gray failure (weedguard, docs/HEALTH.md)


class TestSigstopGrayFailure:
    """A SIGSTOP'd volume server keeps its TCP sessions open and its
    heartbeat STREAM alive — the binary liveness model can't see it
    until node_timeout. The phi-accrual detector must mark it suspect
    within ≤3 heartbeat intervals, write assignment must route around
    it at once, no acked write may be lost, and after SIGCONT the node
    must rejoin healthy. Runs on both serving paths."""

    HB = 0.5  # subprocess heartbeat interval (s)

    @pytest.mark.parametrize("native", ["1", "0"])
    def test_pause_suspect_exclude_recover(self, tmp_path, native):
        def http_json_url(url, timeout=3):
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read())

        def try_json(url):
            try:
                return http_json_url(url)
            except (OSError, ValueError):
                return None

        mport = free_port()
        va_port, vb_port = free_port(), free_port()
        dirs = [tmp_path / "va", tmp_path / "vb"]
        for d in dirs:
            d.mkdir()
        env_extra = {"WEED_NATIVE_SERVE": native}
        procs = [
            wiring.spawn_cli(
                "master", "-port", str(mport), "-nodeTimeout", "60",
                env_extra=env_extra,
            )
        ]
        maddr = f"127.0.0.1:{mport}"
        try:
            assert wait_for(
                lambda: try_json(f"http://{maddr}/cluster/status")
                is not None,
                45,
            )
            for port, d in ((va_port, dirs[0]), (vb_port, dirs[1])):
                procs.append(
                    wiring.spawn_cli(
                        "volume", "-port", str(port), "-dir", str(d),
                        "-mserver", maddr, "-heartbeat", str(self.HB),
                        env_extra=env_extra,
                    )
                )
            vb_url = f"127.0.0.1:{vb_port}"

            def assign():
                a = try_json(f"http://{maddr}/dir/assign")
                return None if a is None or a.get("error") else a

            def nodes_registered():
                h = try_json(f"http://{maddr}/cluster/health")
                return h is not None and len(h["NodeHealth"]["Nodes"]) == 2

            assert wait_for(nodes_registered, 60), "nodes never registered"
            assert wait_for(assign, 30)

            # seed writes so BOTH nodes hold writable volumes (the
            # exclusion assertion is vacuous otherwise) — and give the
            # phi detector a beat history to learn the cadence from
            acked = {}
            t0 = time.time()
            while time.time() - t0 < 30:
                a = assign()
                if a is None:
                    continue
                payload = f"gray {len(acked)} ".encode() * 20
                req = urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}", data=payload,
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).read()
                acked[a["fid"]] = (payload, a["url"])
                seen = {u for _, u in acked.values()}
                if len(seen) == 2 and len(acked) >= 8:
                    break
            assert {u for _, u in acked.values()} == {
                f"127.0.0.1:{va_port}", vb_url
            }, "writes never spread over both nodes"
            def node_row(url):
                h = http_json_url(f"http://{maddr}/cluster/health")
                return h["NodeHealth"]["Nodes"].get(url, {})

            def state_of(url):
                return node_row(url).get("State")

            # cadence warm-up: barrier on the detector's own Warmed bit
            # rather than sleeping a fixed 6 beats. The sleep assumed
            # wall time == beat count; under rig load the subprocess
            # beat threads run late and a fixed sleep can end with
            # fewer than the detector's minimum samples in its ring —
            # phi then stays pinned at 0 and the SIGSTOP below is
            # undetectable inside any timeout (the PR-18 flake)
            assert wait_for(
                lambda: node_row(vb_url).get("Warmed")
                and node_row(f"127.0.0.1:{va_port}").get("Warmed"),
                30,
            ), "detector never accumulated its minimum cadence samples"
            assert wait_for(lambda: state_of(vb_url) == "healthy", 10)

            # --- the gray failure: freeze B, sessions stay open
            paused = procs[2]
            # the promptness bound must track the LEARNED cadence, not
            # the configured one: the detector's gate opens at 2x the
            # worst observed inter-arrival gap, and on a loaded rig
            # that gap legitimately stretches past the configured tick
            # — a bound stated in configured beats flakes exactly then
            gate_s = float(node_row(vb_url).get("GateS") or 0.0)
            assert gate_s > 0.0, "warmed detector reported no gate"
            paused.send_signal(__import__("signal").SIGSTOP)
            t_pause = time.monotonic()
            assert wait_for(
                lambda: state_of(vb_url) == "suspect",
                max(10.0, gate_s + 10.0), interval=0.03,
            ), "paused node never went suspect"
            detect_s = time.monotonic() - t_pause
            # earliest detectable silence: the gate past the LAST beat
            # (which landed up to one full beat before the pause), then
            # ~a beat of margin for the phi threshold crossing and the
            # master-side evaluation, then poll slop
            assert detect_s <= gate_s + 2 * self.HB + 0.5, (
                f"suspect detection took {detect_s:.2f}s "
                f"(measured gate {gate_s:.2f}s + 2 beats + poll slop)"
            )

            # excluded from assignment while suspect — and writes keep
            # succeeding (routed at the healthy node), zero loss
            for i in range(8):
                a = assign()
                assert a is not None
                assert a["url"] != vb_url, (
                    f"assign targeted the SIGSTOP'd node: {a}"
                )
                payload = f"during-pause {i} ".encode() * 20
                req = urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}", data=payload,
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).read()
                acked[a["fid"]] = (payload, a["url"])

            # --- SIGCONT: the node must rejoin HEALTHY (hysteresis
            # holds it suspect briefly, then clean beats clear it)
            paused.send_signal(__import__("signal").SIGCONT)
            assert wait_for(
                lambda: state_of(vb_url) == "healthy", 30
            ), "node never recovered to healthy after SIGCONT"

            # zero acked-write loss across the whole episode
            for fid, (payload, url) in acked.items():
                with urllib.request.urlopen(
                    f"http://{url}/{fid}", timeout=10
                ) as r:
                    assert r.read() == payload, fid
        finally:
            wiring.reap_procs(procs)


# ---------------------------------------------------------------------------
# scenario: filer/S3-tier partition under the deadline plane


class TestFilerPartitionS3:
    """The chaos quartet faults master+volume; this covers the gateway
    tier (ROADMAP weedchaos follow-on): the S3 gateway reaches its
    filer only through a ChaosProxy pair. Under a blackhole partition,
    S3 GET/PUT carrying an X-Weed-Deadline budget must fail WITHIN the
    budget's order (bounded, never a 60 s park), and after heal the
    tier serves acked objects byte-identical."""

    def test_s3_bounded_failure_and_heal(self, tmp_path_factory):
        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.s3api.s3api_server import S3ApiServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs = wiring.start_volume_server(tmp_path_factory, maddr, "fp")
        fport = free_port()
        pair = chaos_mod.ProxyPair(f"127.0.0.1:{fport}")
        filer = FilerServer([maddr], port=fport, store="memory")
        filer.start()
        # the gateway reaches the filer ONLY through the faulted pair
        s3 = S3ApiServer(filer=pair.addr, port=free_port())
        s3.start()
        base = f"http://127.0.0.1:{s3.port}"
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 1)

            def s3req(url, data=None, method="GET", headers=None, timeout=30):
                req = urllib.request.Request(url, data=data, method=method)
                for k, v in (headers or {}).items():
                    req.add_header(k, v)
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return r.status, r.read()

            # healthy tier: bucket + object round-trip
            assert s3req(f"{base}/chaosbkt", method="PUT")[0] == 200
            body = b"filer partition payload " * 40
            assert s3req(
                f"{base}/chaosbkt/obj1", data=body, method="PUT"
            )[0] == 200
            status, got = s3req(f"{base}/chaosbkt/obj1")
            assert status == 200 and got == body

            # --- partition the filer: S3 requests with a deadline
            # budget fail BOUNDED (the gateway hop inherits the budget
            # → capped socket timeouts), never a full-timeout park
            pair.partition()
            budget_ms = 1500.0
            for method, data in (("GET", None), ("PUT", b"never lands")):
                t0 = time.monotonic()
                with pytest.raises((urllib.error.HTTPError, OSError)):
                    s3req(
                        f"{base}/chaosbkt/obj1",
                        data=data,
                        method=method,
                        headers={"X-Weed-Deadline": str(budget_ms)},
                        timeout=30,
                    )
                elapsed = time.monotonic() - t0
                assert elapsed < 10.0, (
                    f"{method} under partition took {elapsed:.1f}s — the "
                    f"deadline plane did not bound the filer hop"
                )

            # --- heal: the acked object reads back byte-identical and
            # PUTs flow again
            pair.heal()

            def healed():
                try:
                    s, g = s3req(f"{base}/chaosbkt/obj1", timeout=10)
                    return s == 200 and g == body
                except (OSError, urllib.error.HTTPError):
                    return False

            assert wait_for(healed, 30), "tier never healed"
            assert s3req(
                f"{base}/chaosbkt/obj2", data=b"after heal", method="PUT"
            )[0] == 200
            status, got = s3req(f"{base}/chaosbkt/obj2")
            assert status == 200 and got == b"after heal"
        finally:
            pair.stop()
            s3.stop()
            filer.stop()
            vs.stop()
            master.stop()


# ---------------------------------------------------------------------------
# scenario: 30% loss on the EC gather path


class TestLossyEcGather:
    def test_degraded_reads_survive_30pct_loss(self, tmp_path_factory):
        """Kill a shard on node A while node B (holding half the
        survivors) drops 30% of transfers mid-flight: degraded reads
        must stay byte-identical through the retry/hedge planes, with
        the fault verifiably firing."""
        from seaweedfs_tpu.server.master_server import MasterServer

        master = MasterServer(
            port=free_port(), volume_size_limit_mb=64, vacuum_interval=0
        )
        master.start()
        maddr = f"127.0.0.1:{master.port}"
        vs_a = wiring.start_volume_server(tmp_path_factory, maddr, "la")
        vs_b, pair = wiring.proxied_volume_server(tmp_path_factory, maddr, "lb")
        try:
            assert wait_for(lambda: len(master.topology.data_nodes()) == 2)
            vid, keys = wiring.seed_ec_volume(master, "lchaos")
            assert wait_for(
                lambda: wiring.registered_shards(master, vid) == 14, 30
            )
            dead = DeadShard(vid, volume_servers=[vs_a], collection="lchaos")
            dead.kill()

            # 30% of B's gRPC transfers (the shard gather wire) die
            # mid-flight — connection-granularity loss, the only kind
            # TCP can express
            pair.grpc.response.drop_conn_p = 0.30

            # generous attempt cap with real backoff: a dropped gRPC
            # stream leaves the channel in TRANSIENT_FAILURE for a
            # beat, so immediate retries fail in a burst — the jittered
            # waits are what let the link recover between attempts
            policy = retry_mod.RetryPolicy(
                attempts=12, backoff_ms=100, backoff_max_ms=600,
                retry_on=(OSError, urllib.error.HTTPError), budget=None,
                label="chaos-lossy-read",
            )
            url_a = f"127.0.0.1:{vs_a.port}"
            bad = []
            for fid, want in keys.items():
                def read_once(attempt, _fid=fid):
                    data, _ = op.download(
                        f"{url_a}/{_fid}?collection=lchaos", timeout=10
                    )
                    return data
                got = policy.run(read_once)
                if got != want:
                    bad.append(fid)
            assert not bad, f"corrupt degraded reads under loss: {bad}"
            assert (
                pair.grpc.conns_dropped + pair.grpc.conns_rst > 0
                or pair.grpc.bytes_forwarded > 0
            ), "the lossy link never carried/dropped gather traffic"
        finally:
            pair.stop()
            vs_b.stop()
            vs_a.stop()
            master.stop()
