"""WebDAV gateway tests over a live master+volume+filer stack, using
http.client for the non-standard DAV verbs."""

import http.client
import socket
import time
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.webdav.webdav_server import WebDavServer


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


@pytest.fixture(scope="module")
def dav(tmp_path_factory):
    mport = free_port()
    master = MasterServer(port=mport, volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("davvol"))],
        port=free_port(),
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        max_volume_counts=[20],
    )
    vs.start()
    fport = free_port()
    filer = FilerServer([f"127.0.0.1:{mport}"], port=fport, store="memory")
    filer.start()
    dport = free_port()
    wd = WebDavServer(filer=f"127.0.0.1:{fport}", port=dport)
    wd.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    yield dport
    wd.stop()
    filer.stop()
    vs.stop()
    master.stop()


def dav_req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data, dict(resp.getheaders())


def strip_ns(root):
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


class TestWebDav:
    def test_options_advertises_dav(self, dav):
        status, _, headers = dav_req(dav, "OPTIONS", "/")
        assert status == 200
        assert "1,2" in headers["DAV"]
        assert "PROPFIND" in headers["Allow"]

    def test_mkcol_put_get(self, dav):
        status, _, _ = dav_req(dav, "MKCOL", "/docs")
        assert status == 201
        # MKCOL on existing → 405
        status, _, _ = dav_req(dav, "MKCOL", "/docs")
        assert status == 405
        status, _, _ = dav_req(dav, "PUT", "/docs/readme.txt", body=b"dav content",
                               headers={"Content-Type": "text/plain"})
        assert status == 201
        status, data, headers = dav_req(dav, "GET", "/docs/readme.txt")
        assert status == 200
        assert data == b"dav content"
        assert headers["Content-Type"] == "text/plain"

    def test_propfind_depth(self, dav):
        dav_req(dav, "MKCOL", "/tree")
        dav_req(dav, "PUT", "/tree/a.bin", body=b"12345")
        dav_req(dav, "PUT", "/tree/b.bin", body=b"xy")
        status, body, _ = dav_req(dav, "PROPFIND", "/tree", headers={"Depth": "1"})
        assert status == 207
        root = strip_ns(ET.fromstring(body))
        hrefs = [r.findtext("href") for r in root.iter("response")]
        assert "/tree/" in hrefs
        assert "/tree/a.bin" in hrefs and "/tree/b.bin" in hrefs
        sizes = {
            r.findtext("href"): r.findtext("propstat/prop/getcontentlength")
            for r in root.iter("response")
        }
        assert sizes["/tree/a.bin"] == "5"
        # depth 0: only the collection itself
        status, body, _ = dav_req(dav, "PROPFIND", "/tree", headers={"Depth": "0"})
        root = strip_ns(ET.fromstring(body))
        assert len(list(root.iter("response"))) == 1
        # collections carry <collection/>
        assert root.find("response/propstat/prop/resourcetype/collection") is not None

    def test_propfind_missing_404(self, dav):
        status, _, _ = dav_req(dav, "PROPFIND", "/nope", headers={"Depth": "0"})
        assert status == 404

    def test_move(self, dav):
        dav_req(dav, "MKCOL", "/mv")
        dav_req(dav, "PUT", "/mv/old.txt", body=b"move-me")
        status, _, _ = dav_req(
            dav, "MOVE", "/mv/old.txt",
            headers={"Destination": "/mv/new.txt"},
        )
        assert status == 201
        status, data, _ = dav_req(dav, "GET", "/mv/new.txt")
        assert data == b"move-me"
        status, _, _ = dav_req(dav, "GET", "/mv/old.txt")
        assert status == 404

    def test_copy(self, dav):
        dav_req(dav, "MKCOL", "/cp")
        dav_req(dav, "PUT", "/cp/src.txt", body=b"copy-me")
        status, _, _ = dav_req(
            dav, "COPY", "/cp/src.txt", headers={"Destination": "/cp/dst.txt"}
        )
        assert status == 201
        _, data, _ = dav_req(dav, "GET", "/cp/dst.txt")
        assert data == b"copy-me"
        _, data, _ = dav_req(dav, "GET", "/cp/src.txt")
        assert data == b"copy-me"

    def test_delete(self, dav):
        dav_req(dav, "MKCOL", "/rm")
        dav_req(dav, "PUT", "/rm/f.txt", body=b"bye")
        status, _, _ = dav_req(dav, "DELETE", "/rm/f.txt")
        assert status == 204
        status, _, _ = dav_req(dav, "GET", "/rm/f.txt")
        assert status == 404
        # recursive collection delete
        dav_req(dav, "PUT", "/rm/deep.txt", body=b"x")
        status, _, _ = dav_req(dav, "DELETE", "/rm")
        assert status == 204
        status, _, _ = dav_req(dav, "PROPFIND", "/rm", headers={"Depth": "0"})
        assert status == 404

    def test_lock_unlock(self, dav):
        dav_req(dav, "PUT", "/locked.txt", body=b"v1")
        status, body, headers = dav_req(dav, "LOCK", "/locked.txt")
        assert status == 200
        assert "opaquelocktoken" in headers["Lock-Token"]
        status, _, _ = dav_req(dav, "UNLOCK", "/locked.txt")
        assert status == 204


def test_ranged_get(dav):
    """WebDAV forwards Range to the filer (video seeks, resumable copies)."""
    payload = bytes(range(256)) * 8
    status, _, _ = dav_req(dav, "PUT", "/r.bin", body=payload)
    assert status in (200, 201)
    status, data, headers = dav_req(
        dav, "GET", "/r.bin", headers={"Range": "bytes=100-199"}
    )
    assert status == 206
    assert data == payload[100:200]
    assert headers["Content-Range"] == f"bytes 100-199/{len(payload)}"
