"""Regression sweep: C vs Python byte-identity on every corpus entry.

tests/corpus/ holds adversarial multipart/POST inputs — the
deterministic seed set from `fuzz_post --seed-corpus`, handcrafted
edge framings, and any div_*/pending_* entries a fuzz run ever
persisted (a pending_* file in the tree means a past run CRASHED on
that input; it must now pass, or stay red until the C bug is fixed).
Each entry runs through the same oracle the fuzzer uses: the C path
either declines or matches the pure-Python path byte for byte on
.dat, .idx, and the HTTP reply.

Runs under the sanitizer builds too: WEED_NATIVE_SAN=asan plus the
LD_PRELOAD recipe from `_build.asan_preload_env()` turns this sweep
into the heap-corruption gate `bench.py --check` drives.
"""

from __future__ import annotations

import os
import pathlib

import pytest

from seaweedfs_tpu.analysis import fuzz_post

CORPUS = pathlib.Path(__file__).parent / "corpus"

pytestmark = pytest.mark.usefixtures("native_post_toolchain")


def _entries() -> list[str]:
    return sorted(p.name for p in CORPUS.glob("*.json"))


def test_corpus_is_seeded():
    """The corpus must keep its adversarial floor: ≥20 entries."""
    assert len(_entries()) >= 20, (
        "tests/corpus/ lost entries; re-seed with "
        "`python -m seaweedfs_tpu.analysis.fuzz_post --seed-corpus`"
    )


@pytest.mark.parametrize("name", _entries())
def test_corpus_entry_byte_identity(tmp_path, name):
    case = fuzz_post.case_from_json(
        (CORPUS / name).read_text(encoding="utf-8")
    )
    verdict, divergence = fuzz_post.run_case(case, str(tmp_path))
    assert divergence is None, f"{name} [{verdict}]: {divergence}"


def test_fresh_fuzz_round(tmp_path):
    """A small live round on top of the standing corpus, so tier-1
    keeps probing NEW inputs every run (fixed seed: deterministic)."""
    report = fuzz_post.run(
        iterations=25, seed=1234, corpus_dir=str(tmp_path / "corpus")
    )
    assert report.iterations == 25
    assert not report.divergences, report.divergences
