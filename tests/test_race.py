"""weedrace v4 (docs/ANALYSIS.md): the dynamic schedule enumerator,
the shm GCRA model check, and the cross-process SIGKILL sweep over the
real mmap'd admission bucket.

The proof structure mirrors weedcrash's: every fixed unit must hold
its invariant under the explored schedules (negative controls), and
the pre-fix PR-9 / PR-15 orderings replayed as planted-bug arms must
be DETECTED (positive controls) — an enumerator that cannot re-find
the tree's own historical races certifies nothing. The GCRA check is
exhaustive for 2 workers (every load/CAS interleaving including
SIGKILL-mid-update arms), and the sweep at the bottom runs the same
kill against the REAL serve.c bucket across live sibling processes.
"""

from __future__ import annotations

import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from seaweedfs_tpu.analysis import race
from seaweedfs_tpu.util import native_serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# dynamic enumerator: fixed arms hold, planted arms detected


class TestFixedUnits:
    """Every concurrency unit's stated invariant must survive the
    schedule budget — these are the shapes the tree actually ships
    (AdmissionController, TileCache, GroupCommitter, gather_first_k,
    HandoffAgent, SingleFlight)."""

    @pytest.mark.parametrize("unit", sorted(race.ALL_UNITS))
    def test_invariant_holds_under_schedules(self, unit):
        rep = race.ALL_UNITS[unit](budget=15, seed=0)
        assert rep.violations == [], (
            f"{unit}: {rep.violations[:2]} after {rep.schedules_run} "
            f"schedules"
        )
        assert rep.schedules_run > 0
        # the scheduler must actually have interleaved something — a
        # run with zero decision points explored exactly one ordering
        # and proves nothing
        assert rep.decision_points > 0, (
            f"{unit}: no scheduling decisions taken "
            f"({rep.schedules_run} schedules ran free)"
        )

    def test_report_shape(self):
        rep = race.run_admission(budget=6, seed=0)
        d = rep.to_dict()
        assert d["unit"] == "admission"
        assert d["schedules_run"] <= 6
        assert isinstance(d["violations"], list)


class TestPlantedArms:
    """The regression arms: pre-fix orderings out of the tree's own
    git history, replayed through the enumerator."""

    def test_pr9_admission_ordering_detected(self):
        # check under one lock hold, count under a later one — the
        # burst that breached the in-flight cap in PR 9
        rep = race.run_admission(budget=64, seed=0, pre_fix=True)
        assert any("cap breached" in v for v in rep.violations), (
            f"pre-fix admission survived {rep.schedules_run} schedules"
        )
        # every violation carries its replay token
        assert all(v.startswith("[") for v in rep.violations)

    def test_pr15_handoff_ordering_detected(self):
        # remove-then-count: the agent that unlinked the hint before
        # counting it, leaving a window where the spool looks empty
        # with nothing counted yet
        rep = race.run_handoff(budget=72, seed=0, pre_fix=True)
        assert rep.violations, (
            f"pre-fix handoff survived {rep.schedules_run} schedules"
        )

    def test_pr12_tile_cache_ordering_detected(self):
        # generation check outside the insert's lock hold: an
        # invalidation between them leaves a stale tile resident
        rep = race.run_tile_cache(budget=32, seed=0, pre_fix=True)
        assert any("stale" in v for v in rep.violations), (
            f"pre-fix tile cache survived {rep.schedules_run} schedules"
        )


class TestKnobs:
    def test_budget_and_seed_env(self, monkeypatch):
        monkeypatch.setenv("WEED_RACE_BUDGET", "7")
        monkeypatch.setenv("WEED_RACE_SEED", "3")
        assert race.budget_default() == 7
        assert race.seed_default() == 3
        rep = race.run_admission()
        assert rep.schedules_run <= 7

    def test_garbage_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("WEED_RACE_BUDGET", "plenty")
        assert race.budget_default() == 64


# ---------------------------------------------------------------------------
# shm GCRA model check


class TestGcraModelCheck:
    def test_two_workers_exhaustive_with_kill_arms(self):
        rep = race.model_check_gcra(
            workers=2, attempts_per_worker=2, budget=20000
        )
        assert not rep.truncated, "2-worker space must enumerate fully"
        assert rep.violations == []
        # burst=2.0 at one instant: EXACTLY two tokens exist, and every
        # interleaving (including every SIGKILL placement) grants both
        assert (rep.admitted_min, rep.admitted_max) == (2, 2)
        assert rep.interleavings > 1000

    def test_kill_arms_enlarge_the_space(self):
        base = race.model_check_gcra(
            workers=2, attempts_per_worker=2, budget=20000, kill_arm=False
        )
        with_kill = race.model_check_gcra(
            workers=2, attempts_per_worker=2, budget=20000
        )
        assert with_kill.interleavings > base.interleavings
        assert base.violations == []

    def test_three_workers_clean(self):
        rep = race.model_check_gcra(
            workers=3, attempts_per_worker=1, budget=20000
        )
        assert rep.violations == []
        assert (rep.admitted_min, rep.admitted_max) == (2, 2)

    def test_blind_store_double_spends(self):
        # the planted arm: replace the CAS with a plain store and the
        # model check must observe a double-spend — this is the bug
        # class the shm-atomics ctier rule guards serve.c against
        rep = race.model_check_gcra(
            workers=2, attempts_per_worker=2,
            blind_store=True, kill_arm=False,
        )
        assert any("double-spend" in v for v in rep.violations)


# ---------------------------------------------------------------------------
# the real bucket: SIGKILL a sibling mid-update (weedcrash idiom)

_needs_shm = pytest.mark.skipif(
    not native_serve.available(),
    reason="native serve extension (shm bucket) unavailable",
)

_CHILD = """\
import sys, time
from seaweedfs_tpu.util import native_serve as ns
path, rate, burst, dur = sys.argv[1], float(sys.argv[2]), \
    float(sys.argv[3]), float(sys.argv[4])
ns.admission_shm_attach(path, rate, burst, 0.0)
print("up", flush=True)
t0 = time.monotonic()
n = 0
while time.monotonic() - t0 < dur:
    if ns.admission_shm_admit("tenant") == 0.0:
        n += 1
    time.sleep(0.001)
print(n, flush=True)
"""


@_needs_shm
class TestShmSigkillSweep:
    def _spawn(self, path: str, rate: float, burst: float, dur: float):
        return subprocess.Popen(
            [sys.executable, "-c", _CHILD, path, str(rate), str(burst),
             str(dur)],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.PIPE,
            text=True,
        )

    def test_sigkill_mid_update_survivors_keep_budget(self, tmp_path):
        """Three siblings hammer one bucket; one dies by SIGKILL
        mid-loop. Survivors must neither wedge nor overrun the GLOBAL
        budget, and a fresh process must attach the same file and get
        a sane bucket afterwards (no corrupt state inherited)."""
        shm = str(tmp_path / "adm.tb")
        rate, burst, dur = 50.0, 10.0, 1.2
        t0 = time.monotonic()
        procs = [self._spawn(shm, rate, burst, dur) for _ in range(3)]
        try:
            for p in procs:  # all attached and admitting
                assert p.stdout.readline().strip() == "up"
            time.sleep(0.3)
            victim = procs[0]
            victim.kill()  # SIGKILL: no atexit, no detach, no unlock
            victim.wait(timeout=10)
            counts = []
            for p in procs[1:]:
                out, _ = p.communicate(timeout=30)
                assert p.returncode == 0, "survivor wedged or crashed"
                counts.append(int(out.strip().splitlines()[-1]))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        elapsed = time.monotonic() - t0
        budget = burst + rate * elapsed
        # the victim's pre-death admits also drew real tokens, so the
        # survivors alone must land under the whole-bucket cap
        assert sum(counts) <= 1.1 * budget + 1, (
            f"survivors admitted {sum(counts)} of a {budget:.1f} budget "
            f"— the killed sibling's state leaked tokens back"
        )
        assert all(c > 0 for c in counts), (
            f"a survivor starved entirely ({counts}) — wedged bucket"
        )
        # recovery arm: a clean successor attaches the same file and a
        # NEW tenant still gets its exact burst
        probe = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "from seaweedfs_tpu.util import native_serve as ns\n"
             f"ns.admission_shm_attach({shm!r}, {rate}, {burst}, 0.0)\n"
             "print(sum(1 for _ in range(40)"
             " if ns.admission_shm_admit('fresh-tenant') == 0.0))\n"],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120,
        )
        assert probe.returncode == 0, probe.stderr[-2000:]
        assert int(probe.stdout.strip()) == int(burst), (
            "successor did not inherit a sane bucket"
        )

    def test_torn_header_rejected_not_inherited(self, tmp_path):
        """The torn-state arm: a corrupted header (bad magic) must be
        REJECTED at attach — never silently mapped as a budget."""
        shm = str(tmp_path / "adm.tb")
        init = subprocess.run(
            [sys.executable, "-c",
             "from seaweedfs_tpu.util import native_serve as ns\n"
             f"ns.admission_shm_attach({shm!r}, 50.0, 10.0, 0.0)\n"
             "ns.admission_shm_admit('t')\n"],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120,
        )
        assert init.returncode == 0, init.stderr[-2000:]
        with open(shm, "r+b") as f:  # scribble the magic
            f.write(struct.pack("<Q", 0xDEADBEEF))
        probe = subprocess.run(
            [sys.executable, "-c",
             "from seaweedfs_tpu.util import native_serve as ns\n"
             "try:\n"
             f"    ns.admission_shm_attach({shm!r}, 50.0, 10.0, 0.0)\n"
             "except OSError:\n"
             "    print('rejected')\n"
             "else:\n"
             "    print('accepted')\n"],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120,
        )
        assert probe.returncode == 0, probe.stderr[-2000:]
        assert probe.stdout.strip() == "rejected", (
            "corrupt bucket header was silently accepted"
        )
