"""Notification queues + cross-cluster replication tests: queue units,
then a live source-cluster → sink-cluster replication pass (the
reference covers this only via manual docker-compose; SURVEY §4)."""

import os
import socket
import time
import urllib.request

import pytest

from seaweedfs_tpu import notification
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.sink import FilerSink, LocalSink
from seaweedfs_tpu.replication.source import FilerSource
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.util.config import Configuration


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def _event(key_old=None, key_new=None, chunks=()):
    msg = fpb.EventNotification()
    if key_old:
        msg.old_entry.name = key_old
    if key_new:
        msg.new_entry.name = key_new
        for fid in chunks:
            msg.new_entry.chunks.add(fid=fid, size=1)
    return msg


class TestQueues:
    def test_memory_queue(self):
        q = notification.MemoryQueue()
        q.send_message("/a", _event(key_new="a"))
        got = q.receive(timeout=1)
        assert got is not None
        key, msg = got
        assert key == "/a"
        assert msg.new_entry.name == "a"
        assert q.receive(timeout=0.01) is None

    def test_dir_queue_durable_ordering(self, tmp_path):
        q = notification.DirQueue(str(tmp_path))
        for i in range(5):
            q.send_message(f"/k{i}", _event(key_new=f"e{i}"))
        got = list(q.consume())
        assert [k for _, k, _ in got] == [f"/k{i}" for i in range(5)]
        # a new instance over the same dir continues the sequence
        q2 = notification.DirQueue(str(tmp_path))
        q2.send_message("/k5", _event(key_new="e5"))
        seqs = [s for s, _, _ in q2.consume()]
        assert seqs == sorted(seqs) and len(seqs) == 6
        # offset-based resume
        tail = list(q2.consume(after_seq=seqs[3]))
        assert [k for _, k, _ in tail] == ["/k4", "/k5"]

    def test_configure_from_toml(self, tmp_path):
        cfg = Configuration(
            {"notification": {"dirqueue": {"enabled": True, "dir": str(tmp_path / "q")}}},
            env={},
        )
        q = notification.configure(cfg)
        assert isinstance(q, notification.DirQueue)
        notification.queue = None

    def test_gated_queue_raises(self):
        cfg = Configuration(
            {"notification": {"kafka": {"enabled": True}}}, env={}
        )
        with pytest.raises(RuntimeError, match="kafka"):
            notification.configure(cfg)
        notification.queue = None


@pytest.fixture()
def two_clusters(tmp_path_factory):
    """source (master+volume+filer, dirqueue notifications) and sink
    (master+volume+filer) clusters."""
    qdir = str(tmp_path_factory.mktemp("queue"))
    notification.queue = notification.DirQueue(qdir)
    stacks = []
    try:
        filers = []
        for name in ("src", "dst"):
            mport = free_port()
            master = MasterServer(port=mport, volume_size_limit_mb=64)
            master.start()
            vs = VolumeServer(
                [str(tmp_path_factory.mktemp(f"{name}vol"))],
                port=free_port(),
                master=f"127.0.0.1:{mport}",
                heartbeat_interval=0.2,
                max_volume_counts=[20],
            )
            vs.start()
            fport = free_port()
            filer = FilerServer(
                [f"127.0.0.1:{mport}"], port=fport, store="memory"
            )
            filer.start()
            stacks.extend([filer, vs, master])
            deadline = time.time() + 45
            while time.time() < deadline and not master.topology.data_nodes():
                time.sleep(0.05)
            filers.append(f"127.0.0.1:{fport}")
            if name == "src":
                # only the source publishes events
                notification.queue = None
        notification.queue = None
        yield filers[0], filers[1], qdir
    finally:
        notification.queue = None
        for s in stacks:
            s.stop()


def _drain(qdir: str, replicator: Replicator) -> int:
    q = notification.DirQueue(qdir)
    n = 0
    for _, key, msg in q.consume():
        replicator.replicate(key, msg)
        n += 1
    return n


class TestReplicationEndToEnd:
    def _post(self, filer, path, data):
        req = urllib.request.Request(
            f"http://{filer}{path}", data=data, method="POST"
        )
        urllib.request.urlopen(req, timeout=10).close()

    def _get(self, filer, path) -> bytes:
        with urllib.request.urlopen(f"http://{filer}{path}", timeout=10) as r:
            return r.read()

    def test_filer_sink_create_and_delete(self, two_clusters):
        src_filer, dst_filer, qdir = two_clusters
        # re-arm the queue for the source writes below
        notification.queue = notification.DirQueue(qdir)
        try:
            src_stack_payload = b"replicate-me " * 1000
            self._post(src_filer, "/buckets/docs/a.txt", src_stack_payload)
            self._post(src_filer, "/buckets/docs/b.txt", b"second-file")
        finally:
            notification.queue = None

        source = FilerSource(src_filer, directory="/buckets")
        sink = FilerSink(dst_filer, directory="/backup")
        replicator = Replicator(source, sink)
        assert _drain(qdir, replicator) >= 2
        assert self._get(dst_filer, "/backup/docs/a.txt") == src_stack_payload
        assert self._get(dst_filer, "/backup/docs/b.txt") == b"second-file"

        # delete propagates
        notification.queue = notification.DirQueue(qdir)
        try:
            req = urllib.request.Request(
                f"http://{src_filer}/buckets/docs/b.txt", method="DELETE"
            )
            urllib.request.urlopen(req, timeout=10).close()
        finally:
            notification.queue = None
        # replay only the tail (skip already-applied events)
        q = notification.DirQueue(qdir)
        events = list(q.consume())
        last_seq, last_key, last_msg = events[-1]
        replicator.replicate(last_key, last_msg)
        with pytest.raises(urllib.error.HTTPError):
            self._get(dst_filer, "/backup/docs/b.txt")
        source.close()
        sink.close()

    def test_local_sink(self, two_clusters, tmp_path):
        src_filer, _, qdir = two_clusters
        notification.queue = notification.DirQueue(qdir)
        try:
            self._post(src_filer, "/buckets/imgs/x.bin", b"local-sink-bytes")
        finally:
            notification.queue = None
        source = FilerSource(src_filer, directory="/buckets")
        sink = LocalSink(str(tmp_path / "mirror"))
        _drain(qdir, Replicator(source, sink))
        assert (tmp_path / "mirror/imgs/x.bin").read_bytes() == b"local-sink-bytes"
        source.close()


import urllib.error  # noqa: E402


class TestS3Sink:
    """Replicate filer updates into an S3 bucket — served by this
    repo's own gateway (sink/s3sink/s3_sink.go role)."""

    def test_create_update_delete_through_s3(self, tmp_path_factory):
        import socket
        import time as _time

        from seaweedfs_tpu.replication.replicator import Replicator
        from seaweedfs_tpu.replication.sink import S3Sink
        from seaweedfs_tpu.replication.source import FilerSource
        from seaweedfs_tpu.s3api import S3ApiServer
        from seaweedfs_tpu.s3api.auth import Identity, IdentityAccessManagement
        from seaweedfs_tpu.s3api.client import S3Client
        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        from seaweedfs_tpu.util.availability import free_port

        servers = []

        def up(s):
            s.start()
            servers.append(s)
            return s

        master = up(MasterServer(port=free_port(), volume_size_limit_mb=64))
        vs = up(
            VolumeServer(
                [str(tmp_path_factory.mktemp("s3sinkvs"))],
                port=free_port(),
                master=f"127.0.0.1:{master.port}",
                heartbeat_interval=0.2,
                max_volume_counts=[100],
            )
        )
        deadline = _time.time() + 45
        while _time.time() < deadline and len(master.topology.data_nodes()) < 1:
            _time.sleep(0.05)
        filer = up(
            FilerServer(
                [f"127.0.0.1:{master.port}"], port=free_port(), store="memory"
            )
        )
        iam = IdentityAccessManagement([Identity("r", "rk", "rs")])
        gw = up(
            S3ApiServer(
                filer=f"127.0.0.1:{filer.port}", port=free_port(), iam=iam
            )
        )
        try:
            client = S3Client(f"127.0.0.1:{gw.port}", "rk", "rs")
            client.create_bucket("repl-dest")

            # source entry: write through the filer
            import urllib.request

            payload = b"replicate me to s3" * 20
            req = urllib.request.Request(
                f"http://127.0.0.1:{filer.port}/src/doc.bin",
                data=payload,
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()

            source = FilerSource(
                f"127.0.0.1:{filer.port}", directory="/src"
            )
            sink = S3Sink(
                f"127.0.0.1:{gw.port}", "repl-dest", "rk", "rs", directory="mirror"
            )
            sink.set_source_filer(source)
            replicator = Replicator(source, sink)

            import grpc as _grpc

            from seaweedfs_tpu.pb import filer_pb2 as fpb
            from seaweedfs_tpu.pb import rpc as _rpc

            with _grpc.insecure_channel(
                f"127.0.0.1:{filer.port + 10000}"
            ) as ch:
                entry = (
                    _rpc.filer_stub(ch)
                    .LookupDirectoryEntry(
                        fpb.LookupDirectoryEntryRequest(
                            directory="/src", name="doc.bin"
                        )
                    )
                    .entry
                )

            # create
            replicator.replicate(
                "/src/doc.bin",
                fpb.EventNotification(new_entry=entry),
            )
            assert (
                client.get_object("repl-dest", "mirror/doc.bin") == payload
            )

            # delete
            replicator.replicate(
                "/src/doc.bin",
                fpb.EventNotification(
                    old_entry=entry, delete_chunks=True
                ),
            )
            from seaweedfs_tpu.s3api.client import S3ClientError

            with pytest.raises(S3ClientError):
                client.get_object("repl-dest", "mirror/doc.bin")
        finally:
            for s in reversed(servers):
                s.stop()


def test_s3_sink_assemble_respects_visibility():
    """Overlapping chunks resolve by mtime (newest wins) and truncated
    entries stay clamped — a raw offset sort would do neither."""
    from seaweedfs_tpu.filer import filechunks
    from seaweedfs_tpu.pb import filer_pb2 as fpb
    from seaweedfs_tpu.replication.sink import S3Sink

    sink = S3Sink.__new__(S3Sink)  # no network needed for _assemble

    class FakeSource:
        def __init__(self, blobs):
            self.blobs = blobs

        def read_chunk(self, fid):
            return self.blobs[fid]

    old = filechunks.make_chunk("1,old", 10, 50, mtime=1)
    new = filechunks.make_chunk("1,new", 0, 100, mtime=2)
    sink.source = FakeSource({"1,old": b"O" * 50, "1,new": b"N" * 100})
    entry = fpb.Entry(name="f", chunks=[old, new])
    entry.attributes.file_size = 100
    assert sink._assemble(entry) == b"N" * 100  # newest wins everywhere

    # truncation: file_size clamps below the chunk extent
    entry2 = fpb.Entry(name="g", chunks=[new])
    entry2.attributes.file_size = 40
    assert sink._assemble(entry2) == b"N" * 40


def test_s3_sink_directory_delete_sweeps_prefix(tmp_path_factory):
    """One recursive directory-delete event must remove every
    replicated object under the prefix."""
    import socket
    import time as _time
    import urllib.request

    import grpc as _grpc

    from seaweedfs_tpu.pb import filer_pb2 as fpb
    from seaweedfs_tpu.pb import rpc as _rpc
    from seaweedfs_tpu.replication.replicator import Replicator
    from seaweedfs_tpu.replication.sink import S3Sink
    from seaweedfs_tpu.replication.source import FilerSource
    from seaweedfs_tpu.s3api import S3ApiServer
    from seaweedfs_tpu.s3api.auth import Identity, IdentityAccessManagement
    from seaweedfs_tpu.s3api.client import S3Client
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    from seaweedfs_tpu.util.availability import free_port

    servers = []

    def up(s):
        s.start()
        servers.append(s)
        return s

    master = up(MasterServer(port=free_port(), volume_size_limit_mb=64))
    vs = up(
        VolumeServer(
            [str(tmp_path_factory.mktemp("s3dirvs"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
    )
    deadline = _time.time() + 45
    while _time.time() < deadline and len(master.topology.data_nodes()) < 1:
        _time.sleep(0.05)
    filer = up(
        FilerServer([f"127.0.0.1:{master.port}"], port=free_port(), store="memory")
    )
    iam = IdentityAccessManagement([Identity("r", "rk", "rs")])
    gw = up(S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=free_port(), iam=iam))
    try:
        client = S3Client(f"127.0.0.1:{gw.port}", "rk", "rs")
        client.create_bucket("dir-del")
        source = FilerSource(f"127.0.0.1:{filer.port}", directory="/src")
        sink = S3Sink(f"127.0.0.1:{gw.port}", "dir-del", "rk", "rs")
        replicator = Replicator(source, sink)

        for name in ("sub/a.txt", "sub/b.txt"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{filer.port}/src/{name}",
                data=b"x" * 64,
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()
            d, _, n = f"/src/{name}".rpartition("/")
            with _grpc.insecure_channel(f"127.0.0.1:{filer.port + 10000}") as ch:
                entry = (
                    _rpc.filer_stub(ch)
                    .LookupDirectoryEntry(
                        fpb.LookupDirectoryEntryRequest(directory=d, name=n)
                    )
                    .entry
                )
            replicator.replicate(
                f"/src/{name}", fpb.EventNotification(new_entry=entry)
            )
        assert len(client.list_objects("dir-del", "sub/")) == 2

        # one recursive directory-delete event
        replicator.replicate(
            "/src/sub",
            fpb.EventNotification(
                old_entry=fpb.Entry(name="sub", is_directory=True),
                delete_chunks=True,
            ),
        )
        assert client.list_objects("dir-del", "sub/") == []
    finally:
        for s in reversed(servers):
            s.stop()


class TestPartitionedLogQueue:
    """The embedded Kafka-role broker (notification/logqueue.py):
    partition/offset/consumer-group/segment-retention semantics."""

    @staticmethod
    def _mk(tmp_path, **kw):
        from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue

        return PartitionedLogQueue(str(tmp_path / "q"), **kw)

    @staticmethod
    def _event(name: str):
        from seaweedfs_tpu.pb import filer_pb2 as fpb

        ev = fpb.EventNotification()
        ev.new_entry.name = name
        return ev

    def test_poll_commit_resume(self, tmp_path):
        q = self._mk(tmp_path, partitions=2)
        for i in range(10):
            q.send_message(f"/k{i}", self._event(f"e{i}"))
        assert q.depth("replicate") == 10

        got = q.poll("replicate", max_records=4)
        assert len(got) == 4
        high = {}
        for part, off, key, msg in got:
            high[part] = off + 1
        for part, n in high.items():
            q.commit("replicate", part, n)
        # the rest, then nothing
        rest = q.poll("replicate", max_records=100)
        assert len(rest) == 6
        seen = {m.new_entry.name for _, _, _, m in got} | {
            m.new_entry.name for _, _, _, m in rest
        }
        assert seen == {f"e{i}" for i in range(10)}
        for part, off, key, msg in rest:
            q.commit("replicate", part, off + 1)
        assert q.poll("replicate") == []
        assert q.depth("replicate") == 0
        q.close()

    def test_key_order_within_partition_and_groups_independent(self, tmp_path):
        q = self._mk(tmp_path, partitions=4)
        for i in range(6):
            q.send_message("/same/key", self._event(f"v{i}"))
        got = q.poll("a", max_records=100)
        # same key -> same partition, in append order
        assert len({part for part, *_ in got}) == 1
        assert [m.new_entry.name for _, _, _, m in got] == [
            f"v{i}" for i in range(6)
        ]
        for part, off, _, _ in got:
            q.commit("a", part, off + 1)
        # group b is unaffected by a's commits
        assert len(q.poll("b", max_records=100)) == 6
        q.close()

    def test_durable_across_reopen(self, tmp_path):
        q = self._mk(tmp_path, partitions=2)
        for i in range(5):
            q.send_message(f"/k{i}", self._event(f"e{i}"))
        got = q.poll("g", max_records=2)
        for part, off, _, _ in got:
            q.commit("g", part, off + 1)
        q.close()

        q2 = self._mk(tmp_path, partitions=2)
        rest = q2.poll("g", max_records=100)
        assert len(rest) == 3
        names = {m.new_entry.name for _, _, _, m in got} | {
            m.new_entry.name for _, _, _, m in rest
        }
        assert names == {f"e{i}" for i in range(5)}
        # producer offsets continue, no overwrite
        q2.send_message("/k9", self._event("e9"))
        assert q2.depth("g") == 4
        q2.close()

    def test_segment_roll_and_trim(self, tmp_path):
        import os

        q = self._mk(tmp_path, partitions=1, segment_bytes=256)
        for i in range(30):
            q.send_message("/k", self._event(f"payload-{i:04d}"))
        part_dir = tmp_path / "q" / "p000"
        segs = [n for n in os.listdir(part_dir) if n.endswith(".seg")]
        assert len(segs) > 1, "segments never rolled at 256B"

        got = q.poll("g", max_records=1000)
        assert len(got) == 30
        q.commit("g", 0, 30)
        removed = q.trim()
        assert removed >= 1
        left = [n for n in os.listdir(part_dir) if n.endswith(".seg")]
        assert len(left) < len(segs)
        # a new group still starts at its own offset 0 but the data is
        # gone below the trim point — documented retention-by-consumption
        q.close()

    def test_corrupt_record_cut(self, tmp_path):
        import os

        q = self._mk(tmp_path, partitions=1)
        for i in range(3):
            q.send_message("/k", self._event(f"e{i}"))
        q.close()
        part_dir = tmp_path / "q" / "p000"
        seg = next(
            os.path.join(part_dir, n)
            for n in os.listdir(part_dir)
            if n.endswith(".seg")
        )
        raw = open(seg, "rb").read()
        with open(seg, "wb") as f:  # flip a byte in the last record
            f.write(raw[:-2] + bytes([raw[-2] ^ 0xFF]) + raw[-1:])
        q2 = self._mk(tmp_path, partitions=1)
        got = q2.poll("g", max_records=100)
        assert [m.new_entry.name for _, _, _, m in got] == ["e0", "e1"]
        q2.close()

    def test_failed_replicate_redelivers_then_succeeds(self, tmp_path):
        """At-least-once: an event whose replicate() raises is NOT
        committed past — the next poll redelivers it, and per-partition
        order holds behind the failure (ADVICE r2: the old loop
        committed offset+1 even on failure, silently dropping it)."""
        from seaweedfs_tpu.replication.replicate_runner import _consume_logqueue

        q = self._mk(tmp_path, partitions=1)
        for i in range(3):
            q.send_message("/k", self._event(f"e{i}"))

        class Flaky:
            def __init__(self):
                self.done, self.failures = [], 0

            def replicate(self, key, msg):
                if msg.new_entry.name == "e1" and self.failures < 2:
                    self.failures += 1
                    raise RuntimeError("sink down")
                self.done.append(msg.new_entry.name)

        r = Flaky()
        rc = _consume_logqueue(q, r, poll_interval=0.01, stop_after_idle=0.3)
        assert rc == 0
        # e1 retried until success; order preserved; nothing dropped
        assert r.done == ["e0", "e1", "e2"]
        assert r.failures == 2
        assert q.committed("replicate", 0) == 3
        q.close()

    def test_poison_event_skipped_after_max_retries(self, tmp_path):
        """A permanently failing event is skipped (committed past) after
        the retry budget, so it can't wedge its partition forever."""
        from seaweedfs_tpu.replication import replicate_runner
        from seaweedfs_tpu.replication.replicate_runner import _consume_logqueue

        q = self._mk(tmp_path, partitions=1)
        q.send_message("/k", self._event("poison"))
        q.send_message("/k", self._event("after"))

        class AlwaysFails:
            def __init__(self):
                self.done, self.attempts = [], 0

            def replicate(self, key, msg):
                if msg.new_entry.name == "poison":
                    self.attempts += 1
                    raise RuntimeError("boom")
                self.done.append(msg.new_entry.name)

        r = AlwaysFails()
        rc = _consume_logqueue(q, r, poll_interval=0.0, stop_after_idle=5.0)
        assert rc == 0
        assert r.attempts == replicate_runner._MAX_EVENT_RETRIES
        assert r.done == ["after"]  # the partition drained past the poison
        assert q.committed("replicate", 0) == 2
        q.close()

    def test_trim_protects_group_that_polled_but_not_committed(self, tmp_path):
        """A group's first poll registers a zero offset, so trim() keeps
        its unread segments even when other groups are far ahead."""
        import os

        q = self._mk(tmp_path, partitions=1, segment_bytes=256)
        for i in range(30):
            q.send_message("/k", self._event(f"payload-{i:04d}"))
        part_dir = tmp_path / "q" / "p000"
        segs = {n for n in os.listdir(part_dir) if n.endswith(".seg")}
        assert len(segs) > 1

        assert len(q.poll("slow", max_records=5)) == 5  # polls, never commits
        got = q.poll("fast", max_records=1000)
        assert len(got) == 30
        q.commit("fast", 0, 30)
        assert q.trim() == 0, "trim deleted segments an active group hasn't read"
        assert {n for n in os.listdir(part_dir) if n.endswith(".seg")} == segs
        # slow group can still read everything from the start
        assert len(q.poll("slow", max_records=1000)) == 30
        q.close()

    def test_trim_unpins_abandoned_group_after_staleness(self, tmp_path):
        """A group that stops polling/committing goes stale after
        stale_group_seconds and no longer blocks segment retention."""
        import os
        import time as _time

        q = self._mk(tmp_path, partitions=1, segment_bytes=256,
                     stale_group_seconds=0.3)
        for i in range(30):
            q.send_message("/k", self._event(f"payload-{i:04d}"))
        part_dir = tmp_path / "q" / "p000"
        segs = {n for n in os.listdir(part_dir) if n.endswith(".seg")}

        q.poll("abandoned", max_records=5)  # registers, never returns
        got = q.poll("live", max_records=1000)
        q.commit("live", 0, 30)
        assert q.trim() == 0  # abandoned still fresh: protected
        _time.sleep(0.4)
        q.commit("live", 0, 30)  # live proves liveness; abandoned is stale
        assert q.trim() >= 1
        assert len({n for n in os.listdir(part_dir) if n.endswith(".seg")}) < len(segs)
        q.close()

    def test_configure_builds_logqueue(self, tmp_path):
        from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue
        from seaweedfs_tpu.util.config import Configuration

        cfg = Configuration(
            {
                "notification": {
                    "logqueue": {
                        "enabled": True,
                        "dir": str(tmp_path / "nq"),
                        "partitions": "2",
                    }
                }
            }
        )
        q = notification.configure(cfg)
        try:
            assert isinstance(q, PartitionedLogQueue)
            assert len(q.partitions) == 2
        finally:
            q.close()
            notification.queue = None

    def test_end_to_end_local_sink(self, two_clusters, tmp_path):
        """filer events -> logqueue -> consumer-group drain -> LocalSink,
        via the same loop filer.replicate runs (_consume_logqueue)."""
        from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue
        from seaweedfs_tpu.replication.replicate_runner import _consume_logqueue

        src_filer, _, _ = two_clusters
        qdir = str(tmp_path / "lq")
        notification.queue = PartitionedLogQueue(qdir, partitions=2)
        try:
            req = urllib.request.Request(
                f"http://{src_filer}/buckets/lq/y.bin",
                data=b"logqueue-bytes",
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()
        finally:
            notification.queue.close()
            notification.queue = None

        source = FilerSource(src_filer, directory="/buckets")
        sink = LocalSink(str(tmp_path / "mirror"))
        lq = PartitionedLogQueue(qdir, partitions=2)
        _consume_logqueue(
            lq, Replicator(source, sink), poll_interval=0.05, stop_after_idle=0.3
        )
        assert (tmp_path / "mirror/lq/y.bin").read_bytes() == b"logqueue-bytes"
        assert lq.depth("replicate") == 0
        lq.close()
        source.close()

    def test_consumer_sees_segments_rolled_after_open(self, tmp_path):
        """Regression: the consumer's segment view must track segments
        rolled (and records appended) by the producer after the
        consumer instance opened — a long-lived filer.replicate must
        never stall on a stale snapshot."""
        producer = self._mk(tmp_path, partitions=1, segment_bytes=128)
        producer.send_message("/k", self._event("early"))
        consumer = self._mk(tmp_path, partitions=1, segment_bytes=128)
        assert len(consumer.poll("g", max_records=100)) == 1
        consumer.commit("g", 0, 1)
        # producer keeps writing: tail grows AND new segments roll
        for i in range(12):
            producer.send_message("/k", self._event(f"late-{i:02d}"))
        got = consumer.poll("g", max_records=100)
        assert [m.new_entry.name for _, _, _, m in got] == [
            f"late-{i:02d}" for i in range(12)
        ]
        assert consumer.depth("g") == 12
        consumer.close()
        producer.close()

    def test_partition_count_pinned_by_meta(self, tmp_path):
        q4 = self._mk(tmp_path, partitions=4)
        for i in range(8):
            q4.send_message(f"/k{i}", self._event(f"e{i}"))
        q4.close()
        # reopening with a different configured count adopts the
        # on-disk count instead of stranding p002/p003
        q2 = self._mk(tmp_path, partitions=2)
        assert len(q2.partitions) == 4
        assert len(q2.poll("g", max_records=100)) == 8
        q2.close()

    def test_poll_fairness_hot_partition(self, tmp_path):
        q = self._mk(tmp_path, partitions=2)
        # find keys for each partition
        from seaweedfs_tpu.notification.logqueue import _partition_of

        k0 = next(f"/a{i}" for i in range(100) if _partition_of(f"/a{i}", 2) == 0)
        k1 = next(f"/b{i}" for i in range(100) if _partition_of(f"/b{i}", 2) == 1)
        for i in range(50):
            q.send_message(k0, self._event(f"hot-{i}"))
        q.send_message(k1, self._event("cold"))
        got = q.poll("g", max_records=10)
        parts = {p for p, *_ in got}
        assert 1 in parts, "hot partition starved the cold one"
        assert len(got) == 10, "leftover budget not refilled from the hot partition"
        q.close()

    def test_concurrent_producer_and_consumer_threads(self, tmp_path):
        """One producer thread appending while a consumer thread
        polls/commits from the same queue object (the filer process's
        own drain case): at-least-once, no loss, order kept per key."""
        import threading

        q = self._mk(tmp_path, partitions=2, segment_bytes=512)
        total = 300
        got: list = []
        errors: list = []
        produced_all = threading.Event()

        def producer():
            try:
                for i in range(total):
                    q.send_message(f"/k{i % 5}", self._event(f"m{i:04d}"))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                produced_all.set()

        def consumer():
            try:
                idle = 0
                # only count idle polls once the producer is done — a
                # descheduled producer must not end the drain early
                while idle < 5:
                    batch = q.poll("g", max_records=32)
                    if not batch:
                        if produced_all.is_set():
                            idle += 1
                        import time as _t

                        _t.sleep(0.01)
                        continue
                    idle = 0
                    high: dict[int, int] = {}
                    for part, off, key, msg in batch:
                        got.append((key, msg.new_entry.name))
                        high[part] = off + 1
                    for part, n in high.items():
                        q.commit("g", part, n)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        tp = threading.Thread(target=producer)
        tc = threading.Thread(target=consumer)
        tp.start(); tc.start()
        tp.join(); tc.join()
        assert not errors, errors
        assert len(got) >= total  # at-least-once
        assert {n for _, n in got} == {f"m{i:04d}" for i in range(total)}
        # per-key order preserved (same key -> same partition, append order)
        for k in range(5):
            names = [n for key, n in got if key == f"/k{k}"]
            assert names == sorted(names), f"key {k} out of order"
        q.close()

    def test_cursor_commit_survives_every_crash_state(self, tmp_path):
        """Crash-enumerator sweep of the consumer-cursor publish: a
        crash anywhere inside commit() must leave the offsets file at
        the OLD or the NEW cursor. The cursor rides util/durable
        (write tmp → fsync → rename); a raw-rename regression would
        surface an empty-file state here, which committed() parses as
        0 — silently restarting the whole group."""
        from seaweedfs_tpu.analysis import crash

        q = self._mk(tmp_path, partitions=1)
        for i in range(10):
            q.send_message("/k", self._event(f"m{i}"))
        assert len(q.poll("g", max_records=10)) == 10
        q.commit("g", 0, 5)  # the settled cursor the crash must keep
        assert q.committed("g", 0) == 5

        offsets_dir = os.path.join(str(tmp_path / "q"), "p000", "offsets")
        rec = crash.Recorder(offsets_dir)
        with rec:
            q.commit("g", 0, 9)
        states, truncated, _n = crash.enumerate_states(rec.trace, budget=256)
        assert not truncated
        assert states
        seen = set()
        for s in states:
            cur = s.files.get("g")
            assert cur in (b"5", b"9"), (
                f"torn cursor {cur!r} at crash index {s.crash_index}"
            )
            seen.add(cur)
        # both the kept-old and published-new outcomes are reachable
        assert seen == {b"5", b"9"}
        q.close()


class TestKafkaWireProtocol:
    """The library-free Kafka client (notification/kafka.py) against the
    in-repo fake broker (kafka_fake.py): record-batch v2 round-trips,
    Metadata/Produce/Fetch over a real socket, and the replication e2e
    the reference runs through sarama (notification/kafka/kafka_queue.go
    + replication/sub/notification_kafka.go)."""

    @pytest.fixture()
    def broker(self):
        from seaweedfs_tpu.notification.kafka_fake import FakeKafkaBroker

        b = FakeKafkaBroker(partitions=2)
        b.start()
        yield b
        b.stop()

    def test_record_batch_roundtrip(self):
        from seaweedfs_tpu.notification.kafka import (
            decode_record_batches,
            encode_record_batch,
        )

        recs = [(b"k1", b"v1"), (None, b"v2"), (b"k3", b"x" * 3000)]
        blob = encode_record_batch(recs, 1234567890)
        got = decode_record_batches(blob)
        assert got == [(0, b"k1", b"v1"), (1, None, b"v2"), (2, b"k3", b"x" * 3000)]

    def test_api_versions_handshake_accepts_supported(self, broker):
        """The dial-time ApiVersions probe (sarama's negotiation role,
        behind notification/kafka/kafka_queue.go) passes on a broker
        advertising the pinned versions, and the probe runs once."""
        from seaweedfs_tpu.notification.kafka import KafkaClient

        c = KafkaClient(f"{broker.host}:{broker.port}")
        assert c.metadata("t") == [0, 1]
        assert c._versions_checked

    def test_api_versions_handshake_rejects_unsupported(self, broker):
        """A broker whose Produce range excludes the pinned v3 must be
        rejected at dial with guidance, not a mid-publish wire error."""
        from seaweedfs_tpu.notification.kafka import KafkaClient

        broker.api_ranges[0] = (6, 8)  # Produce v6..v8 only (too new)
        c = KafkaClient(f"{broker.host}:{broker.port}")
        with pytest.raises(RuntimeError, match="Produce v3"):
            c.metadata("t")

    def test_api_versions_probe_killed_falls_back(self, broker):
        """A pre-ApiVersions broker (drops the probe connection) still
        serves: the client redials and proceeds on pinned versions."""
        from seaweedfs_tpu.notification import kafka_fake
        from seaweedfs_tpu.notification.kafka import KafkaClient

        broker.drop_api_versions = True
        c = KafkaClient(f"{broker.host}:{broker.port}")
        assert c.metadata("t") == [0, 1]

    def test_metadata_produce_fetch_over_socket(self, broker):
        from seaweedfs_tpu.notification.kafka import KafkaClient

        c = KafkaClient(f"{broker.host}:{broker.port}")
        assert c.metadata("t") == [0, 1]
        base = c.produce("t", 0, [(b"a", b"one"), (b"b", b"two")])
        assert base == 0
        base2 = c.produce("t", 0, [(b"c", b"three")])
        assert base2 == 2
        records, high = c.fetch("t", 0, 0)
        assert high == 3
        assert [(o, k, v) for o, k, v in records] == [
            (0, b"a", b"one"),
            (1, b"b", b"two"),
            (2, b"c", b"three"),
        ]
        # fetch from a mid offset returns only the tail
        records, _ = c.fetch("t", 0, 2)
        assert [(o, v) for o, k, v in records] == [(2, b"three")]
        c.close()

    def test_queue_gates_on_connectivity(self):
        from seaweedfs_tpu.notification.kafka import KafkaQueue

        with pytest.raises(RuntimeError, match="cannot reach a broker"):
            KafkaQueue("127.0.0.1:1")  # nothing listens on port 1

    def test_configure_builds_kafka_queue(self, broker):
        from seaweedfs_tpu.notification.kafka import KafkaQueue
        from seaweedfs_tpu.util.config import Configuration

        cfg = Configuration(
            {
                "notification": {
                    "kafka": {
                        "enabled": True,
                        "hosts": f"{broker.host}:{broker.port}",
                        "topic": "filer_events",
                    }
                }
            }
        )
        q = notification.configure(cfg)
        try:
            assert isinstance(q, KafkaQueue)
            ev = fpb.EventNotification()
            ev.new_entry.name = "via-configure"
            q.send_message("/some/path", ev)
            total = sum(len(v) for v in broker.logs.values())
            assert total == 1
        finally:
            q.close()
            notification.queue = None

    def test_replication_e2e_over_kafka(self, broker, two_clusters, tmp_path):
        """filer events -> kafka producer -> fake broker -> subscriber
        -> LocalSink, through the same at-least-once drain loop
        filer.replicate uses, with durable consumer-side offsets."""
        from seaweedfs_tpu.notification.kafka import KafkaQueue, KafkaSubscriber
        from seaweedfs_tpu.replication.replicate_runner import (
            _KafkaOffsetAdapter,
            _consume_logqueue,
        )

        src_filer, _, _ = two_clusters
        hosts = f"{broker.host}:{broker.port}"
        notification.queue = KafkaQueue(hosts, topic="filer_events")
        try:
            req = urllib.request.Request(
                f"http://{src_filer}/buckets/kq/z.bin",
                data=b"kafka-wire-bytes",
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()
        finally:
            notification.queue.close()
            notification.queue = None
        assert sum(len(v) for v in broker.logs.values()) >= 1

        source = FilerSource(src_filer, directory="/buckets")
        sink = LocalSink(str(tmp_path / "mirror"))
        sub = KafkaSubscriber(hosts, topic="filer_events")
        adapter = _KafkaOffsetAdapter(sub, str(tmp_path / "offsets"))
        _consume_logqueue(
            adapter, Replicator(source, sink), poll_interval=0.05,
            stop_after_idle=0.3,
        )
        assert (tmp_path / "mirror/kq/z.bin").read_bytes() == b"kafka-wire-bytes"
        # offsets persisted: a fresh subscriber+adapter resumes past it
        sub2 = KafkaSubscriber(hosts, topic="filer_events")
        adapter2 = _KafkaOffsetAdapter(sub2, str(tmp_path / "offsets"))
        assert adapter2.poll("replicate") == []
        sub.close()
        sub2.close()
        source.close()

    def test_broker_outage_does_not_fail_filer_writes(self, two_clusters):
        """A raising queue (kafka with a dead broker) must not turn a
        durably-stored filer write into a 500 (filer_notify.go logs and
        continues)."""
        src_filer, _, _ = two_clusters

        class ExplodingQueue:
            def send_message(self, key, message):
                raise ConnectionError("broker down")

        notification.queue = ExplodingQueue()
        try:
            req = urllib.request.Request(
                f"http://{src_filer}/buckets/oq/w.bin",
                data=b"survives-broker-outage",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status in (200, 201)
            with urllib.request.urlopen(
                f"http://{src_filer}/buckets/oq/w.bin", timeout=10
            ) as r:
                assert r.read() == b"survives-broker-outage"
        finally:
            notification.queue = None

    def test_subscriber_resets_on_offset_out_of_range(self, broker):
        """Broker retention trimmed past our offset: the subscriber must
        log-and-reset to the high watermark, not crash-loop."""
        from seaweedfs_tpu.notification.kafka import KafkaClient, KafkaSubscriber

        hosts = f"{broker.host}:{broker.port}"
        c = KafkaClient(hosts)
        c.produce("t2", 0, [(b"k", b"v1"), (b"k", b"v2")])
        c.close()
        sub = KafkaSubscriber(hosts, topic="t2")
        sub.offsets[0] = 99  # beyond the log: fake returns empty, so
        # simulate the broker-side error path directly
        from seaweedfs_tpu.notification.kafka import KafkaError

        orig_fetch = sub.client.fetch

        def erroring_fetch(topic, partition, offset, max_bytes=1 << 20):
            if offset == 99:
                raise KafkaError("fetch", KafkaError.OFFSET_OUT_OF_RANGE, 2)
            return orig_fetch(topic, partition, offset, max_bytes)

        sub.client.fetch = erroring_fetch
        assert sub.poll() == []  # reset happened instead of raising
        assert sub.offsets[0] == 2
        sub.close()


class TestCloudSinks:
    """GCS / Azure / B2 sinks speaking their REST protocols against the
    in-repo fakes (tests/cloud_fakes.py) — create, recursive directory
    delete, and (for Azure) SharedKey signature validation on every
    request."""

    def _drive(self, two_clusters, sink, fake, tag):
        src_filer, _, qdir = two_clusters
        notification.queue = notification.DirQueue(qdir)
        try:
            for name, data in (("a.bin", b"alpha-" * 100), ("sub/b.bin", b"beta")):
                req = urllib.request.Request(
                    f"http://{src_filer}/buckets/{tag}/{name}",
                    data=data,
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=10).close()
        finally:
            notification.queue = None
        source = FilerSource(src_filer, directory="/buckets")
        sink.set_source_filer(source)
        replicator = Replicator(source, sink)
        assert _drain(qdir, replicator) >= 2
        assert fake.objects[f"{tag}/a.bin"] == b"alpha-" * 100
        assert fake.objects[f"{tag}/sub/b.bin"] == b"beta"

        # recursive directory delete sweeps the prefix
        notification.queue = notification.DirQueue(qdir)
        try:
            req = urllib.request.Request(
                f"http://{src_filer}/buckets/{tag}?recursive=true",
                method="DELETE",
            )
            urllib.request.urlopen(req, timeout=10).close()
        finally:
            notification.queue = None
        q = notification.DirQueue(qdir)
        ev = list(q.consume())[-1]
        replicator.replicate(ev[1], ev[2])
        assert not any(k.startswith(f"{tag}/") for k in fake.objects), (
            fake.objects
        )
        source.close()

    def test_gcs_sink(self, two_clusters):
        from seaweedfs_tpu.replication.cloud_sinks import GcsSink
        from tests.cloud_fakes import FakeGcs

        fake = FakeGcs()
        fake.start()
        try:
            sink = GcsSink("bkt", token="t0k", endpoint=fake.endpoint)
            self._drive(two_clusters, sink, fake, "gcs")
        finally:
            fake.stop()

    def test_azure_sink_with_shared_key_signing(self, two_clusters):
        import base64

        from seaweedfs_tpu.replication.cloud_sinks import AzureSink
        from tests.cloud_fakes import FakeAzure

        key = base64.b64encode(b"azure-secret-key-32-bytes-long!!").decode()
        fake = FakeAzure("acct1", key, "cont")
        fake.start()
        try:
            sink = AzureSink("acct1", key, "cont", endpoint=fake.endpoint)
            self._drive(two_clusters, sink, fake, "az")
            # a wrong key is rejected by the fake's signature check
            bad = AzureSink(
                "acct1",
                base64.b64encode(b"wrong-key").decode(),
                "cont",
                endpoint=fake.endpoint,
            )
            import pytest as _pytest

            with _pytest.raises(RuntimeError, match="http 403"):
                bad._put("x", b"y")
        finally:
            fake.stop()

    def test_b2_sink(self, two_clusters):
        from seaweedfs_tpu.replication.cloud_sinks import B2Sink
        from tests.cloud_fakes import FakeB2

        fake = FakeB2("keyid", "appkey", "bkt2")
        fake.start()
        try:
            sink = B2Sink("keyid", "appkey", "bkt2", endpoint=fake.endpoint)
            self._drive(two_clusters, sink, fake, "b2")
        finally:
            fake.stop()

    def test_azure_b2_gate_on_missing_credentials(self, tmp_path):
        from seaweedfs_tpu.replication.replicate_runner import build_replicator
        from seaweedfs_tpu.util.config import Configuration

        for kind, needle in (
            ("azure", "account_key"),
            ("backblaze", "application_key"),
        ):
            cfg = Configuration(
                {
                    "source": {"filer": {"grpcAddress": "x:1"}},
                    "sink": {kind: {"enabled": True}},
                }
            )
            with pytest.raises(RuntimeError, match=needle):
                build_replicator(cfg)

    def test_b2_delete_removes_all_versions(self, two_clusters, tmp_path):
        """B2 keeps every uploaded version: an update then a delete
        must remove them ALL or the old version resurfaces."""
        from seaweedfs_tpu.replication.cloud_sinks import B2Sink
        from tests.cloud_fakes import FakeB2

        fake = FakeB2("k", "a", "b")
        fake.start()
        try:
            sink = B2Sink("k", "a", "b", endpoint=fake.endpoint)
            sink._put("f.bin", b"v1")
            sink._put("f.bin", b"v2")  # upsert: B2 now holds 2 versions
            assert len(fake.versions["f.bin"]) == 2
            sink._delete("f.bin")
            assert "f.bin" not in fake.objects
            assert "f.bin" not in fake.versions
        finally:
            fake.stop()

    def test_list_pagination_sweeps_every_page(self):
        """Recursive directory deletes must walk ALL list pages — a
        first-page-only sweep silently strands objects."""
        from seaweedfs_tpu.replication.cloud_sinks import (
            B2Sink,
            GcsSink,
        )
        from tests.cloud_fakes import FakeB2, FakeGcs

        fake = FakeGcs()
        fake.page_size = 2
        fake.start()
        try:
            sink = GcsSink("bkt", token="t", endpoint=fake.endpoint)
            for i in range(7):
                fake.objects[f"d/{i}.bin"] = b"x"
            assert len(sink._list("d/")) == 7
        finally:
            fake.stop()

        fake2 = FakeB2("k", "a", "b")
        fake2.page_size = 2
        fake2.start()
        try:
            sink2 = B2Sink("k", "a", "b", endpoint=fake2.endpoint)
            for i in range(7):
                sink2._put(f"d/{i}.bin", b"x")
            assert len(sink2._list("d/")) == 7
        finally:
            fake2.stop()

    def test_azure_list_pagination(self, two_clusters):
        import base64

        from seaweedfs_tpu.replication.cloud_sinks import AzureSink
        from tests.cloud_fakes import FakeAzure

        key = base64.b64encode(b"k" * 32).decode()
        fake = FakeAzure("a1", key, "c")
        fake.page_size = 2
        fake.start()
        try:
            sink = AzureSink("a1", key, "c", endpoint=fake.endpoint)
            for i in range(5):
                sink._put(f"d/{i} sp.bin", b"x")  # space: encoded-path signing
            assert len(sink._list("d/")) == 5
        finally:
            fake.stop()


class TestCloudQueues:
    """SQS (Query protocol + SigV4) and Pub/Sub (REST publish) queues
    against the in-repo fakes — the last two reference notification
    backends, implemented on the wire instead of via SDKs."""

    def test_sqs_queue_sends_signed_messages(self):
        from seaweedfs_tpu.util.config import Configuration
        from tests.cloud_fakes import FakeSqs

        fake = FakeSqs("AKID", "SECRET", "us-east-1", "weedq")
        fake.start()
        try:
            cfg = Configuration(
                {
                    "notification": {
                        "aws_sqs": {
                            "enabled": True,
                            "aws_access_key_id": "AKID",
                            "aws_secret_access_key": "SECRET",
                            "region": "us-east-1",
                            "sqs_queue_name": "weedq",
                            "endpoint": fake.endpoint,
                        }
                    }
                }
            )
            q = notification.configure(cfg)
            try:
                ev = fpb.EventNotification()
                ev.new_entry.name = "sqs-file"
                q.send_message("/buckets/sqs-file", ev)
                assert fake.messages, "no message landed"
                key, body = fake.messages[0]
                assert key == "/buckets/sqs-file"
                assert "sqs-file" in body  # text-proto form, like the reference
            finally:
                notification.queue = None
        finally:
            fake.stop()

    def test_sqs_wrong_secret_rejected(self):
        from seaweedfs_tpu.notification.cloud_queues import SqsQueue
        from tests.cloud_fakes import FakeSqs

        fake = FakeSqs("AKID", "SECRET", "us-east-1", "weedq")
        fake.start()
        try:
            with pytest.raises(RuntimeError, match="http 403"):
                SqsQueue(
                    "AKID", "WRONG", "us-east-1", "weedq",
                    endpoint=fake.endpoint,
                )
        finally:
            fake.stop()

    def test_pubsub_queue_publishes(self):
        from seaweedfs_tpu.util.config import Configuration
        from tests.cloud_fakes import FakePubSub

        fake = FakePubSub("proj1", "weedtopic")
        fake.start()
        try:
            cfg = Configuration(
                {
                    "notification": {
                        "google_pub_sub": {
                            "enabled": True,
                            "project_id": "proj1",
                            "topic": "weedtopic",
                            "endpoint": fake.endpoint,
                        }
                    }
                }
            )
            q = notification.configure(cfg)
            try:
                ev = fpb.EventNotification()
                ev.new_entry.name = "ps-file"
                q.send_message("/buckets/ps-file", ev)
                assert fake.messages
                key, data = fake.messages[0]
                assert key == "/buckets/ps-file"
                got = fpb.EventNotification()
                got.ParseFromString(data)  # serialized proto, per reference
                assert got.new_entry.name == "ps-file"
            finally:
                notification.queue = None
        finally:
            fake.stop()

    def test_pubsub_gates_without_token_on_real_endpoint(self):
        from seaweedfs_tpu.notification.cloud_queues import PubSubQueue

        with pytest.raises(RuntimeError, match="bearer"):
            PubSubQueue("p", "t")  # default googleapis endpoint, no token
