"""Admin shell tests.

Planner tests run on pure in-memory EcNode state with apply=False —
the same no-cluster pattern as the reference's shell/command_ec_test.go
(newEcNode/addEcVolumeAndShardsForTest + applyBalancing=false).
Pipeline tests drive a live in-process cluster end-to-end:
ec.encode → kill a shard → ec.rebuild → ec.balance → degraded read.
"""

import io
import time
import urllib.request

import pytest

from seaweedfs_tpu.shell import ec_common
from seaweedfs_tpu.shell.command_env import CommandEnv, TopologyDump, TopologyNodeInfo
from seaweedfs_tpu.shell.commands import (
    collect_volume_ids_for_ec_encode,
    plan_fix_replication,
    plan_volume_balance,
    run_command,
)
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from tests.test_cluster import free_port, http_get, http_json


def new_ec_node(url, rack, free=100, shards=None):
    n = ec_common.EcNode(url=url, dc="dc1", rack=rack, free_ec_slot=free)
    for vid, sids in (shards or {}).items():
        n.ec_shards[vid] = ("", ec_common.ids_to_shard_bits(sids))
        n.free_ec_slot -= len(sids)
    return n


ENV = CommandEnv(["127.0.0.1:0"])  # planners with apply=False never dial


class TestEcPlanners:
    def test_shard_bits_roundtrip(self):
        ids = [0, 3, 13]
        assert ec_common.shard_bits_to_ids(ec_common.ids_to_shard_bits(ids)) == ids

    def test_balanced_distribution_prefers_free(self):
        nodes = [
            new_ec_node("a:1", "r1", free=100),
            new_ec_node("b:1", "r1", free=10),
            new_ec_node("c:1", "r2", free=1),
        ]
        picked = ec_common.balanced_ec_distribution(nodes)
        assert len(picked) == 14
        counts = {u: sum(1 for p in picked if p.url == u) for u in ("a:1", "b:1", "c:1")}
        # the freest node takes the most shards; every node's allocation
        # reflects its capacity ordering
        assert counts["a:1"] >= counts["b:1"] >= counts["c:1"]

    def test_balanced_distribution_insufficient_slots(self):
        # fewer than 14 free slots in the whole cluster → [] (no hang)
        nodes = [new_ec_node("a:1", "r1", free=5)]
        assert ec_common.balanced_ec_distribution(nodes) == []

    def test_dedup_removes_extra_copies(self):
        nodes = [
            new_ec_node("a:1", "r1", shards={5: [0, 1]}),
            new_ec_node("b:1", "r1", shards={5: [1, 2]}),
        ]
        removed = ec_common.dedup_ec_shards(ENV, nodes, 5, apply=False)
        assert removed == 1
        holders = [n for n in nodes if 1 in n.local_shard_ids(5)]
        assert len(holders) == 1

    def test_balance_across_racks(self):
        # all 14 shards in one rack, 2 racks exist → half must move
        nodes = [
            new_ec_node("a:1", "r1", shards={7: list(range(14))}),
            new_ec_node("b:1", "r2", free=100),
        ]
        moves = ec_common.balance_across_racks(ENV, nodes, 7, apply=False)
        assert moves == 7
        assert len(nodes[1].local_shard_ids(7)) == 7

    def test_balance_within_racks(self):
        nodes = [
            new_ec_node("a:1", "r1", shards={9: list(range(10))}),
            new_ec_node("b:1", "r1", free=100),
        ]
        moves = ec_common.balance_within_racks(ENV, nodes, 9, apply=False)
        assert moves > 0
        assert len(nodes[0].local_shard_ids(9)) == 5
        assert len(nodes[1].local_shard_ids(9)) == 5

    def test_balance_ec_rack_totals(self):
        nodes = [
            new_ec_node("a:1", "r1", shards={1: list(range(8)), 2: list(range(6))}),
            new_ec_node("b:1", "r1", free=100),
        ]
        moves = ec_common.balance_ec_rack(ENV, nodes, apply=False)
        # reference semantics: only move a volume the receiver does not
        # already hold, so one shard of each volume migrates (2 moves)
        assert moves == 2
        assert sorted(nodes[1].ec_shards) == [1, 2]

    def test_full_balance_pass(self):
        nodes = [
            new_ec_node("a:1", "r1", shards={3: list(range(14))}),
            new_ec_node("b:1", "r1", free=100),
            new_ec_node("c:1", "r2", free=100),
            new_ec_node("d:1", "r2", free=100),
        ]
        stats = ec_common.balance_ec_volumes(ENV, nodes, apply=False)
        assert stats["across_racks"] > 0
        # shard sets stay complete
        total = sum(len(n.local_shard_ids(3)) for n in nodes)
        assert total == 14
        per_rack = {}
        for n in nodes:
            per_rack[n.rack] = per_rack.get(n.rack, 0) + len(n.local_shard_ids(3))
        assert per_rack["r1"] == 7 and per_rack["r2"] == 7

    def test_find_missing_shards(self):
        nodes = [
            new_ec_node("a:1", "r1", shards={4: [0, 1, 2]}),
            new_ec_node("b:1", "r1", shards={4: [3, 4, 5, 6, 7, 8, 9, 10, 11, 12]}),
        ]
        from seaweedfs_tpu.shell.commands import find_missing_shards

        assert find_missing_shards(nodes, 4) == [13]


class TestVolumePlanners:
    def _dump(self, spec):
        """spec: {url: (rack, max, [vid...])}"""
        nodes = []
        for url, (rack, mx, vids) in spec.items():
            nodes.append(
                TopologyNodeInfo(
                    url=url,
                    public_url=url,
                    dc="dc1",
                    rack=rack,
                    max_volumes=mx,
                    volumes=[
                        {
                            "Id": vid,
                            "Collection": "",
                            "Size": 100,
                            "FileCount": 1,
                            "DeleteCount": 0,
                            "DeletedByteCount": 0,
                            "ReadOnly": False,
                            "ReplicaPlacement": 0,
                            "Ttl": 0,
                        }
                        for vid in vids
                    ],
                )
            )
        return TopologyDump(volume_size_limit_mb=30 * 1024, nodes=nodes)

    def test_balance_moves_from_loaded_to_empty(self):
        dump = self._dump({"a:1": ("r1", 10, [1, 2, 3, 4]), "b:1": ("r1", 10, [])})
        moves = plan_volume_balance(dump)
        assert moves
        assert all(m["from"] == "a:1" and m["to"] == "b:1" for m in moves)
        # ends balanced within 1
        a = 4 - len(moves)
        assert abs(a - len(moves)) <= 1

    def test_balance_noop_when_even(self):
        dump = self._dump({"a:1": ("r1", 10, [1, 2]), "b:1": ("r1", 10, [3, 4])})
        assert plan_volume_balance(dump) == []

    def test_fix_replication_prefers_other_rack(self):
        dump = self._dump(
            {
                "a:1": ("r1", 10, [1]),
                "b:1": ("r1", 10, []),
                "c:1": ("r2", 10, []),
            }
        )
        # volume 1 wants replication 010 (one replica on another rack)
        dump.nodes[0].volumes[0]["ReplicaPlacement"] = 10  # "010": one replica on another rack
        plans = plan_fix_replication(dump)
        assert plans == [
            {"vid": 1, "collection": "", "from": "a:1", "to": "c:1"}
        ]

    def test_fix_replication_noop_when_satisfied(self):
        dump = self._dump({"a:1": ("r1", 10, [1]), "b:1": ("r1", 10, [1])})
        dump.nodes[0].volumes[0]["ReplicaPlacement"] = 1  # "001"
        dump.nodes[1].volumes[0]["ReplicaPlacement"] = 1  # "001"
        assert plan_fix_replication(dump) == []

    def test_collect_volume_ids_for_ec_encode(self):
        dump = self._dump({"a:1": ("r1", 10, [1, 2])})
        dump.volume_size_limit_mb = 1  # 1 MiB limit
        dump.nodes[0].volumes[0]["Size"] = 2 * 1024 * 1024  # full
        dump.nodes[0].volumes[0]["Collection"] = "x"
        dump.nodes[0].volumes[1]["Collection"] = "x"
        vids = collect_volume_ids_for_ec_encode(dump, "x", 60, 95)
        assert vids == [1]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    master_port = free_port()
    master = MasterServer(port=master_port, volume_size_limit_mb=64)
    master.start()
    volume_servers = []
    for i in range(3):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"svs{i}"))],
            port=free_port(),
            master=f"127.0.0.1:{master_port}",
            rack=f"rack{i % 2}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        vs.start()
        volume_servers.append(vs)
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topology.data_nodes()) < 3:
        time.sleep(0.05)
    yield master, volume_servers
    for vs in volume_servers:
        vs.stop()
    master.stop()


def wait_for(predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.1)
    return False


class TestShellPipeline:
    def test_ec_encode_rebuild_balance_end_to_end(self, cluster):
        master, volume_servers = cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])

        # write a blob into a fresh collection
        _, assign = http_json(
            f"http://127.0.0.1:{master.port}/dir/assign?collection=shellec"
        )
        payload = b"shell pipeline payload " * 1000
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}", data=payload, method="POST"
            ),
            timeout=10,
        ).close()
        vid = int(assign["fid"].split(",")[0])

        # ec.encode via the shell command
        out = io.StringIO()
        run_command(env, f"ec.encode -collection shellec -volumeId {vid}", out)
        assert f"ec encoded volume {vid}" in out.getvalue()

        # master learns the shard map via heartbeats
        assert wait_for(
            lambda: (locs := master.topology.lookup_ec_shards(vid)) is not None
            and sum(1 for l in locs.locations if l) == 14
        )

        # degraded read through any server
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200 and body == payload

        # drop one shard somewhere, then ec.rebuild restores it
        victim = None
        for vs in volume_servers:
            ev = vs.store.find_ec_volume(vid)
            if ev is not None and ev.shard_ids():
                victim = vs
                break
        assert victim is not None
        lost = victim.store.find_ec_volume(vid).shard_ids()[0]
        victim.store.unmount_ec_shards(vid, [lost])
        import os

        base = victim.store.find_ec_volume(vid) or None
        # remove the shard file so rebuild has real work
        for loc in victim.store.locations:
            p = os.path.join(loc.directory, f"shellec_{vid}.ec{lost:02d}")
            if os.path.exists(p):
                os.remove(p)
        assert wait_for(
            lambda: (locs := master.topology.lookup_ec_shards(vid)) is not None
            and not locs.locations[lost]
        )

        out = io.StringIO()
        run_command(env, f"ec.rebuild -volumeId {vid} -force", out)
        assert "rebuilt shards" in out.getvalue()
        assert wait_for(
            lambda: (locs := master.topology.lookup_ec_shards(vid)) is not None
            and sum(1 for l in locs.locations if l) == 14
        )

        # ec.balance runs clean over the live topology
        out = io.StringIO()
        run_command(env, "ec.balance -force", out)
        assert "applied=True" in out.getvalue()

        # data still readable after rebuild + balance
        status, body = http_get(f"http://{assign['url']}/{assign['fid']}")
        assert status == 200 and body == payload

    def test_volume_list_and_collection_list(self, cluster):
        master, _ = cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        out = io.StringIO()
        run_command(env, "volume.list", out)
        assert "node 127.0.0.1:" in out.getvalue()
        out = io.StringIO()
        run_command(env, "collection.list", out)
        # the shellec collection became EC volumes; collection listing
        # includes ec collections
        assert "collection:" in out.getvalue() or out.getvalue() == ""

    def test_volume_vacuum_command(self, cluster):
        master, _ = cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        # create garbage: write then delete
        _, assign = http_json(
            f"http://127.0.0.1:{master.port}/dir/assign?collection=vac"
        )
        url = f"http://{assign['url']}/{assign['fid']}"
        urllib.request.urlopen(
            urllib.request.Request(url, data=b"garbage" * 1000, method="POST"),
            timeout=10,
        ).close()
        urllib.request.urlopen(
            urllib.request.Request(url, method="DELETE"), timeout=10
        ).close()
        out = io.StringIO()
        run_command(env, "volume.vacuum -garbageThreshold 0.0001", out)
        assert "vacuumed" in out.getvalue()

    def test_maintenance_runner_once(self, cluster):
        master, _ = cluster
        from seaweedfs_tpu.shell.shell_runner import MaintenanceRunner

        runner = MaintenanceRunner(
            [f"127.0.0.1:{master.port}"],
            scripts=["volume.fix.replication -n", "ec.balance"],
            period_s=3600,
        )
        outputs = runner.run_once()
        assert len(outputs) == 2
        assert all("unknown command" not in o for o in outputs)


class TestFsCommands:
    """fs.* against a live filer (command_fs_*.go role)."""

    @pytest.fixture(scope="class")
    def fs_env(self, tmp_path_factory):
        import socket
        import time as _time

        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.shell import CommandEnv

        from seaweedfs_tpu.util.availability import free_port

        master = MasterServer(port=free_port(), volume_size_limit_mb=64)
        master.start()
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp("fsvs"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
        )
        vs.start()
        deadline = _time.time() + 10
        while _time.time() < deadline and len(master.topology.data_nodes()) < 1:
            _time.sleep(0.05)
        filer = FilerServer(
            [f"127.0.0.1:{master.port}"], port=free_port(), store="memory"
        )
        filer.start()

        # seed a small namespace through the filer HTTP API
        import urllib.request

        for path, data in [
            ("/docs/a.txt", b"alpha"),
            ("/docs/b.txt", b"beta beta"),
            ("/docs/sub/c.txt", b"gamma!"),
            ("/top.txt", b"root file"),
        ]:
            req = urllib.request.Request(
                f"http://127.0.0.1:{filer.port}{path}", data=data, method="POST"
            )
            urllib.request.urlopen(req, timeout=10).close()

        env = CommandEnv([f"127.0.0.1:{master.port}"])
        yield env, filer
        filer.stop()
        vs.stop()
        master.stop()

    def _run(self, env, line):
        from seaweedfs_tpu.shell import run_command

        return run_command(env, line)

    def test_cd_pwd_ls(self, fs_env):
        env, filer = fs_env
        self._run(env, f"fs.cd http://127.0.0.1:{filer.port}/docs")
        assert env.filer == f"127.0.0.1:{filer.port}"
        assert env.cwd == "/docs"
        assert f"/docs" in self._run(env, "fs.pwd")
        listing = self._run(env, "fs.ls")
        assert "a.txt" in listing and "sub/" in listing
        long_listing = self._run(env, "fs.ls -l")
        assert "total" in long_listing

    def test_du_and_tree(self, fs_env):
        env, filer = fs_env
        self._run(env, f"fs.cd http://127.0.0.1:{filer.port}/")
        du = self._run(env, "fs.du /docs")
        assert "3 files" in du
        tree = self._run(env, "fs.tree /docs")
        assert "└──" in tree or "├──" in tree
        assert "c.txt" in tree

    def test_cat(self, fs_env):
        env, filer = fs_env
        self._run(env, f"fs.cd http://127.0.0.1:{filer.port}/")
        assert self._run(env, "fs.cat /docs/a.txt") == "alpha"

    def test_mv(self, fs_env):
        # own subtree: /docs must stay untouched for the other tests
        import urllib.request

        env, filer = fs_env
        req = urllib.request.Request(
            f"http://127.0.0.1:{filer.port}/mvsrc/top.txt",
            data=b"root file",
            method="POST",
        )
        urllib.request.urlopen(req, timeout=10).close()
        self._run(env, f"fs.cd http://127.0.0.1:{filer.port}/")
        self._run(env, "fs.mv /mvsrc/top.txt /mvsrc/renamed.txt")
        assert "renamed.txt" in self._run(env, "fs.ls /mvsrc")
        assert self._run(env, "fs.cat /mvsrc/renamed.txt") == "root file"

    def test_meta_cat_save_load(self, fs_env, tmp_path):
        import grpc

        from seaweedfs_tpu.pb import filer_pb2 as fpb
        from seaweedfs_tpu.pb import rpc as _rpc

        env, filer = fs_env
        self._run(env, f"fs.cd http://127.0.0.1:{filer.port}/")
        meta = self._run(env, "fs.meta.cat /docs/a.txt")
        assert "a.txt" in meta
        out_file = str(tmp_path / "docs.meta")
        saved = self._run(env, f"fs.meta.save -o {out_file} /docs")
        assert "saved" in saved

        # delete an entry's metadata, then load restores it
        with grpc.insecure_channel(
            f"127.0.0.1:{filer.port + 10000}"
        ) as ch:
            _rpc.filer_stub(ch).DeleteEntry(
                fpb.DeleteEntryRequest(
                    directory="/docs", name="a.txt", is_delete_data=False
                )
            )
        assert "a.txt" not in self._run(env, "fs.ls /docs")
        loaded = self._run(env, f"fs.meta.load {out_file}")
        assert "loaded" in loaded
        assert "a.txt" in self._run(env, "fs.ls /docs")
        assert self._run(env, "fs.cat /docs/a.txt") == "alpha"


class TestEcBatchVerb:
    def test_batch_encode_four_volumes_one_program(self, cluster):
        """ec.batch: 4 sealed volumes encoded through ONE MeshCodec
        program per tile round on the 8-device CPU mesh, then serving
        reads from their EC shards — and the shard bytes are identical
        to the per-volume classic encoder's (§2.6.2 volume parallelism
        end-to-end, VERDICT r2 item 10)."""
        import os

        import numpy as np

        from seaweedfs_tpu.ec import ec_files
        from seaweedfs_tpu.ec.codec import new_encoder
        from seaweedfs_tpu.shell.commands import run_command

        master, volume_servers = cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])

        rng = np.random.default_rng(9)
        writes = {}  # vid -> (url, fid, payload)
        # distinct collections => distinct volumes (growth per collection)
        for i in range(4):
            _, assign = http_json(
                f"http://127.0.0.1:{master.port}/dir/assign?collection=ecb{i}"
            )
            payload = rng.integers(
                0, 256, int(rng.integers(20_000, 90_000)), dtype=np.uint8
            ).tobytes()
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{assign['url']}/{assign['fid']}",
                    data=payload,
                    method="POST",
                ),
                timeout=10,
            ).close()
            vid = int(assign["fid"].split(",")[0])
            writes[vid] = (assign["url"], assign["fid"], payload)

        # snapshot each volume's .dat BEFORE the verb (ec.batch deletes
        # the original volume once its EC set is mounted)
        import shutil
        import tempfile

        snap = tempfile.mkdtemp()
        refs = {}
        for server in volume_servers:
            for loc in server.store.locations:
                for vid, vol in loc.volumes.items():
                    if vid in writes:
                        ref_base = os.path.join(snap, f"ref{vid}")
                        shutil.copyfile(
                            vol.base_name + ".dat", ref_base + ".dat"
                        )
                        refs[vid] = (server, vol.base_name, ref_base)
        assert set(refs) == set(writes)

        vids = ",".join(str(v) for v in sorted(writes))
        out = io.StringIO()
        run_command(env, f"ec.batch -volumeIds {vids}", out)
        assert "one mesh program" in out.getvalue()

        # shard bytes == classic per-volume encoder's on the snapshot
        for vid, (server, base, ref_base) in refs.items():
            ec_files.write_ec_files(ref_base, rs=new_encoder(backend="cpu"))
            for i in range(14):
                got = open(base + ec_files.to_ext(i), "rb").read()
                want = open(ref_base + ec_files.to_ext(i), "rb").read()
                assert got == want, (vid, i)
        shutil.rmtree(snap)

        # every payload still readable — served from the EC shards now
        for vid, (url, fid, payload) in writes.items():
            status, body = http_get(f"http://{url}/{fid}")
            assert status == 200 and body == payload, vid
            # the original volume is gone; the ec volume serves
            srv, _, _ = refs[vid]
            assert srv.store.find_volume(vid) is None
            assert srv.store.find_ec_volume(vid) is not None


class TestEcVerify:
    """`ec.verify` scrub (beyond-reference surface: the reference has
    no EC integrity command): clean volumes verify 0 mismatches; a
    flipped byte in a PARITY shard shows only in its own row, a flipped
    byte in a DATA shard disagrees with every parity row."""

    def test_verify_clean_then_corrupt(self, cluster):
        import os
        import re

        from seaweedfs_tpu.shell.commands import do_ec_verify

        master, volume_servers = cluster
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        _, assign = http_json(
            f"http://127.0.0.1:{master.port}/dir/assign?collection=scrub"
        )
        payload = b"scrub me " * 4096
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}",
                data=payload,
                method="POST",
            ),
            timeout=10,
        ).close()
        vid = int(assign["fid"].split(",")[0])
        out = io.StringIO()
        run_command(env, f"ec.encode -collection scrub -volumeId {vid}", out)
        assert wait_for(
            lambda: (locs := master.topology.lookup_ec_shards(vid)) is not None
            and sum(1 for l in locs.locations if l) == 14
        )

        out = io.StringIO()
        mism = do_ec_verify(env, vid, out)
        assert mism == [0, 0, 0, 0], out.getvalue()
        assert "verified clean" in out.getvalue()

        def shard_path(sid):
            for vs in volume_servers:
                for loc in vs.store.locations:
                    p = os.path.join(loc.directory, f"scrub_{vid}.ec{sid:02d}")
                    if os.path.exists(p):
                        return p
            return None

        # flip one byte in PARITY shard 12 (row index 2)
        p12 = shard_path(12)
        assert p12
        with open(p12, "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0x5A]))
        out = io.StringIO()
        mism = do_ec_verify(env, vid, out)
        assert mism[2] > 0 and mism[0] == mism[1] == mism[3] == 0
        assert "parity shard(s) corrupt" in out.getvalue()
        # restore
        with open(p12, "r+b") as f:
            f.seek(100)
            f.write(b)

        # flip one byte in DATA shard 3: every parity row disagrees
        p3 = shard_path(3)
        assert p3
        with open(p3, "r+b") as f:
            f.seek(200)
            b = f.read(1)
            f.seek(200)
            f.write(bytes([b[0] ^ 0x77]))
        out = io.StringIO()
        mism = do_ec_verify(env, vid, out)
        assert all(m > 0 for m in mism), (mism, out.getvalue())
        assert "data shard corruption" in out.getvalue()
        with open(p3, "r+b") as f:
            f.seek(200)
            f.write(b)
        out = io.StringIO()
        assert do_ec_verify(env, vid, out) == [0, 0, 0, 0]
