"""Degraded-read fast path + repair-bandwidth-frugal rebuild
(docs/SCRUB.md degraded section): the reconstructed-tile cache, the
first-k-wins parallel shard gather through the shared qos.hedge attempt
pool, the rebuild piggyback session, and the fast-path load-tracker
wiring.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.ec import ec_files, repair_session
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.ec.ec_volume import NotEnoughShards
from seaweedfs_tpu.ec.tile_cache import TileCache
from seaweedfs_tpu.qos import hedge
from seaweedfs_tpu.stats.metrics import (
    EC_DEGRADED_READS,
    EC_REPAIR_BYTES_READ,
    EC_REPAIR_BYTES_WRITTEN,
    EC_REPAIR_DONATED_BYTES,
    EC_TILE_CACHE,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume

from tests.faults import DeadShard


def make_needle(nid, data, cookie=0x12345678):
    return Needle(cookie=cookie, id=nid, data=data)


def _local_ec_store(tmp_path, n_needles=40, vid=9, seed=5):
    d = str(tmp_path)
    v = Volume(d, vid)
    rng = random.Random(seed)
    payload = {}
    for k in range(1, n_needles + 1):
        data = bytes(rng.randbytes(rng.randint(500, 4000)))
        payload[k] = data
        v.write_needle(make_needle(k, data))
    v.close()
    base = os.path.join(d, str(vid))
    ec_files.write_ec_files(base, rs=new_encoder(backend="cpu"))
    ec_files.write_sorted_file_from_idx(base)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    store = Store([d], ec_backend="cpu")
    assert store.find_ec_volume(vid) is not None
    return store, payload


def _tile_counts():
    return EC_TILE_CACHE.value("hit"), EC_TILE_CACHE.value("miss")


# ---------------------------------------------------------------------------
class TestTileCache:
    def test_lru_eviction_bounds_bytes(self):
        c = TileCache(capacity_bytes=3 * 100, tile_bytes=4096)
        for i in range(10):
            c.put(0, i * 4096, bytes([i]) * 100)
            assert c.total_bytes <= 300
        # the oldest tiles were evicted, the newest survive
        assert c.get(0, 9 * 4096) is not None
        assert c.get(0, 0) is None

    def test_get_touches_lru_order(self):
        c = TileCache(capacity_bytes=2 * 100, tile_bytes=4096)
        c.put(0, 0, b"a" * 100)
        c.put(0, 4096, b"b" * 100)
        assert c.get(0, 0) is not None  # touch: 0 is now most-recent
        c.put(0, 8192, b"c" * 100)  # evicts 4096, not 0
        assert c.get(0, 0) is not None
        assert c.get(0, 4096) is None

    def test_covers_spans_and_partial_tail(self):
        c = TileCache(capacity_bytes=1 << 20, tile_bytes=4096)
        c.put(3, 0, b"x" * 4096)
        c.put(3, 4096, b"y" * 1000)  # short tail tile
        assert c.covers(3, 100, 200)
        assert c.covers(3, 4000, 200)  # crosses into the tail tile
        assert c.covers(3, 4096, 1000)
        assert not c.covers(3, 4096, 2000)  # beyond the cached tail
        assert not c.covers(3, 8192, 1)
        assert not c.covers(4, 0, 1)  # other shard

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("WEED_EC_TILE_CACHE", "0")
        c = TileCache()
        assert not c.enabled
        c.put(0, 0, b"z" * 10)
        assert c.get(0, 0) is None

    def test_invalidate_drops_everything(self):
        c = TileCache(capacity_bytes=1 << 20, tile_bytes=4096)
        c.put(0, 0, b"x" * 50)
        c.invalidate()
        assert c.total_bytes == 0
        assert c.get(0, 0) is None
        assert c.invalidations == 1


# ---------------------------------------------------------------------------
class TestFirstKGather:
    def test_first_k_wins_does_not_wait_for_stragglers(self):
        def fast(tag):
            return lambda done: tag

        def slow(done):
            time.sleep(3.0)
            return "slow"

        t0 = time.perf_counter()
        got = hedge.gather_first_k(
            {"a": fast("a"), "b": fast("b"), "z": slow}, 2, timeout=10.0
        )
        elapsed = time.perf_counter() - t0
        assert set(got) == {"a", "b"}
        assert elapsed < 2.0, "gather blocked on the straggler"

    def test_failures_and_nones_are_misses(self):
        def boom(done):
            raise OSError("down")

        got = hedge.gather_first_k(
            {"x": boom, "y": lambda done: None, "z": lambda done: 7},
            2,
            timeout=5.0,
        )
        assert got == {"z": 7}

    def test_done_event_set_after_k(self):
        saw = {}

        def task(tag):
            def run(done):
                saw[tag] = done
                return tag

            return run

        got = hedge.gather_first_k({1: task(1), 2: task(2)}, 1, timeout=5.0)
        assert len(got) == 1
        deadline = time.time() + 2.0
        while time.time() < deadline and not all(
            d.is_set() for d in saw.values()
        ):
            time.sleep(0.01)
        assert all(d.is_set() for d in saw.values())


# ---------------------------------------------------------------------------
class TestDegradedRead:
    def test_cached_vs_fresh_byte_identity(self, tmp_path):
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        assert ev.quarantine_shard(0, "test")
        h0, m0 = _tile_counts()
        d0 = EC_DEGRADED_READS.value()
        fresh = {k: bytes(ev.read_needle(k).data) for k in payload}
        h1, m1 = _tile_counts()
        assert m1 > m0, "first pass must decode at least one tile"
        cached = {k: bytes(ev.read_needle(k).data) for k in payload}
        h2, m2 = _tile_counts()
        assert m2 == m1, "second pass must be all cache hits"
        assert h2 > h1
        assert EC_DEGRADED_READS.value() > d0
        for k in payload:
            assert fresh[k] == payload[k] == cached[k]
        store.close()

    def test_remount_invalidates_cache(self, tmp_path):
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        ev.quarantine_shard(0, "test")
        for k in list(payload)[:5]:
            ev.read_needle(k)
        assert ev.tile_cache.total_bytes > 0
        inv0 = ev.tile_cache.invalidations
        # rebuild regenerates the .bad-renamed shard; remount must drop
        # every cached tile (they were decoded against the old state)
        rebuilt = ec_files.rebuild_ec_files(
            os.path.join(str(tmp_path), "9"), rs=new_encoder(backend="cpu")
        )
        assert rebuilt == [0]
        store.mount_ec_shards(9, "", [0])
        assert ev.tile_cache.total_bytes == 0
        assert ev.tile_cache.invalidations > inv0
        for k, data in payload.items():
            assert bytes(ev.read_needle(k).data) == data
        store.close()

    def test_bounded_memory_under_concurrent_readers(self, tmp_path):
        store, payload = _local_ec_store(tmp_path, n_needles=60)
        ev = store.find_ec_volume(9)
        ev.quarantine_shard(0, "test")
        # tiny tiles + a 3-tile budget: concurrent misses must never
        # blow past the cap even while every thread is inserting
        ev.tile_cache = TileCache(capacity_bytes=3 * 8192, tile_bytes=8192)
        errors: list = []
        peak = [0]

        def reader(seed):
            rng = random.Random(seed)
            try:
                for _ in range(30):
                    k = rng.choice(list(payload))
                    got = bytes(ev.read_needle(k).data)
                    if got != payload[k]:
                        raise AssertionError(f"needle {k} corrupt")
                    peak[0] = max(peak[0], ev.tile_cache.total_bytes)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=reader, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors[:2]
        assert peak[0] <= 3 * 8192
        store.close()

    def test_gather_uses_fetch_for_unmounted_survivors(self, tmp_path):
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        ev.quarantine_shard(0, "test")
        # unmount four healthy shards: 9 locals remain, the gather must
        # race the "remote" candidates through the attempt pool
        paths = {sid: ev.shards[sid].path for sid in (1, 2, 3, 4)}
        for sid in paths:
            ev.unmount_shard(sid)
        fetched: list[int] = []

        def fetch(sid, offset, size):
            p = paths.get(sid)
            if p is None:
                return None
            fetched.append(sid)
            with open(p, "rb") as f:
                f.seek(offset)
                return f.read(size)

        for k, data in payload.items():
            assert bytes(ev.read_needle(k, fetch=fetch).data) == data
        assert fetched, "remote fetch never used despite missing locals"
        store.close()

    def test_singleflight_one_decode_per_hot_tile(self, tmp_path):
        """8 concurrent degraded GETs of one cold hot key must collapse
        to (about) one k-shard gather + decode, not fan out 8."""
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        ev.quarantine_shard(0, "test")
        calls: list[int] = []
        orig = ev._reconstruct_range

        def counting(*a, **kw):
            calls.append(1)
            time.sleep(0.05)  # widen the would-be stampede window
            return orig(*a, **kw)

        ev._reconstruct_range = counting
        hot = next(iter(payload))
        errors: list = []

        def read():
            try:
                assert bytes(ev.read_needle(hot).data) == payload[hot]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=read) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors[:2]
        # one leader per tile the needle spans (2 allows a boundary
        # needle); without singleflight this is 8+
        assert len(calls) <= 2, f"{len(calls)} concurrent decodes"
        store.close()

    def test_not_enough_shards_raises(self, tmp_path):
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        for sid in range(5):  # 9 survivors < k=10
            ev.quarantine_shard(sid, "test")
        with pytest.raises(NotEnoughShards):
            ev.read_needle(next(iter(payload)))
        store.close()

    def test_serial_fallback_gone_from_hot_path(self):
        # planted-regression guard (also in bench --check): the old
        # per-call ThreadPoolExecutor gather must never come back
        import inspect

        from seaweedfs_tpu.ec import ec_volume

        src = inspect.getsource(ec_volume)
        assert "ThreadPoolExecutor" not in src
        assert "as_completed" not in src


# ---------------------------------------------------------------------------
class TestRepairSession:
    def test_consume_coverage_and_gaps(self):
        sess = repair_session.RebuildSession(7, (1,))
        assert sess.donate(1, 0, b"a" * 100)
        assert sess.donate(1, 300, b"b" * 100)
        covered, gaps = sess.consume(0, 500)
        assert [(off, per[1]) for off, per in covered] == [
            (0, b"a" * 100),
            (300, b"b" * 100),
        ]
        assert gaps == [(100, 200), (400, 100)]

    def test_donation_clipped_to_tile_keeps_remainder(self):
        sess = repair_session.RebuildSession(7, (1,))
        sess.donate(1, 50, b"x" * 100)  # spans [50, 150)
        covered, gaps = sess.consume(0, 100)
        assert [(off, len(per[1])) for off, per in covered] == [(50, 50)]
        assert gaps == [(0, 50)]
        # the out-of-window tail [100, 150) survives for the next tile —
        # a serve tile larger than the rebuild tile must not lose its
        # remainder to the first claim
        covered2, gaps2 = sess.consume(100, 100)
        assert [(off, per[1]) for off, per in covered2] == [(100, b"x" * 50)]
        assert gaps2 == [(150, 50)]

    def test_donation_overlapping_claim_is_trimmed_not_rejected(self):
        sess = repair_session.RebuildSession(7, (1,))
        sess.consume(0, 100)  # claim [0, 100)
        assert sess.donate(1, 50, b"y" * 100)  # [50,150): head claimed
        covered, gaps = sess.consume(100, 100)
        assert [(off, per[1]) for off, per in covered] == [(100, b"y" * 50)]
        assert gaps == [(150, 50)]

    def test_late_donations_for_claimed_ranges_rejected(self):
        sess = repair_session.RebuildSession(7, (1,))
        sess.consume(0, 1000)
        assert not sess.donate(1, 0, b"x" * 100)
        assert sess.donate(1, 1000, b"y" * 100)

    def test_multi_target_requires_all_targets(self):
        sess = repair_session.RebuildSession(7, (1, 2))
        sess.donate(1, 0, b"a" * 100)  # target 2 missing for [0,100)
        covered, gaps = sess.consume(0, 100)
        assert covered == []
        assert gaps == [(0, 100)]
        sess2 = repair_session.RebuildSession(7, (1, 2))
        sess2.donate(1, 0, b"a" * 100)
        sess2.donate(2, 0, b"b" * 100)
        covered, gaps = sess2.consume(0, 100)
        assert len(covered) == 1 and gaps == []

    def test_non_target_donation_rejected(self):
        sess = repair_session.RebuildSession(7, (1,))
        assert not sess.donate(5, 0, b"x" * 10)

    def test_yield_to_serving_waits_bounded(self):
        sess = repair_session.RebuildSession(7, (1,))
        sess.serving_enter()
        t0 = time.perf_counter()
        sess.yield_to_serving(max_wait_s=0.2)
        waited = time.perf_counter() - t0
        assert 0.15 <= waited < 2.0
        assert sess.yields > 0
        sess.serving_exit()
        t0 = time.perf_counter()
        sess.yield_to_serving(max_wait_s=0.2)
        assert time.perf_counter() - t0 < 0.1, "idle serving must not block"

    def test_registry_open_find_close(self):
        sess = repair_session.open_session(42, (3,))
        assert repair_session.find(42) is sess
        repair_session.close_session(sess)
        assert repair_session.find(42) is None

    def test_stream_rebuild_consumes_donations_byte_identical(self, tmp_path):
        from seaweedfs_tpu.ec import ec_stream

        d = str(tmp_path)
        base = os.path.join(d, "7")
        rng = random.Random(3)
        with open(base + ".dat", "wb") as f:
            f.write(bytes(rng.randbytes(3_000_000)))
        rs = new_encoder(backend="cpu")
        ec_files.write_ec_files(base, rs=rs)
        shard_bytes = {}
        for i in range(14):
            with open(base + ec_files.to_ext(i), "rb") as f:
                shard_bytes[i] = f.read()
        os.remove(base + ec_files.to_ext(1))
        remote = {}
        for i in (10, 11, 12, 13):
            os.remove(base + ec_files.to_ext(i))
            remote[i] = (
                lambda off, size, data=shard_bytes[i]: data[off : off + size]
            )
        rl0 = EC_REPAIR_BYTES_READ.value("local")
        rr0 = EC_REPAIR_BYTES_READ.value("remote")
        w0 = EC_REPAIR_BYTES_WRITTEN.value()
        sess = repair_session.open_session(7, (1,))
        for off in (0, 262144):  # 512 KiB of 1 MiB donated
            sess.donate(1, off, shard_bytes[1][off : off + 262144])
        rfn, ffn = ec_stream.local_rebuild_fns(rs)
        stats: dict = {}
        rebuilt = ec_stream.stream_rebuild_ec_files(
            base,
            rebuild_fn=rfn,
            fetch_fn=ffn,
            remote_readers=remote,
            session=sess,
            durable=True,
            stats=stats,
        )
        repair_session.close_session(sess)
        assert rebuilt == [1]
        with open(base + ec_files.to_ext(1), "rb") as f:
            assert f.read() == shard_bytes[1], "donated rebuild differs"
        shard_len = len(shard_bytes[1])
        read = (
            EC_REPAIR_BYTES_READ.value("local")
            - rl0
            + EC_REPAIR_BYTES_READ.value("remote")
            - rr0
        )
        written = EC_REPAIR_BYTES_WRITTEN.value() - w0
        assert written == shard_len
        # donations halve the gather: 10 survivors x the uncovered half
        assert read == 10 * (shard_len - 524288)
        assert stats["used_donated_bytes"] == 524288

    def test_donate_cached_tiles_seeds_session(self, tmp_path):
        store, payload = _local_ec_store(tmp_path)
        ev = store.find_ec_volume(9)
        ev.quarantine_shard(0, "test")
        for k in payload:
            ev.read_needle(k)  # warms the tile cache
        assert ev.tile_cache.total_bytes > 0
        sess = repair_session.RebuildSession(9, (0,))
        donated = ev.donate_cached_tiles(sess)
        assert donated > 0
        assert sess.donated_bytes == ev.tile_cache.total_bytes
        store.close()


# ---------------------------------------------------------------------------
class TestFastPathLoadSignal:
    def test_resolve_enters_complete_exits(self):
        from seaweedfs_tpu import qos
        from seaweedfs_tpu.util import native_serve

        class Srv:
            RequestHandlerClass = object
            trace_name = "volume"
            trace_node = "t:1"
            load_tracker = qos.LoadTracker()

            def fast_resolver(self, path, rng, head_only):
                if path == "/miss":
                    return None
                return (200, b"HTTP/1.1 200 OK\r\n\r\n", b"hi", -1, 0, 0)

        srv = Srv()
        srv.fast_resolver = srv.fast_resolver.__get__(srv)
        resolve, _handoff, complete = native_serve._callbacks(srv)
        assert srv.load_tracker.inflight() == 0
        plan = resolve("/1,abc", None, False, "", None)
        assert plan is not None
        assert srv.load_tracker.inflight() == 1, (
            "fast-path GET invisible to the heartbeat load signal"
        )
        ctx = plan[7]
        complete(ctx, 200, 2, 0.0, 0.0, 0.0, 1)
        assert srv.load_tracker.inflight() == 0
        # a declined resolve must not touch the counter
        assert resolve("/miss", None, False, "", None) is None
        assert srv.load_tracker.inflight() == 0
        # a legacy 6-tuple plan cannot validate If-None-Match: a
        # conditional GET must decline to the threaded arm
        assert resolve("/1,abc", None, False, "", '"x"') is None
        assert srv.load_tracker.inflight() == 0


# ---------------------------------------------------------------------------
# live mini-cluster: degraded serving + piggybacked rebuild end to end
@pytest.fixture(scope="module")
def degraded_cluster(tmp_path_factory):
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.util.availability import free_port

    master = MasterServer(
        port=free_port(),
        volume_size_limit_mb=64,
        vacuum_interval=0,
        repair_interval=0,
    )
    master.start()
    servers = []
    for i in range(3):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"deg{i}"))],
            port=free_port(),
            master=f"127.0.0.1:{master.port}",
            rack=f"rack{i}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            ec_codec="cpu",
            scrub_interval=0,
        )
        vs.start()
        servers.append(vs)
    deadline = time.time() + 45
    while time.time() < deadline:
        if len(master.topology.data_nodes()) == 3:
            break
        time.sleep(0.1)
    assert len(master.topology.data_nodes()) == 3
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


class TestDegradedServingEndToEnd:
    def _seed_and_encode(self, master, n=24):
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.commands import do_ec_encode
        import io

        rng = random.Random(11)
        keys = {}
        vid = None
        for i in range(n):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.port}/dir/assign", timeout=10
            ) as r:
                a = json.loads(r.read())
            data = bytes(rng.randbytes(1800 + i))
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}",
                    data=data,
                    method="POST",
                    headers={"Content-Type": "application/octet-stream"},
                ),
                timeout=10,
            ).close()
            keys[a["fid"]] = data
            vid = int(a["fid"].partition(",")[0])
        env = CommandEnv([f"127.0.0.1:{master.port}"])
        do_ec_encode(env, vid, "", io.StringIO())
        return vid, keys

    def test_degraded_get_tile_cache_and_piggybacked_rebuild(
        self, degraded_cluster
    ):
        master, servers = degraded_cluster
        vid, keys = self._seed_and_encode(master)
        # all data lives in shard 0 (dat < 1MB => striping block 0);
        # kill it over the operator route on whichever node mounts it
        holder = next(
            vs
            for vs in servers
            if (ev := vs.store.find_ec_volume(vid)) is not None
            and 0 in ev.shards
        )
        fault = DeadShard(vid, sid=0, addr=f"127.0.0.1:{holder.port}")
        assert fault.kill() == 0
        # serve degraded GETs from a surviving holder: byte-identical,
        # second pass all tile-cache hits
        server = next(
            vs
            for vs in servers
            if vs.store.find_ec_volume(vid) is not None
            and vs.store.find_ec_volume(vid).shard_ids()
        )
        d0 = EC_DEGRADED_READS.value()

        def get_all():
            for fid, data in keys.items():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/{fid}", timeout=30
                ) as r:
                    assert r.read() == data, f"degraded GET {fid} corrupt"

        get_all()
        assert EC_DEGRADED_READS.value() > d0
        h1, m1 = _tile_counts()
        get_all()
        h2, m2 = _tile_counts()
        assert m2 == m1 and h2 > h1, "warm pass must be all cache hits"
        # rebuild ON the warm node: its cached tiles seed the session,
        # so the gather skips the donated ranges entirely
        don0 = EC_REPAIR_DONATED_BYTES.value()
        from seaweedfs_tpu.pb import rpc, volume_pb2

        with rpc.dial(f"127.0.0.1:{server.port + 10000}") as ch:
            resp = rpc.volume_stub(ch).VolumeEcShardsRebuild(
                volume_pb2.VolumeEcShardsRebuildRequest(volume_id=vid),
                timeout=120,
            )
        assert list(resp.rebuilt_shard_ids) == [0]
        assert EC_REPAIR_DONATED_BYTES.value() > don0, (
            "piggyback: cached degraded tiles never reached the rebuild"
        )
        server.store.mount_ec_shards(vid, "", [0])
        # healthy again: reads still byte-identical
        for fid, data in list(keys.items())[:5]:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/{fid}", timeout=30
            ) as r:
                assert r.read() == data
