"""Filer tests.

Chunk-algebra table tests are ported verbatim from the reference's
filer2/filechunks_test.go (TestIntervalMerging / TestChunksReading /
TestCompactFileChunks) — SURVEY §5 calls for porting them unchanged.
Store tests mirror filer2/leveldb/leveldb_store_test.go CRUD. Server
tests drive the live HTTP+gRPC surface against an in-process cluster.
"""

import json
import time
import urllib.request

import pytest

from seaweedfs_tpu.filer import filechunks as fc
from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry, split_path
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import (
    EntryNotFound,
    MemoryStore,
    SortedLogStore,
    SqliteStore,
)


def C(offset, size, fid, mtime):
    return fc.make_chunk(fid, offset, size, mtime)


class TestIntervalMerging:
    # (chunks, expected [(start, stop, fid)]) — filechunks_test.go cases 0-8
    CASES = [
        (
            [C(0, 100, "abc", 123), C(100, 100, "asdf", 134), C(200, 100, "fsad", 353)],
            [(0, 100, "abc"), (100, 200, "asdf"), (200, 300, "fsad")],
        ),
        ([C(0, 100, "abc", 123), C(0, 200, "asdf", 134)], [(0, 200, "asdf")]),
        (
            [C(0, 100, "abc", 123), C(0, 50, "asdf", 134)],
            [(0, 50, "asdf"), (50, 100, "abc")],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 200, "asdf", 134), C(50, 250, "xxxx", 154)],
            [(0, 50, "asdf"), (50, 300, "xxxx")],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 200, "asdf", 134), C(250, 250, "xxxx", 154)],
            [(0, 200, "asdf"), (250, 500, "xxxx")],
        ),
        (
            [
                C(0, 100, "abc", 123),
                C(0, 200, "asdf", 184),
                C(70, 150, "abc", 143),
                C(80, 100, "xxxx", 134),
            ],
            [(0, 200, "asdf"), (200, 220, "abc")],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 100, "abc", 123), C(0, 100, "abc", 123)],
            [(0, 100, "abc")],
        ),
        (
            [
                C(0, 2097152, "7,0294cbb9892b", 123),
                C(0, 3145728, "3,029565bf3092", 130),
                C(2097152, 3145728, "6,029632f47ae2", 140),
                C(5242880, 3145728, "2,029734c5aa10", 150),
                C(8388608, 3145728, "5,02982f80de50", 160),
                C(11534336, 2842193, "7,0299ad723803", 170),
            ],
            [
                (0, 2097152, "3,029565bf3092"),
                (2097152, 5242880, "6,029632f47ae2"),
                (5242880, 8388608, "2,029734c5aa10"),
                (8388608, 11534336, "5,02982f80de50"),
                (11534336, 14376529, "7,0299ad723803"),
            ],
        ),
        (
            [
                C(0, 77824, "4,0b3df938e301", 123),
                C(471040, 472225 - 471040, "6,0b3e0650019c", 130),
                C(77824, 208896 - 77824, "4,0b3f0c7202f0", 140),
                C(208896, 339968 - 208896, "2,0b4031a72689", 150),
                C(339968, 471040 - 339968, "3,0b416a557362", 160),
            ],
            [
                (0, 77824, "4,0b3df938e301"),
                (77824, 208896, "4,0b3f0c7202f0"),
                (208896, 339968, "2,0b4031a72689"),
                (339968, 471040, "3,0b416a557362"),
                (471040, 472225, "6,0b3e0650019c"),
            ],
        ),
    ]

    @pytest.mark.parametrize("case_idx", range(len(CASES)))
    def test_case(self, case_idx):
        chunks, expected = self.CASES[case_idx]
        got = [
            (v.start, v.stop, v.fid)
            for v in fc.non_overlapping_visible_intervals(chunks)
        ]
        assert got == expected


class TestChunksReading:
    # (chunks, offset, size, expected [(chunk_offset, size, fid, logic_offset)])
    CASES = [
        (
            [C(0, 100, "abc", 123), C(100, 100, "asdf", 134), C(200, 100, "fsad", 353)],
            0,
            250,
            [(0, 100, "abc", 0), (0, 100, "asdf", 100), (0, 50, "fsad", 200)],
        ),
        ([C(0, 100, "abc", 123), C(0, 200, "asdf", 134)], 50, 100, [(50, 100, "asdf", 50)]),
        (
            [C(0, 100, "abc", 123), C(0, 50, "asdf", 134)],
            25,
            50,
            [(25, 25, "asdf", 25), (0, 25, "abc", 50)],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 200, "asdf", 134), C(50, 250, "xxxx", 154)],
            0,
            200,
            [(0, 50, "asdf", 0), (0, 150, "xxxx", 50)],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 200, "asdf", 134), C(250, 250, "xxxx", 154)],
            0,
            400,
            [(0, 200, "asdf", 0)],
        ),
        (
            [
                C(0, 100, "abc", 123),
                C(0, 200, "asdf", 184),
                C(70, 150, "abc", 143),
                C(80, 100, "xxxx", 134),
            ],
            0,
            220,
            [(0, 200, "asdf", 0), (0, 20, "abc", 200)],
        ),
        (
            [C(0, 100, "abc", 123), C(0, 100, "abc", 123), C(0, 100, "abc", 123)],
            0,
            100,
            [(0, 100, "abc", 0)],
        ),
    ]

    @pytest.mark.parametrize("case_idx", range(len(CASES)))
    def test_case(self, case_idx):
        chunks, offset, size, expected = self.CASES[case_idx]
        got = [
            (v.offset, v.size, v.fid, v.logic_offset)
            for v in fc.view_from_chunks(chunks, offset, size)
        ]
        assert got == expected


class TestCompact:
    def test_compact_file_chunks(self):
        chunks = [
            C(10, 100, "abc", 50),
            C(100, 100, "def", 100),
            C(200, 100, "ghi", 200),
            C(110, 200, "jkl", 300),
        ]
        compacted, garbage = fc.compact_file_chunks(chunks)
        assert len(compacted) == 3
        assert len(garbage) == 1

    def test_compact_file_chunks2(self):
        chunks = [
            C(0, 100, "abc", 50),
            C(100, 100, "def", 100),
            C(200, 100, "ghi", 200),
            C(0, 100, "abcf", 300),
            C(50, 100, "fhfh", 400),
            C(100, 100, "yuyu", 500),
        ]
        k = 3
        for n in range(k):
            chunks.append(C(n * 100, 100, f"fileId{n}", n))
            chunks.append(C(n * 50, 100, f"fileId{n + k}", n + k))
        compacted, garbage = fc.compact_file_chunks(chunks)
        assert len(compacted) == 4
        assert len(garbage) == 8

    def test_minus_chunks(self):
        a = [C(0, 100, "abc", 1), C(100, 100, "def", 2)]
        b = [C(0, 100, "abc", 1)]
        assert [c.fid for c in fc.minus_chunks(a, b)] == ["def"]

    def test_total_size_and_etag(self):
        chunks = [C(0, 100, "a", 1), C(50, 100, "b", 2)]
        assert fc.total_size(chunks) == 150
        only = [fc.make_chunk("x", 0, 10, 1, e_tag="deadbeef")]
        assert fc.etag(only) == "deadbeef"
        assert fc.etag(chunks)  # fnv combined


def _lsm_factory(tmp):
    from seaweedfs_tpu.filer.lsm import LsmStore

    return LsmStore(str(tmp / "lsm"))


def _sql_factory(tmp):
    from seaweedfs_tpu.filer.abstract_sql import new_sqlite_sql_store

    return new_sqlite_sql_store(str(tmp / "filer.sql.db"))


class _FakeBackedFactory:
    """Starts a fresh in-repo protocol fake per store instance and
    stops it when the store closes."""

    def __init__(self, fake_cls, store_builder):
        self._fake_cls = fake_cls
        self._build = store_builder

    def __call__(self, tmp):
        fake = self._fake_cls()
        fake.start()
        store = self._build(fake)
        orig_close = store.close

        def close():
            orig_close()
            fake.stop()

        store.close = close
        return store


def _redis_factory():
    from seaweedfs_tpu.filer.redis_store import RedisStore
    from tests.cloud_fakes import FakeRedis

    return _FakeBackedFactory(FakeRedis, lambda f: RedisStore(f.address))


def _cassandra_factory():
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore
    from tests.cloud_fakes import FakeCassandra

    return _FakeBackedFactory(
        FakeCassandra, lambda f: CassandraStore(f.address)
    )


def _etcd_factory():
    from seaweedfs_tpu.filer.etcd_store import EtcdFilerStore
    from tests.cloud_fakes import FakeEtcd

    return _FakeBackedFactory(FakeEtcd, lambda f: EtcdFilerStore(f.endpoint))


def _tikv_factory():
    from seaweedfs_tpu.filer.tikv_store import TikvStore
    from tests.cloud_fakes import FakeTikv

    return _FakeBackedFactory(FakeTikv, lambda f: TikvStore(f.address))


def _mysql_factory():
    from seaweedfs_tpu.filer.abstract_sql import new_mysql_store
    from tests.cloud_fakes import FakeMysql

    return _FakeBackedFactory(
        lambda: FakeMysql(password="pw"),
        lambda f: new_mysql_store(
            f"{f.address}/seaweedfs?user=seaweedfs&password=pw"
        ),
    )


def _postgres_factory():
    from seaweedfs_tpu.filer.abstract_sql import new_postgres_store
    from tests.cloud_fakes import FakePostgres

    return _FakeBackedFactory(
        lambda: FakePostgres(password="pw"),
        lambda f: new_postgres_store(
            f"{f.address}/seaweedfs?user=seaweedfs&password=pw"
        ),
    )


@pytest.mark.parametrize(
    "store_factory",
    [
        lambda tmp: MemoryStore(),
        lambda tmp: SqliteStore(str(tmp / "filer.db")),
        lambda tmp: SortedLogStore(str(tmp / "filer.log")),
        _lsm_factory,
        _sql_factory,
        _redis_factory(),
        _cassandra_factory(),
        _etcd_factory(),
        _postgres_factory(),
        _mysql_factory(),
        _tikv_factory(),
    ],
    ids=[
        "memory", "sqlite", "sortedlog", "lsm", "sql", "redis",
        "cassandra", "etcd", "postgres", "mysql", "tikv",
    ],
)
class TestFilerStores:
    def test_crud_and_list(self, store_factory, tmp_path):
        store = store_factory(tmp_path)
        e = Entry("/home/user/file.txt", attr=Attr(mtime=5, crtime=5))
        store.insert_entry(e)
        got = store.find_entry("/home/user/file.txt")
        assert got.full_path == "/home/user/file.txt"
        assert got.attr.mtime == 5

        store.insert_entry(Entry("/home/user/b.txt", attr=Attr(mtime=6)))
        store.insert_entry(Entry("/home/user/a.txt", attr=Attr(mtime=7)))
        names = [x.name for x in store.list_directory_entries("/home/user", "", True, 10)]
        assert names == ["a.txt", "b.txt", "file.txt"]

        # pagination (leveldb_store_test.go list semantics)
        names = [x.name for x in store.list_directory_entries("/home/user", "a.txt", False, 10)]
        assert names == ["b.txt", "file.txt"]

        store.delete_entry("/home/user/a.txt")
        with pytest.raises(EntryNotFound):
            store.find_entry("/home/user/a.txt")
        store.close()

    def test_chunks_roundtrip(self, store_factory, tmp_path):
        store = store_factory(tmp_path)
        e = Entry(
            "/data/x.bin",
            attr=Attr(mtime=1, mime="application/x-bin"),
            chunks=[fc.make_chunk("3,01abc", 0, 100, 7, e_tag="t")],
        )
        store.insert_entry(e)
        got = store.find_entry("/data/x.bin")
        assert len(got.chunks) == 1
        assert got.chunks[0].fid == "3,01abc"
        assert got.chunks[0].size == 100
        assert got.attr.mime == "application/x-bin"
        store.close()


class TestAbstractSql:
    """The dialect layer itself (filer2/abstract_sql/): dirhash
    compatibility, dialect SQL parity, gating of driverless kinds."""

    def test_dirhash_matches_reference_fold(self):
        """HashStringToLong (util/bytes.go:53) = first 8 md5 bytes
        folded big-endian into a SIGNED int64. Golden values pinned so
        the schema stays row-compatible with reference deployments."""
        from seaweedfs_tpu.filer.abstract_sql import hash_string_to_long

        assert hash_string_to_long("/home/user") == 1669289113769266586
        assert hash_string_to_long("/") == 7378810950367401542
        assert hash_string_to_long("") == -3162216497309240828  # sign wrap

    def test_mysql_postgres_dialects_mirror_reference_sql(self):
        """Each dialect's statements are the reference's verbatim SQL
        shapes (mysql_store.go:45-52, postgres_store.go:47-54)."""
        from seaweedfs_tpu.filer.abstract_sql import (
            MYSQL_DIALECT,
            POSTGRES_DIALECT,
        )

        assert (
            MYSQL_DIALECT.insert
            == "INSERT INTO filemeta (dirhash,name,directory,meta) VALUES(%s,%s,%s,%s)"
        )
        assert "name>=%s" in MYSQL_DIALECT.list_inclusive
        assert (
            POSTGRES_DIALECT.update
            == "UPDATE filemeta SET meta=$1 WHERE dirhash=$2 AND name=$3 AND directory=$4"
        )
        assert "name>$2" in POSTGRES_DIALECT.list_exclusive

    def test_mysql_packet_framing_splits_at_16mib(self):
        """MySQL frames cap at 0xFFFFFF payload bytes; a max-size frame
        signals continuation and a >=16MiB logical packet must be split
        on send and reassembled on read — a near-16MiB filemeta blob
        must not desync the connection."""
        import io

        from seaweedfs_tpu.filer.mysql_driver import MysqlConnection

        conn = MysqlConnection.__new__(MysqlConnection)
        sent: list[bytes] = []

        class _Sock:
            @staticmethod
            def sendall(b):
                sent.append(bytes(b))

        conn.sock = _Sock()
        for size in (0xFFFFFF - 1, 0xFFFFFF, 0xFFFFFF + 7):
            sent.clear()
            payload = (b"0123456789abcdef" * ((size // 16) + 1))[:size]
            conn._seq = 0
            conn._send_packet(payload)
            wire = b"".join(sent)
            # frame walk: every non-final frame is exactly max-size,
            # sequence ids increment per frame
            off, frames = 0, []
            while off < len(wire):
                ln = int.from_bytes(wire[off : off + 3], "little")
                seq = wire[off + 3]
                frames.append((ln, seq))
                off += 4 + ln
            assert off == len(wire)
            assert [s for _, s in frames] == list(range(len(frames)))
            assert all(ln == 0xFFFFFF for ln, _ in frames[:-1])
            assert frames[-1][0] < 0xFFFFFF  # incl. empty terminator
            # reassembly round-trips
            conn.rfile = io.BytesIO(wire)
            assert conn._read_packet() == payload

    def test_gated_kinds_raise_with_guidance(self):
        from seaweedfs_tpu.filer.filerstore import new_store

        with pytest.raises(RuntimeError, match="cannot reach"):
            new_store("mysql", "127.0.0.1:1")
        # wrong mysql password: reachable, clear auth error
        from tests.cloud_fakes import FakeMysql

        fmy = FakeMysql(password="right")
        fmy.start()
        try:
            with pytest.raises(Exception, match="Access denied"):
                new_store(
                    "mysql",
                    f"{fmy.address}/seaweedfs?user=seaweedfs&password=nope",
                )
        finally:
            fmy.stop()
        with pytest.raises(ValueError, match="embedded kinds"):
            new_store("no-such-store")
        # redis / cassandra gate on connectivity, not a library
        with pytest.raises(RuntimeError, match="cannot reach"):
            new_store("redis", "127.0.0.1:1")
        with pytest.raises(RuntimeError, match="cannot reach"):
            new_store("cassandra", "127.0.0.1:1")
        with pytest.raises(RuntimeError, match="cannot reach"):
            new_store("etcd", "127.0.0.1:1")
        with pytest.raises(RuntimeError, match="cannot reach"):
            new_store("postgres", "127.0.0.1:1")
        # wrong password: reachable, clear auth error (not "cannot reach")
        from tests.cloud_fakes import FakePostgres

        fpg = FakePostgres(password="right")
        fpg.start()
        try:
            with pytest.raises(Exception, match="authentication"):
                new_store(
                    "postgres",
                    f"{fpg.address}/seaweedfs?user=seaweedfs&password=wrong",
                )
        finally:
            fpg.stop()
        # tikv gates on PD connectivity like the others
        with pytest.raises(RuntimeError, match="cannot reach PD"):
            new_store("tikv", "127.0.0.1:1")

    def test_insert_degrades_to_update_on_duplicate(self, tmp_path):
        from seaweedfs_tpu.filer.filerstore import new_store

        s = new_store("sql", str(tmp_path / "d.db"))
        s.insert_entry(Entry("/a/x", attr=Attr(mtime=1)))
        s.insert_entry(Entry("/a/x", attr=Attr(mtime=2)))  # dup key
        assert s.find_entry("/a/x").attr.mtime == 2
        s.close()

    def test_transaction_rollback_undoes_batch(self, tmp_path):
        from seaweedfs_tpu.filer.filerstore import new_store

        s = new_store("sql", str(tmp_path / "t.db"))
        s.insert_entry(Entry("/a/keep", attr=Attr(mtime=1)))
        s.begin_transaction()
        s.insert_entry(Entry("/a/tmp1", attr=Attr(mtime=2)))
        s.delete_entry("/a/keep")
        s.rollback_transaction()
        assert s.find_entry("/a/keep").attr.mtime == 1
        with pytest.raises(EntryNotFound):
            s.find_entry("/a/tmp1")
        s.close()

    def test_pg_transaction_rollback_restores_state(self):
        """The wire driver's begin()/rollback() run real server-side
        transactions, and a failed statement inside one rolls back to
        its savepoint without aborting the transaction (the
        insert-degrades-to-update path must survive)."""
        from seaweedfs_tpu.filer.abstract_sql import new_postgres_store
        from tests.cloud_fakes import FakePostgres

        fake = FakePostgres(password="pw")
        fake.start()
        try:
            s = new_postgres_store(
                f"{fake.address}/seaweedfs?user=seaweedfs&password=pw"
            )
            s.insert_entry(Entry("/t/keep", attr=Attr(mtime=1)))
            s.begin_transaction()
            s.insert_entry(Entry("/t/tmp", attr=Attr(mtime=2)))
            # duplicate insert inside the txn: savepoint recovery, then
            # the degrade-to-update applies
            s.insert_entry(Entry("/t/keep", attr=Attr(mtime=5)))
            s.rollback_transaction()
            assert s.find_entry("/t/keep").attr.mtime == 1  # rolled back
            with pytest.raises(EntryNotFound):
                s.find_entry("/t/tmp")
            s.close()
        finally:
            fake.stop()

    def test_filer_atomic_rename_over_sql_store(self, tmp_path):
        """The Filer's AtomicRenameEntry runs inside the store tx hooks
        — the seam the reference created abstract_sql's BeginTransaction
        for (filer_grpc_server_rename.go)."""
        from seaweedfs_tpu.filer.filer import Filer
        from seaweedfs_tpu.filer.filerstore import new_store

        f = Filer(new_store("sql", str(tmp_path / "r.db")))
        f.create_entry(Entry("/dir/old", attr=Attr(mtime=1)))
        f.atomic_rename("/dir/old", "/dir/new")
        assert f.find_entry("/dir/new").attr.mtime == 1
        with pytest.raises(EntryNotFound):
            f.find_entry("/dir/old")


class TestSortedLogPersistence:
    def test_replay_after_reopen(self, tmp_path):
        path = str(tmp_path / "f.log")
        s = SortedLogStore(path)
        s.insert_entry(Entry("/a/b", attr=Attr(mtime=1)))
        s.insert_entry(Entry("/a/c", attr=Attr(mtime=2)))
        s.delete_entry("/a/b")
        s.close()
        s2 = SortedLogStore(path)
        with pytest.raises(EntryNotFound):
            s2.find_entry("/a/b")
        assert s2.find_entry("/a/c").attr.mtime == 2
        s2.close()


class TestFilerCore:
    def test_create_auto_creates_parents(self):
        f = Filer(MemoryStore())
        f.create_entry(Entry("/a/b/c/file.txt", attr=Attr(mtime=1)))
        assert f.find_entry("/a").is_directory
        assert f.find_entry("/a/b").is_directory
        assert f.find_entry("/a/b/c").is_directory
        assert not f.find_entry("/a/b/c/file.txt").is_directory

    def test_overwrite_queues_old_chunks(self):
        f = Filer(MemoryStore())
        f.create_entry(Entry("/f", chunks=[fc.make_chunk("1,aa", 0, 10, 1)]))
        f.create_entry(Entry("/f", chunks=[fc.make_chunk("1,bb", 0, 10, 2)]))
        assert "1,aa" in f._pending_chunk_deletions

    def test_delete_recursive_collects_chunks(self):
        f = Filer(MemoryStore())
        f.create_entry(Entry("/d/x", chunks=[fc.make_chunk("1,aa", 0, 10, 1)]))
        f.create_entry(Entry("/d/sub/y", chunks=[fc.make_chunk("1,bb", 0, 10, 1)]))
        with pytest.raises(ValueError):
            f.delete_entry("/d", is_recursive=False)
        fids = f.delete_entry("/d", is_recursive=True)
        assert sorted(fids) == ["1,aa", "1,bb"]
        with pytest.raises(EntryNotFound):
            f.find_entry("/d/x")

    def test_atomic_rename_file_and_dir(self):
        f = Filer(MemoryStore())
        f.create_entry(Entry("/olddir/f1", chunks=[fc.make_chunk("1,aa", 0, 10, 1)]))
        f.create_entry(Entry("/olddir/sub/f2", attr=Attr(mtime=3)))
        f.atomic_rename("/olddir", "/newdir")
        assert f.find_entry("/newdir/f1").chunks[0].fid == "1,aa"
        assert f.find_entry("/newdir/sub/f2").attr.mtime == 3
        with pytest.raises(EntryNotFound):
            f.find_entry("/olddir")

    def test_events_fire(self):
        events = []
        f = Filer(MemoryStore(), on_event=lambda o, n, d: events.append((o, n, d)))
        f.create_entry(Entry("/ev/file", attr=Attr(mtime=1)))
        f.delete_entry("/ev/file")
        kinds = [
            ("create" if o is None else "delete" if n is None else "update")
            for o, n, d in events
        ]
        assert "create" in kinds and "delete" in kinds


# ----------------------------------------------------------------------
# live server


@pytest.fixture(scope="module")
def filer_cluster(tmp_path_factory):
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    from tests.test_cluster import free_port

    master_port = free_port()
    master = MasterServer(port=master_port, volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("fvs"))],
        port=free_port(),
        master=f"127.0.0.1:{master_port}",
        heartbeat_interval=0.2,
        max_volume_counts=[100],
    )
    vs.start()
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topology.data_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer(
        [f"127.0.0.1:{master_port}"], port=free_port(), store="memory", max_mb=1
    )
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def filer_url(filer, path):
    return f"http://127.0.0.1:{filer.port}{path}"


class TestFilerServer:
    def test_post_get_delete(self, filer_cluster):
        _, _, filer = filer_cluster
        body = b"filer http roundtrip " * 10
        req = urllib.request.Request(
            filer_url(filer, "/docs/hello.txt"), data=body, method="POST"
        )
        req.add_header("Content-Type", "text/plain")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201

        with urllib.request.urlopen(
            filer_url(filer, "/docs/hello.txt"), timeout=10
        ) as r:
            assert r.read() == body
            assert r.headers["Content-Type"] == "text/plain"

        # directory listing
        with urllib.request.urlopen(filer_url(filer, "/docs"), timeout=10) as r:
            listing = json.loads(r.read())
        assert any(e["FullPath"] == "/docs/hello.txt" for e in listing["Entries"])

        req = urllib.request.Request(
            filer_url(filer, "/docs/hello.txt"), method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 204
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(filer_url(filer, "/docs/hello.txt"), timeout=10)

    def test_html_directory_browser(self, filer_cluster):
        """Browsers (Accept: text/html) get the breadcrumbed listing
        the reference renders (filer_ui/templates.go); API clients keep
        JSON, now with the reference's LastFileName/ShouldDisplayLoadMore
        pagination fields (filer_server_handlers_read_dir.go:54-66)."""
        _, _, filer = filer_cluster
        for name in ("ua.txt", "ub.txt", "uc.txt"):
            urllib.request.urlopen(
                urllib.request.Request(
                    filer_url(filer, f"/ui/{name}"), data=b"x", method="POST"
                ),
                timeout=10,
            ).read()
        req = urllib.request.Request(
            filer_url(filer, "/ui/"),
            headers={"Accept": "text/html,application/xhtml+xml"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            page = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/html")
        assert "ua.txt" in page and "ui /" in page  # rows + breadcrumb
        # pagination: limit smaller than the dir shows a load-more link
        req = urllib.request.Request(
            filer_url(filer, "/ui/?limit=2"), headers={"Accept": "text/html"}
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            page = r.read().decode()
        assert "load more" in page and "lastFileName=ub.txt" in page
        # JSON default unchanged + new pagination fields
        with urllib.request.urlopen(
            filer_url(filer, "/ui/?limit=2"), timeout=10
        ) as r:
            d = json.loads(r.read())
        assert d["ShouldDisplayLoadMore"] is True
        assert d["LastFileName"] == "ub.txt"

    def test_autochunk_large_file(self, filer_cluster):
        _, _, filer = filer_cluster
        # max_mb=1 → 2.5 MiB body becomes 3 chunks
        body = bytes(range(256)) * 10240  # 2.5 MiB
        req = urllib.request.Request(
            filer_url(filer, "/big/blob.bin"), data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 201
        entry = filer.filer.find_entry("/big/blob.bin")
        assert len(entry.chunks) == 3
        with urllib.request.urlopen(filer_url(filer, "/big/blob.bin"), timeout=30) as r:
            assert r.read() == body

    def test_grpc_surface(self, filer_cluster):
        import grpc

        from seaweedfs_tpu.pb import filer_pb2 as fpb
        from seaweedfs_tpu.pb import rpc

        _, _, filer = filer_cluster
        with grpc.insecure_channel(f"127.0.0.1:{filer.grpc_port}") as ch:
            stub = rpc.filer_stub(ch)
            stub.CreateEntry(
                fpb.CreateEntryRequest(
                    directory="/grpc",
                    entry=fpb.Entry(
                        name="f1", attributes=fpb.Attributes(mtime=11, file_mode=0o660)
                    ),
                )
            )
            resp = stub.LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(directory="/grpc", name="f1")
            )
            assert resp.entry.attributes.mtime == 11

            entries = list(stub.ListEntries(fpb.ListEntriesRequest(directory="/grpc")))
            assert [e.entry.name for e in entries] == ["f1"]

            stub.AtomicRenameEntry(
                fpb.AtomicRenameEntryRequest(
                    old_directory="/grpc", old_name="f1",
                    new_directory="/grpc2", new_name="f2",
                )
            )
            resp = stub.LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(directory="/grpc2", name="f2")
            )
            assert resp.entry.name == "f2"

            ar = stub.AssignVolume(fpb.AssignVolumeRequest(count=1))
            assert "," in ar.fid and ar.url

            cfg = stub.GetFilerConfiguration(fpb.GetFilerConfigurationRequest())
            assert cfg.max_mb == 1

            stub.DeleteEntry(
                fpb.DeleteEntryRequest(
                    directory="/grpc2", name="f2", is_delete_data=True
                )
            )
            with pytest.raises(grpc.RpcError):
                stub.LookupDirectoryEntry(
                    fpb.LookupDirectoryEntryRequest(directory="/grpc2", name="f2")
                )

    def test_chunk_gc_after_delete(self, filer_cluster):
        master, _, filer = filer_cluster
        body = b"gc me " * 1000
        req = urllib.request.Request(
            filer_url(filer, "/gc/target.bin"), data=body, method="POST"
        )
        urllib.request.urlopen(req, timeout=10).close()
        entry = filer.filer.find_entry("/gc/target.bin")
        fid = entry.chunks[0].fid
        req = urllib.request.Request(filer_url(filer, "/gc/target.bin"), method="DELETE")
        urllib.request.urlopen(req, timeout=10).close()
        filer.filer.flush_chunk_deletions()
        # the chunk is gone from the volume server
        from seaweedfs_tpu.client import operation as op

        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                op.download(op.lookup_file_id(f"127.0.0.1:{master.port}", fid))
            except Exception:
                break
            time.sleep(0.1)
        with pytest.raises(Exception):
            op.download(op.lookup_file_id(f"127.0.0.1:{master.port}", fid))


class TestSqliteTransactions:
    """rollback_transaction must undo everything since begin (the
    atomic_rename contract; regression for per-op commits)."""

    def test_rollback_undoes_inserts_and_deletes(self):
        from seaweedfs_tpu.filer.entry import Entry, Attr
        from seaweedfs_tpu.filer.filerstore import SqliteStore, EntryNotFound

        store = SqliteStore(":memory:")
        keep = Entry(full_path="/keep", attr=Attr(mtime=1))
        store.insert_entry(keep)
        store.begin_transaction()
        store.insert_entry(Entry(full_path="/tx-new", attr=Attr(mtime=2)))
        store.delete_entry("/keep")
        store.rollback_transaction()
        # the pre-tx entry survives, the in-tx insert is gone
        assert store.find_entry("/keep").full_path == "/keep"
        import pytest as _pytest

        with _pytest.raises(EntryNotFound):
            store.find_entry("/tx-new")

    def test_commit_applies(self):
        from seaweedfs_tpu.filer.entry import Entry, Attr
        from seaweedfs_tpu.filer.filerstore import SqliteStore

        store = SqliteStore(":memory:")
        store.begin_transaction()
        store.insert_entry(Entry(full_path="/tx", attr=Attr(mtime=1)))
        store.commit_transaction()
        assert store.find_entry("/tx").full_path == "/tx"


def test_empty_file_get_does_not_crash(tmp_path_factory):
    """A chunkless entry (zero-byte POST) must GET cleanly — the read
    path's master probe has no chunks to probe with."""
    import socket
    import time
    import urllib.request

    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    from seaweedfs_tpu.util.availability import free_port

    master = MasterServer(port=free_port(), volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("emptyvs"))],
        port=free_port(),
        master=f"127.0.0.1:{master.port}",
        heartbeat_interval=0.2,
    )
    vs.start()
    filer = None
    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(master.topology.data_nodes()) < 1:
            time.sleep(0.05)
        filer = FilerServer(
            [f"127.0.0.1:{master.port}"], port=free_port(), store="memory"
        )
        filer.start()
        # an entry with content works; then create a zero-byte file via
        # gRPC CreateEntry (the HTTP empty-POST maps to mkdir)
        import grpc

        from seaweedfs_tpu.pb import filer_pb2 as fpb
        from seaweedfs_tpu.pb import rpc as _rpc

        with grpc.insecure_channel(f"127.0.0.1:{filer.port + 10000}") as ch:
            _rpc.filer_stub(ch).CreateEntry(
                fpb.CreateEntryRequest(
                    directory="/",
                    entry=fpb.Entry(
                        name="empty.txt",
                        is_directory=False,
                        attributes=fpb.Attributes(file_mode=0o644),
                    ),
                )
            )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{filer.port}/empty.txt", timeout=10
        ) as r:
            assert r.status == 200
            assert r.read() == b""
    finally:
        if filer:
            filer.stop()
        vs.stop()
        master.stop()


class TestLsmStore:
    """The embedded LSM engine (filer/lsm.py): flush/compaction/WAL
    machinery beyond the generic store conformance above. Thresholds
    are shrunk so a handful of entries crosses them."""

    @staticmethod
    def _mk(tmp_path, **kw):
        from seaweedfs_tpu.filer.lsm import LsmStore

        return LsmStore(str(tmp_path / "lsm"), **kw)

    @staticmethod
    def _entry(i: int) -> Entry:
        return Entry(f"/d/f{i:04d}", attr=Attr(mtime=i, crtime=i))

    def test_persistence_across_reopen_via_wal(self, tmp_path):
        s = self._mk(tmp_path)
        for i in range(5):
            s.insert_entry(self._entry(i))
        s.delete_entry("/d/f0003")
        # no close(): reopen replays the WAL alone
        s2 = self._mk(tmp_path)
        assert s2.find_entry("/d/f0001").attr.mtime == 1
        with pytest.raises(EntryNotFound):
            s2.find_entry("/d/f0003")
        names = [e.name for e in s2.list_directory_entries("/d", "", True, 100)]
        assert names == ["f0000", "f0001", "f0002", "f0004"]
        s2.close()
        s.close()

    def test_flush_creates_sstable_and_survives(self, tmp_path):
        s = self._mk(tmp_path, memtable_bytes=512)
        for i in range(40):
            s.insert_entry(self._entry(i))
        assert s._tables, "memtable never flushed past the 512B threshold"
        s.close()
        s2 = self._mk(tmp_path, memtable_bytes=512)
        for i in range(40):
            assert s2.find_entry(f"/d/f{i:04d}").attr.mtime == i
        s2.close()

    def test_compaction_merges_and_drops_tombstones(self, tmp_path):
        import os

        s = self._mk(tmp_path, memtable_bytes=256, compact_at=3)
        for i in range(60):
            s.insert_entry(self._entry(i))
            if i % 2:
                s.delete_entry(f"/d/f{i:04d}")
        s.flush()
        assert len(s._tables) < 3, "compaction never ran"
        # tombstones are gone from the merged table's raw bytes
        live = [e.name for e in s.list_directory_entries("/d", "", True, 1000)]
        assert live == [f"f{i:04d}" for i in range(0, 60, 2)]
        s.close()
        # reopen sees the same state from tables alone (WAL is empty)
        s2 = self._mk(tmp_path)
        assert not s2._mem
        got = [e.name for e in s2.list_directory_entries("/d", "", True, 1000)]
        assert got == live
        with pytest.raises(EntryNotFound):
            s2.find_entry("/d/f0001")
        s2.close()
        sst_files = [f for f in os.listdir(tmp_path / "lsm") if f.endswith(".sst")]
        assert len(sst_files) == len(s2._tables), "stale sstables not deleted"

    def test_torn_wal_tail_recovered(self, tmp_path):
        s = self._mk(tmp_path)
        for i in range(4):
            s.insert_entry(self._entry(i))
        wal = tmp_path / "lsm" / "wal.log"
        raw = wal.read_bytes()
        wal.write_bytes(raw[:-3])  # tear the last record mid-value
        s2 = self._mk(tmp_path)
        # first three survive; the torn fourth is dropped, not corrupted
        for i in range(3):
            assert s2.find_entry(f"/d/f{i:04d}").attr.mtime == i
        with pytest.raises(EntryNotFound):
            s2.find_entry("/d/f0003")
        # and the truncated WAL accepts appends again
        s2.insert_entry(self._entry(99))
        assert s2.find_entry("/d/f0099").attr.mtime == 99
        s2.close()
        s.close()

    def test_newest_wins_across_tables(self, tmp_path):
        # memtable large enough that only the explicit flush() per
        # round cuts a table: exactly one sstable per round
        s = self._mk(tmp_path, memtable_bytes=100000, compact_at=100)
        for round_ in range(3):
            for i in range(10):
                s.insert_entry(
                    Entry(f"/d/f{i:04d}", attr=Attr(mtime=round_ * 100 + i))
                )
            s.flush()
        assert len(s._tables) == 3
        for i in range(10):
            assert s.find_entry(f"/d/f{i:04d}").attr.mtime == 200 + i
        s.close()

    def test_list_pagination_spanning_tables_and_memtable(self, tmp_path):
        s = self._mk(tmp_path, memtable_bytes=100000, compact_at=100)
        for i in range(0, 20, 2):
            s.insert_entry(self._entry(i))
        s.flush()
        for i in range(1, 20, 2):
            s.insert_entry(self._entry(i))  # stays in memtable
        page1 = [e.name for e in s.list_directory_entries("/d", "", True, 7)]
        assert page1 == [f"f{i:04d}" for i in range(7)]
        page2 = [
            e.name
            for e in s.list_directory_entries("/d", page1[-1], False, 7)
        ]
        assert page2 == [f"f{i:04d}" for i in range(7, 14)]

        # directories are disjoint key ranges: /d2 unaffected by /d
        s.insert_entry(Entry("/d2/x", attr=Attr(mtime=1)))
        assert [e.name for e in s.list_directory_entries("/d2", "", True, 10)] == ["x"]
        s.close()

    def test_wal_mid_file_corruption_cut(self, tmp_path):
        """Regression: a flipped byte mid-WAL must cut the replay at
        the corrupt record (crc), not desync framing into garbage."""
        s = self._mk(tmp_path)
        for i in range(5):
            s.insert_entry(self._entry(i))
        wal = tmp_path / "lsm" / "wal.log"
        raw = bytearray(wal.read_bytes())
        raw[len(raw) // 2] ^= 0xFF  # middle of some record
        wal.write_bytes(bytes(raw))
        s2 = self._mk(tmp_path)
        names = [e.name for e in s2.list_directory_entries("/d", "", True, 100)]
        # a prefix of entries survives, all of them intact
        assert names == [f"f{i:04d}" for i in range(len(names))]
        assert len(names) < 5
        for n in names:
            assert s2.find_entry(f"/d/{n}").attr.mtime == int(n[1:])
        s2.close()
        s.close()

    def test_concurrent_writers_listers_under_compaction(self, tmp_path):
        """The filer serves LSM from many HTTP threads: hammer inserts,
        deletes, point reads, and paginated lists from worker threads
        while tiny thresholds force constant flush + compaction; every
        surviving key must read back intact afterwards."""
        import threading

        s = self._mk(tmp_path, memtable_bytes=2048, compact_at=3)
        errors: list = []
        survivors: dict[int, dict[int, int]] = {}

        def writer(wid: int):
            mine: dict[int, int] = {}
            try:
                for i in range(120):
                    s.insert_entry(
                        Entry(f"/w{wid}/f{i:04d}", attr=Attr(mtime=wid * 1000 + i))
                    )
                    mine[i] = wid * 1000 + i
                    if i % 7 == 3:
                        s.delete_entry(f"/w{wid}/f{i:04d}")
                        del mine[i]
            except Exception as e:  # noqa: BLE001
                errors.append(("w", wid, e))
            survivors[wid] = mine

        def lister():
            try:
                for _ in range(60):
                    for wid in range(4):
                        out = s.list_directory_entries(f"/w{wid}", "", True, 50)
                        for e in out:  # decoded entries must be intact
                            assert e.name.startswith("f")
            except Exception as e:  # noqa: BLE001
                errors.append(("l", e))

        threads = [
            threading.Thread(target=writer, args=(wid,)) for wid in range(4)
        ] + [threading.Thread(target=lister) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        for wid, mine in survivors.items():
            names = {
                e.name
                for e in s.list_directory_entries(f"/w{wid}", "", True, 1000)
            }
            assert names == {f"f{i:04d}" for i in mine}, f"writer {wid}"
            for i, mtime in mine.items():
                assert s.find_entry(f"/w{wid}/f{i:04d}").attr.mtime == mtime
        s.close()

    def test_delete_shadows_put_across_tables_in_listing(self, tmp_path):
        """Regression (deterministic, no threads): a PUT flushed into
        one SSTable and its DELETE flushed into a later one — the
        listing's cross-table merge must honor table recency, not fall
        back to record-type ordering (where PUT < DEL resurrected
        deleted keys)."""
        s = self._mk(tmp_path, memtable_bytes=1 << 20, compact_at=100)
        s.insert_entry(self._entry(1))
        s.insert_entry(self._entry(2))
        s.flush()  # table A: PUT f0001, PUT f0002
        s.delete_entry("/d/f0001")
        s.flush()  # table B: DEL f0001
        assert [e.name for e in s.list_directory_entries("/d", "", True, 10)] == [
            "f0002"
        ]
        with pytest.raises(EntryNotFound):
            s.find_entry("/d/f0001")
        # and the reverse: a newer PUT over an old DEL stays visible
        s.insert_entry(self._entry(1))
        s.flush()  # table C: PUT f0001 again
        assert [e.name for e in s.list_directory_entries("/d", "", True, 10)] == [
            "f0001",
            "f0002",
        ]
        s.close()


class TestChunkAlgebraProperty:
    """Randomized model check of the chunk algebra (beyond the ported
    reference table tests): simulate every write into a byte array
    tagged per position with (mtime, fid, chunk offset), then compare
    the visible intervals and read views against the simulation."""

    @pytest.mark.parametrize("seed", range(8))
    def test_random_overlapping_writes(self, seed):
        import random as _r

        rng = _r.Random(seed)
        file_len = rng.randint(50, 400)
        n_chunks = rng.randint(1, 12)
        chunks = []
        # distinct mtimes: the algebra breaks ties by mtime order, and
        # real appends always have increasing timestamps
        mtimes = rng.sample(range(1, 10_000), n_chunks)
        for i in range(n_chunks):
            off = rng.randint(0, file_len - 1)
            size = rng.randint(1, file_len - off)
            chunks.append(C(off, size, f"fid{i}", mtimes[i]))

        # byte-level simulation: later mtime wins per position
        owner: list[tuple[int, str, int] | None] = [None] * file_len
        for c in chunks:
            for p in range(c.offset, min(c.offset + c.size, file_len)):
                if owner[p] is None or c.mtime > owner[p][0]:
                    owner[p] = (c.mtime, c.fid, c.offset)

        visible = fc.non_overlapping_visible_intervals(chunks)
        # 1) intervals are disjoint, sorted, and match ownership
        prev_stop = -1
        covered = [None] * file_len
        for v in visible:
            assert v.start >= prev_stop, "overlapping/unsorted intervals"
            prev_stop = v.stop
            for p in range(v.start, v.stop):
                assert owner[p] is not None, f"interval over unwritten byte {p}"
                assert owner[p][1] == v.fid, f"byte {p}: wrong winner"
                covered[p] = v.fid
        # 2) every written byte is covered
        for p in range(file_len):
            if owner[p] is not None:
                assert covered[p] == owner[p][1], f"byte {p} uncovered"

        # 3) read views agree with the simulation for random spans.
        # Reference semantics (ViewFromVisibleIntervals): a read returns
        # only the CONTIGUOUS run starting at `offset` — the first hole
        # ends the view list, and a read starting inside a hole returns
        # nothing.
        for _ in range(10):
            off = rng.randint(0, file_len - 1)
            size = rng.randint(1, file_len - off)
            views = fc.view_from_chunks(chunks, off, size)
            seen = {}
            for view in views:
                for j in range(view.size):
                    seen[view.logic_offset + j] = view.fid
            expect = {}
            p = off
            while p < off + size and owner[p] is not None:
                expect[p] = owner[p][1]
                p += 1
            assert seen == expect, f"span [{off},{off + size})"


class TestTikvStore:
    """tikv-specific behaviors beyond the conformance matrix: PD region
    routing with epoch-retry, scans that cross RawScan batch limits,
    and the md5(dir)+name key scheme (tikv_store.go:223-247)."""

    @pytest.fixture()
    def tikv(self):
        from tests.cloud_fakes import FakeTikv

        f = FakeTikv()
        f.start()
        yield f
        f.stop()

    def test_region_error_refreshes_and_retries(self, tikv):
        from seaweedfs_tpu.filer.tikv_store import TikvStore

        s = TikvStore(tikv.address)
        s.insert_entry(Entry("/d/one", attr=Attr(mtime=1)))
        # stale epoch on the next op: the client must invalidate its
        # region cache, re-route via PD, and succeed on the retry
        tikv.fail_next_with_region_error = 1
        assert s.find_entry("/d/one").attr.mtime == 1
        tikv.fail_next_with_region_error = 1
        s.insert_entry(Entry("/d/two", attr=Attr(mtime=2)))
        assert s.find_entry("/d/two").attr.mtime == 2

    def test_scan_crosses_batch_limit(self, tikv):
        import seaweedfs_tpu.filer.tikv_store as ts

        s = ts.TikvStore(tikv.address)
        old = ts.SCAN_BATCH
        ts.SCAN_BATCH = 7  # force multi-batch iteration
        try:
            names = [f"f{i:03d}" for i in range(25)]
            for n in names:
                s.insert_entry(Entry(f"/big/{n}", attr=Attr(mtime=1)))
            got = [
                e.name
                for e in s.list_directory_entries("/big", "", True, 100)
            ]
            assert got == names
            # pagination across batches too
            got = [
                e.name
                for e in s.list_directory_entries("/big", "f009", False, 100)
            ]
            assert got == names[10:]
            s.delete_folder_children("/big")
            assert s.list_directory_entries("/big", "", True, 100) == []
        finally:
            ts.SCAN_BATCH = old

    def test_key_scheme_matches_reference(self, tikv):
        """Key = md5(dir) + name; sibling dirs with a shared string
        prefix must not bleed into each other's listings (the md5 hash
        is what isolates them, exactly as genKey does)."""
        from seaweedfs_tpu.filer.tikv_store import TikvStore, _gen_key
        import hashlib

        assert _gen_key("/home/user", "a.txt") == (
            hashlib.md5(b"/home/user").digest() + b"a.txt"
        )
        s = TikvStore(tikv.address)
        s.insert_entry(Entry("/pre/x", attr=Attr(mtime=1)))
        s.insert_entry(Entry("/prefix/y", attr=Attr(mtime=2)))
        assert [e.name for e in s.list_directory_entries("/pre", "", True, 10)] == ["x"]

    def test_filer_runs_on_tikv(self, tikv):
        """The whole Filer on a tikv store (the -store tikv path)."""
        from seaweedfs_tpu.filer.filerstore import new_store

        f = Filer(store=new_store("tikv", tikv.address))
        f.create_entry(Entry("/docs/readme.md", attr=Attr(mtime=3, crtime=3)))
        assert f.find_entry("/docs/readme.md").attr.mtime == 3
        names = [e.name for e in f.list_entries("/docs", "", True, 10)]
        assert names == ["readme.md"]
        f.delete_entry("/docs/readme.md")
        with pytest.raises(EntryNotFound):
            f.find_entry("/docs/readme.md")
