"""BandwidthArbiter (scrub/arbiter.py): ONE budget for every
background byte-mover — rebuild, replication, handoff replay, tier
transfers — with weighted max-min shares over ACTIVE claimants and the
serve-first yield.

The regression that motivated it (ROADMAP "repair/handoff
arbitration" gap): a big hinted-handoff replay used to run unpaced
against an EC rebuild racing a second shard loss. The contention test
here proves a rebuild keeps making progress at roughly its weighted
share while a replay storm runs flat out.
"""

from __future__ import annotations

import threading
import time

import pytest

from seaweedfs_tpu.scrub.arbiter import (
    BandwidthArbiter,
    arbiter_enabled,
    get_arbiter,
    set_arbiter,
)


class TestBasics:
    def test_disabled_admits_immediately_but_still_counts(self, monkeypatch):
        monkeypatch.setenv("WEED_ARBITER", "0")
        a = BandwidthArbiter(total_bytes_s=10.0)
        assert not a.enabled
        t0 = time.monotonic()
        for _ in range(50):
            assert a.take("rebuild", 10_000_000)
        assert time.monotonic() - t0 < 1.0  # no pacing at all
        st = a.stats()
        assert st["Claimants"]["rebuild"]["Bytes"] == 50 * 10_000_000
        assert st["Claimants"]["rebuild"]["Takes"] == 50

    def test_env_kill_switch_helper(self, monkeypatch):
        monkeypatch.setenv("WEED_ARBITER", "0")
        assert not arbiter_enabled()
        monkeypatch.delenv("WEED_ARBITER")
        assert arbiter_enabled()

    def test_lone_claimant_gets_whole_budget(self):
        # 1 MB/s total; a lone claimant charging 100 KB chunks should
        # sustain ~the full rate, NOT its 45% weighted slice
        a = BandwidthArbiter(total_bytes_s=1_000_000.0)
        moved = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:
            assert a.take("rebuild", 100_000)
            moved += 100_000
        # generous bound: well above the 450 KB/s a wrongly-applied
        # 45% weighted slice would allow, below the exact 1 MB/s
        assert moved >= 700_000, f"lone claimant starved: {moved} B/s"

    def test_take_charges_full_n_beyond_burst(self):
        # an item larger than burst admits on burst but charges fully:
        # two oversized takes must take >= n/rate seconds in total
        a = BandwidthArbiter(total_bytes_s=1_000_000.0)
        # admits once ~1 s of budget (the burst cap) accrues, but the
        # full 2 MB is charged — leaving ~1 MB of debt behind
        assert a.take("tier", 2_000_000)
        t0 = time.monotonic()
        assert a.take("tier", 100_000)
        # the debt must drain first (~1 s at 1 MB/s; tier is alone so
        # it owns the whole budget)
        assert time.monotonic() - t0 > 0.8

    def test_stop_event_aborts_wait_and_refunds(self):
        a = BandwidthArbiter(total_bytes_s=1000.0)
        stop = threading.Event()
        assert a.take("handoff", 500_000)  # drains the budget deep
        result = {}

        def blocked():
            result["r"] = a.take("handoff", 500_000, stop=stop)

        th = threading.Thread(target=blocked)
        th.start()
        time.sleep(0.2)
        stop.set()
        th.join(timeout=5)
        assert not th.is_alive()
        assert result["r"] is False
        # the aborted take refunded its byte count
        assert a.stats()["Claimants"]["handoff"]["Bytes"] == 500_000

    def test_unknown_claimant_gets_default_weight(self):
        a = BandwidthArbiter(total_bytes_s=1_000_000.0)
        assert a.take("mystery", 1)
        assert "mystery" in a.stats()["Claimants"]

    def test_get_set_roundtrip(self):
        mine = BandwidthArbiter(total_bytes_s=123.0)
        prev = set_arbiter(mine)
        try:
            assert get_arbiter() is mine
        finally:
            set_arbiter(prev)


class TestServeFirstYield:
    def test_note_serve_throttles_background(self):
        a = BandwidthArbiter(
            total_bytes_s=1_000_000.0,
            yield_window_s=10.0,
            yield_factor=0.1,
        )
        a.note_serve()
        st = a.stats()
        assert st["Serving"]
        # every rate is multiplied down by the yield factor
        assert (
            st["Claimants"]["rebuild"]["RateBytesPerSec"]
            <= 0.1 * 1_000_000.0 + 1
        )

    def test_yield_expires(self):
        a = BandwidthArbiter(
            total_bytes_s=1_000_000.0,
            yield_window_s=0.05,
            yield_factor=0.1,
        )
        a.note_serve()
        time.sleep(0.1)
        assert not a.stats()["Serving"]


class TestContention:
    @pytest.mark.slow
    def test_rebuild_progresses_during_handoff_storm(self):
        """THE regression: a rebuild sharing the arbiter with a
        flat-out handoff replay still moves at least its weighted
        share of bytes — the replay cannot starve it."""
        a = BandwidthArbiter(
            total_bytes_s=2_000_000.0,
            yield_window_s=0.0,  # no serving in this test
        )
        stop = threading.Event()
        moved = {"rebuild": 0, "handoff": 0}
        lock = threading.Lock()

        def mover(name, chunk):
            while not stop.is_set():
                if not a.take(name, chunk, stop=stop):
                    return
                with lock:
                    moved[name] += chunk

        threads = [
            threading.Thread(target=mover, args=("handoff", 64_000)),
            threading.Thread(target=mover, args=("rebuild", 64_000)),
        ]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        # weights: rebuild 0.45 vs handoff 0.20 → under contention the
        # rebuild share is 0.45/0.65 ≈ 69%. Bursts blur the edges, so
        # assert the structural property loosely: rebuild got MORE
        # than handoff, and at least a third of the total.
        total = moved["rebuild"] + moved["handoff"]
        assert total > 0
        assert moved["rebuild"] > moved["handoff"], moved
        assert moved["rebuild"] >= total / 3, moved

    @pytest.mark.slow
    def test_inactive_claimant_leaves_no_hole(self):
        """A claimant that stops charging drops out of the share
        denominator within the active window — the survivor's rate
        recovers to ~the whole budget."""
        a = BandwidthArbiter(total_bytes_s=1_000_000.0, yield_window_s=0.0)
        assert a.take("handoff", 1)  # becomes active
        assert a.take("rebuild", 1)
        # both active: rebuild's share is weighted
        shared = a.stats()["Claimants"]["rebuild"]["RateBytesPerSec"]
        assert shared < 900_000
        time.sleep(2.2)  # handoff goes inactive (window = 2 s)
        a.take("rebuild", 1)
        solo = a.stats()["Claimants"]["rebuild"]["RateBytesPerSec"]
        assert solo >= 900_000, (shared, solo)
