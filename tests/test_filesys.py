"""FUSE-layer tests: dirty-page intervals + the in-process mount.

The three ContinuousIntervals cases are ports of the reference's
weed/filesys/dirty_page_interval_test.go; the mount tests drive the
full node layer (write buffering, chunk flush, rename, truncate,
xattr, symlink) against a real in-process master + volume + filer
cluster — coverage the reference itself has no way to run in CI.
"""

import os
import time

import pytest

from seaweedfs_tpu.filesys.page_writer import ContinuousIntervals


def get_bytes(content: int, length: int) -> bytes:
    return bytes([content]) * length


def expected_data(c: ContinuousIntervals, offset: int, *data: int) -> None:
    start, stop = offset, offset + len(data)
    expect = bytes(data)
    for run in c.runs:
        lo, hi = max(start, run.offset), min(stop, run.end)
        if lo < hi:
            buf = bytearray(hi - lo)
            run.read_into(buf, lo, lo, hi)
            assert bytes(buf) == expect[lo - start : hi - start], (
                f"run [{run.offset},{run.end}): {bytes(buf)!r} != "
                f"{expect[lo - start:hi - start]!r}"
            )


class TestContinuousIntervals:
    """dirty_page_interval_test.go ports."""

    def test_add_interval_append(self):
        c = ContinuousIntervals()
        c.add_interval(get_bytes(25, 3), 0)
        c.add_interval(get_bytes(23, 4), 2)
        expected_data(c, 0, 25, 25, 23, 23, 23, 23)
        assert len(c.runs) == 1  # merged into one continuous run

    def test_add_interval_inner_overwrite(self):
        c = ContinuousIntervals()
        c.add_interval(get_bytes(25, 5), 0)
        c.add_interval(get_bytes(23, 2), 2)
        expected_data(c, 0, 25, 25, 23, 23, 25)

    def test_add_interval_full_overwrite(self):
        c = ContinuousIntervals()
        c.add_interval(get_bytes(25, 1), 0)
        c.add_interval(get_bytes(23, 2), 4)
        c.add_interval(get_bytes(24, 4), 3)
        c.add_interval(get_bytes(22, 2), 1)
        expected_data(c, 0, 25, 22, 22, 24, 24, 24, 24)

    def test_read_data_window(self):
        c = ContinuousIntervals()
        c.add_interval(b"abcd", 10)
        c.add_interval(b"xy", 20)
        off, size, buf = c.read_data(16, 8)
        assert off == 10
        assert size == 12  # from 10 to 22
        assert bytes(buf[2:6]) == b"abcd"
        assert bytes(buf[12:14]) == b"xy"

    def test_remove_largest(self):
        c = ContinuousIntervals()
        c.add_interval(b"aa", 0)
        c.add_interval(b"bbbb", 10)
        run = c.remove_largest_run()
        assert run.to_bytes() == b"bbbb" and run.offset == 10
        assert c.total_size() == 2
        assert c.remove_largest_run().to_bytes() == b"aa"
        assert c.remove_largest_run() is None


@pytest.fixture(scope="module")
def mounted(tmp_path_factory):
    """master + volume + filer + MountedFileSystem, all in-process."""
    import socket

    from seaweedfs_tpu.filesys import MountedFileSystem, WfsOption
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    from seaweedfs_tpu.util.availability import free_port

    master = MasterServer(port=free_port(), volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("fusevs"))],
        port=free_port(),
        master=f"127.0.0.1:{master.port}",
        heartbeat_interval=0.2,
        max_volume_counts=[100],
    )
    vs.start()
    deadline = time.time() + 45
    while time.time() < deadline and len(master.topology.data_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer([f"127.0.0.1:{master.port}"], port=free_port(), store="memory")
    filer.start()
    # tiny chunk limit so multi-chunk flushing is exercised
    mfs = MountedFileSystem(
        WfsOption(f"127.0.0.1:{filer.port}", chunk_size_limit=1024)
    )
    yield mfs
    mfs.close()
    filer.stop()
    vs.stop()
    master.stop()


class TestMountedFileSystem:
    def test_write_read_roundtrip(self, mounted):
        mounted.write_file("/hello.txt", b"hello fuse world")
        assert mounted.read_file("/hello.txt") == b"hello fuse world"

    def test_multi_chunk_write(self, mounted):
        # 5000 bytes through a 1024-byte chunk limit: forces repeated
        # largest-run flushes + a final flush; read crosses chunks
        payload = bytes(range(256)) * 20  # 5120 bytes
        with mounted.open("/big.bin", "w") as f:
            for i in range(0, len(payload), 700):
                f.write(payload[i : i + 700])
        assert mounted.read_file("/big.bin") == payload
        st = mounted.stat("/big.bin")
        assert st.size == len(payload)

    def test_oversized_single_write(self, mounted):
        payload = b"z" * 4096  # > chunk_size_limit in one write
        mounted.write_file("/oversize.bin", payload)
        assert mounted.read_file("/oversize.bin") == payload

    def test_read_during_dirty(self, mounted):
        with mounted.open("/dirty.txt", "w") as f:
            f.write(b"0123456789")
            f.seek(3)
            f.write(b"ABC")
            # read-back before flush sees dirty pages win
            f.seek(0)
            assert f.read() == b"012ABC6789"
        assert mounted.read_file("/dirty.txt") == b"012ABC6789"

    def test_overwrite_middle_of_flushed_file(self, mounted):
        mounted.write_file("/ow.txt", b"aaaaaaaaaa")
        with mounted.open("/ow.txt", "r+") as f:
            f.seek(4)
            f.write(b"BB")
        assert mounted.read_file("/ow.txt") == b"aaaaBBaaaa"

    def test_append(self, mounted):
        mounted.write_file("/log.txt", b"line1\n")
        with mounted.open("/log.txt", "a") as f:
            f.write(b"line2\n")
        assert mounted.read_file("/log.txt") == b"line1\nline2\n"

    def test_mkdir_listdir_remove(self, mounted):
        mounted.makedirs("/a/b/c")
        mounted.write_file("/a/b/c/f.txt", b"x")
        assert mounted.listdir("/a/b") == ["c"]
        assert mounted.listdir("/a/b/c") == ["f.txt"]
        assert mounted.stat("/a/b").is_dir
        mounted.unlink("/a/b/c/f.txt")
        assert mounted.listdir("/a/b/c") == []
        mounted.rmdir("/a/b/c")
        assert mounted.listdir("/a/b") == []

    def test_rmdir_nonempty_fails(self, mounted):
        from seaweedfs_tpu.filesys.nodes import NotEmpty

        mounted.makedirs("/ne")
        mounted.write_file("/ne/keep.txt", b"k")
        with pytest.raises(NotEmpty):
            mounted.rmdir("/ne")

    def test_rename(self, mounted):
        mounted.write_file("/old_name.txt", b"payload")
        mounted.makedirs("/sub")
        mounted.rename("/old_name.txt", "/sub/new_name.txt")
        assert not mounted.exists("/old_name.txt")
        assert mounted.read_file("/sub/new_name.txt") == b"payload"

    def test_truncate(self, mounted):
        mounted.write_file("/trunc.txt", b"0123456789")
        mounted.truncate("/trunc.txt", 4)
        st = mounted.stat("/trunc.txt")
        assert st.size == 4
        assert mounted.read_file("/trunc.txt") == b"0123"

    def test_xattr(self, mounted):
        mounted.write_file("/x.txt", b"x")
        mounted.setxattr("/x.txt", "user.tag", b"v1")
        assert mounted.getxattr("/x.txt", "user.tag") == b"v1"
        assert mounted.listxattr("/x.txt") == ["user.tag"]

    def test_symlink(self, mounted):
        mounted.write_file("/target.txt", b"t")
        mounted.symlink("/target.txt", "/alias.txt")
        assert mounted.readlink("/alias.txt") == "/target.txt"

    def test_open_missing_raises(self, mounted):
        from seaweedfs_tpu.filesys.nodes import NotFound

        with pytest.raises(NotFound):
            mounted.open("/nope.txt", "r")


class TestMountConcurrency:
    """Concurrent writers/readers through the in-process mount: each
    thread owns its own files (FUSE guarantees per-handle ordering, not
    cross-file atomicity) and every byte must survive the dirty-page →
    flush → chunk pipeline; one thread re-reads flushed files while
    others are still dirtying theirs."""

    def test_parallel_writers_and_reader(self, mounted):
        import threading

        fs = mounted
        errors: list = []
        payloads: dict[str, bytes] = {}
        lock = threading.Lock()

        def writer(wid: int):
            try:
                import random

                rng = random.Random(wid)
                for i in range(8):
                    path = f"/stress/w{wid}/f{i}.bin"
                    # multi-write files: exercises interval merging
                    parts = [
                        bytes(rng.randbytes(rng.randint(100, 60_000)))
                        for _ in range(3)
                    ]
                    with fs.open(path, "w") as f:
                        for p in parts:
                            f.write(p)
                    with lock:
                        payloads[path] = b"".join(parts)
            except Exception as e:  # noqa: BLE001
                errors.append(("w", wid, e))

        def reader():
            try:
                for _ in range(40):
                    with lock:
                        items = list(payloads.items())[:5]
                    for path, want in items:
                        assert fs.read_file(path) == want, path
            except Exception as e:  # noqa: BLE001
                errors.append(("r", e))

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        threads.append(threading.Thread(target=reader))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:2]
        for path, want in payloads.items():
            assert fs.read_file(path) == want, path
        # and the namespace agrees
        for wid in range(4):
            names = sorted(fs.listdir(f"/stress/w{wid}"))
            assert names == [f"f{i}.bin" for i in range(8)]


def _kernel_fuse_usable() -> bool:
    from seaweedfs_tpu.filesys.fuse_kernel import kernel_fuse_available

    return kernel_fuse_available()


@pytest.mark.skipif(
    not _kernel_fuse_usable(), reason="/dev/fuse not openable in this sandbox"
)
class TestKernelFuseMount:
    """The wire-protocol transport against a REAL kernel mountpoint:
    every operation below goes through the Linux VFS → /dev/fuse →
    fuse_kernel.py → WFS → filer/volume servers. The in-process
    MountedFileSystem tests above stay the no-privilege CI path."""

    @pytest.fixture(scope="class")
    def kmount(self, tmp_path_factory, mounted):
        from seaweedfs_tpu.filesys.fuse_kernel import (
            FuseProtocolError,
            KernelFuseMount,
        )

        mnt = str(tmp_path_factory.mktemp("kfuse"))
        km = KernelFuseMount(mounted, mnt)
        try:
            km.mount()
        except FuseProtocolError as e:
            pytest.skip(f"cannot kernel-mount here: {e}")
        km.serve_background()
        yield mnt
        km.unmount()

    def test_write_read_through_kernel(self, kmount):
        p = os.path.join(kmount, "hello.txt")
        data = b"kernel mount payload " * 200  # multi-chunk (1 KiB limit)
        with open(p, "wb") as f:
            f.write(data)
        with open(p, "rb") as f:
            assert f.read() == data
        assert os.path.getsize(p) == len(data)

    def test_o_excl_create_fails_on_existing(self, kmount):
        """open(O_CREAT|O_EXCL) on an existing file must raise EEXIST
        and leave the content intact — the kernel forwards exclusivity
        enforcement to the CREATE handler when no negative dentry is
        cached."""
        p = os.path.join(kmount, "excl-k.txt")
        with open(p, "wb") as f:
            f.write(b"keep me")
        with pytest.raises(FileExistsError):
            fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            os.close(fd)
        with open(p, "rb") as f:
            assert f.read() == b"keep me"

    def test_mkdir_listdir_rename_unlink(self, kmount):
        d = os.path.join(kmount, "kdir")
        os.mkdir(d)
        for n in ("a.txt", "b.txt"):
            with open(os.path.join(d, n), "wb") as f:
                f.write(n.encode())
        assert sorted(os.listdir(d)) == ["a.txt", "b.txt"]
        os.rename(os.path.join(d, "a.txt"), os.path.join(d, "c.txt"))
        assert sorted(os.listdir(d)) == ["b.txt", "c.txt"]
        with open(os.path.join(d, "c.txt"), "rb") as f:
            assert f.read() == b"a.txt"
        os.unlink(os.path.join(d, "b.txt"))
        assert os.listdir(d) == ["c.txt"]
        os.unlink(os.path.join(d, "c.txt"))
        os.rmdir(d)
        assert "kdir" not in os.listdir(kmount)

    def test_stat_and_truncate(self, kmount):
        p = os.path.join(kmount, "t.bin")
        with open(p, "wb") as f:
            f.write(b"x" * 5000)
        st = os.stat(p)
        assert st.st_size == 5000
        os.truncate(p, 1234)
        assert os.stat(p).st_size == 1234
        with open(p, "rb") as f:
            assert f.read() == b"x" * 1234

    def test_append_through_kernel(self, kmount):
        p = os.path.join(kmount, "log.txt")
        with open(p, "wb") as f:
            f.write(b"one")
        with open(p, "ab") as f:
            f.write(b"two")
        with open(p, "rb") as f:
            assert f.read() == b"onetwo"

    def test_symlink_and_readlink(self, kmount):
        p = os.path.join(kmount, "real.txt")
        with open(p, "wb") as f:
            f.write(b"target data")
        link = os.path.join(kmount, "alias")
        os.symlink("real.txt", link)
        assert os.readlink(link) == "real.txt"
        with open(link, "rb") as f:
            assert f.read() == b"target data"

    def test_subprocess_sees_the_mount(self, kmount):
        """A DIFFERENT process (shell tools) reads the mount — proving
        this is a real kernel filesystem, not process state."""
        import subprocess

        p = os.path.join(kmount, "proc.txt")
        with open(p, "wb") as f:
            f.write(b"cross-process")
        out = subprocess.run(
            ["cat", p], capture_output=True, timeout=30
        )
        assert out.stdout == b"cross-process"
        out = subprocess.run(
            ["ls", kmount], capture_output=True, text=True, timeout=30
        )
        assert "proc.txt" in out.stdout

    def test_parallel_writers_through_kernel_mount(self, kmount):
        """Concurrent OS-level file IO through the real mount: the
        single-threaded FUSE loop serializes requests, but interleaved
        open/write/close from many threads must stay byte-correct."""
        import threading

        payloads = {}
        errors = []
        lock = threading.Lock()

        def writer(wid):
            try:
                rng_data = bytes((wid * 37 + i) % 256 for i in range(30_000))
                for i in range(4):
                    p = os.path.join(kmount, f"kstress_{wid}_{i}.bin")
                    with open(p, "wb") as f:
                        for off in range(0, len(rng_data), 7000):
                            f.write(rng_data[off : off + 7000])
                    with lock:
                        payloads[p] = rng_data
            except Exception as e:  # noqa: BLE001
                errors.append((wid, repr(e)))

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for p, want in payloads.items():
            with open(p, "rb") as f:
                assert f.read() == want, p
        for p in payloads:
            os.unlink(p)


@pytest.mark.skipif(
    not _kernel_fuse_usable(), reason="/dev/fuse not openable in this sandbox"
)
class TestKernelFuseProtocol:
    """Wire-level dispatch semantics that shell IO doesn't reach:
    RENAME2 flag handling and FORGET nodeid reclamation."""

    @pytest.fixture()
    def km(self, mounted):
        from seaweedfs_tpu.filesys.fuse_kernel import KernelFuseMount

        # dispatch-level tests need no real mount: drive _dispatch
        return KernelFuseMount(mounted, "/nonexistent-not-mounted")

    def test_rename2_noreplace_and_exchange(self, km):
        import errno
        import struct

        from seaweedfs_tpu.filesys import fuse_kernel as fk

        km.mfs.write_file("/r2a.txt", b"a")
        km.mfs.write_file("/r2b.txt", b"b")
        hdr = struct.Struct("<QII")

        def rename2(old, new, flags):
            body = hdr.pack(1, flags, 0) + old + b"\0" + new + b"\0"
            return km._dispatch(fk.RENAME2, 1, body)

        # NOREPLACE onto an existing target: EEXIST, target untouched
        assert rename2(b"r2a.txt", b"r2b.txt", 1) == -errno.EEXIST
        assert km.mfs.read_file("/r2b.txt") == b"b"
        # EXCHANGE is unsupported: EINVAL, nothing moved
        assert rename2(b"r2a.txt", b"r2b.txt", 2) == -errno.EINVAL
        assert km.mfs.read_file("/r2a.txt") == b"a"
        # NOREPLACE onto a fresh name succeeds
        assert rename2(b"r2a.txt", b"r2c.txt", 1) == b""
        assert km.mfs.read_file("/r2c.txt") == b"a"

    def test_forget_reclaims_nodeids(self, km):
        import struct

        from seaweedfs_tpu.filesys import fuse_kernel as fk

        km.mfs.write_file("/fg.txt", b"x")
        out = km._dispatch(fk.LOOKUP, 1, b"fg.txt\0")
        assert isinstance(out, bytes)
        (nid,) = struct.unpack_from("<Q", out)
        assert nid in km._nodes and km._nlookup[nid] == 1
        # second lookup bumps the kernel refcount
        km._dispatch(fk.LOOKUP, 1, b"fg.txt\0")
        assert km._nlookup[nid] == 2
        # forget with the full count reclaims the id
        km._dispatch(fk.FORGET, nid, struct.pack("<Q", 2))
        assert nid not in km._nodes and nid not in km._nlookup

    def test_batch_forget(self, km):
        import struct

        from seaweedfs_tpu.filesys import fuse_kernel as fk

        km.mfs.write_file("/bf1.txt", b"x")
        km.mfs.write_file("/bf2.txt", b"y")
        n1 = struct.unpack_from(
            "<Q", km._dispatch(fk.LOOKUP, 1, b"bf1.txt\0")
        )[0]
        n2 = struct.unpack_from(
            "<Q", km._dispatch(fk.LOOKUP, 1, b"bf2.txt\0")
        )[0]
        body = struct.pack("<II", 2, 0) + struct.pack("<QQ", n1, 1)
        body += struct.pack("<QQ", n2, 1)
        km._dispatch(fk.BATCH_FORGET, 0, body)
        assert n1 not in km._nodes and n2 not in km._nodes

    def test_create_o_excl_on_existing_file(self, km):
        """CREATE must enforce O_EXCL itself: with no cached negative
        dentry the kernel forwards O_CREAT|O_EXCL for existing files,
        and truncating instead of failing EEXIST loses data."""
        import errno
        import os
        import struct

        from seaweedfs_tpu.filesys import fuse_kernel as fk

        km.mfs.write_file("/excl.txt", b"precious")
        body = (
            struct.pack(
                "<IIII", os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644, 0, 0
            )
            + b"excl.txt\0"
        )
        assert km._dispatch(fk.CREATE, 1, body) == -errno.EEXIST
        assert km.mfs.read_file("/excl.txt") == b"precious"
        # O_CREAT without O_TRUNC on an existing file preserves content
        # (read-modify-write openers must not lose data)
        body = struct.pack("<IIII", os.O_CREAT | os.O_WRONLY, 0o644, 0, 0)
        body += b"excl.txt\0"
        out = km._dispatch(fk.CREATE, 1, body)
        assert isinstance(out, bytes)
        assert km.mfs.read_file("/excl.txt") == b"precious"
        # O_CREAT|O_TRUNC clobbers, as it should
        body = struct.pack(
            "<IIII", os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o644, 0, 0
        )
        body += b"excl.txt\0"
        out = km._dispatch(fk.CREATE, 1, body)
        assert isinstance(out, bytes)
        assert km.mfs.read_file("/excl.txt") == b""


@pytest.mark.skipif(
    not _kernel_fuse_usable(), reason="/dev/fuse not openable in this sandbox"
)
class TestKernelFuseConcurrency:
    """The dispatch loop is concurrent (per-nodeid strands on a thread
    pool, the bazil goroutine-per-request model behind wfs.go:46-70):
    a READ blocked on a slow backend must not stall unrelated ops."""

    def test_slow_read_does_not_block_lookup(self, mounted, tmp_path_factory):
        import threading
        import time as _time

        from seaweedfs_tpu.filesys.fuse_kernel import (
            FuseProtocolError,
            KernelFuseMount,
        )

        mnt = str(tmp_path_factory.mktemp("kfuse-conc"))
        km = KernelFuseMount(mounted, mnt)
        try:
            km.mount()
        except FuseProtocolError as e:
            pytest.skip(f"cannot kernel-mount here: {e}")
        km.serve_background()
        try:
            mounted.write_file("/slow.bin", b"s" * 4096)
            mounted.write_file("/fast-a.txt", b"f")
            # wrap open(): reads of /slow.bin stall 1.5 s in the handler
            orig_open = mounted.open

            def slow_open(path, mode="r"):
                f = orig_open(path, mode)
                if path.endswith("slow.bin"):
                    orig_read = f.read

                    def slow_read(size=-1):
                        _time.sleep(1.5)
                        return orig_read(size)

                    f.read = slow_read
                return f

            mounted.open = slow_open
            try:
                done = {}

                def reader():
                    with open(os.path.join(mnt, "slow.bin"), "rb") as f:
                        done["data"] = f.read()

                t = threading.Thread(target=reader)
                t.start()
                _time.sleep(0.3)  # let the READ reach the slow backend
                t0 = _time.perf_counter()
                st = os.stat(os.path.join(mnt, "fast-a.txt"))
                dt = _time.perf_counter() - t0
                t.join(timeout=10)
                assert st.st_size == 1
                assert done.get("data") == b"s" * 4096
                # single-threaded dispatch would serialize this stat
                # behind the 1.5 s read
                assert dt < 1.0, f"LOOKUP blocked {dt:.2f}s behind slow READ"
            finally:
                mounted.open = orig_open
        finally:
            km.unmount()

    def test_strands_keep_same_node_order(self, mounted):
        """Ops for one nodeid run in arrival order even under the pool;
        different nodeids interleave freely."""
        import random
        import threading
        import time as _time
        from concurrent.futures import ThreadPoolExecutor

        from seaweedfs_tpu.filesys.fuse_kernel import READ, KernelFuseMount

        km = KernelFuseMount(mounted, "/nonexistent-not-mounted")
        km._pool = ThreadPoolExecutor(max_workers=8)
        seen: dict[int, list[int]] = {}
        lock = threading.Lock()
        rng = random.Random(3)

        def fake_handle(opcode, nodeid, unique, body):
            _time.sleep(rng.random() * 0.002)
            with lock:
                seen.setdefault(nodeid, []).append(unique)

        km._handle_one = fake_handle
        expect: dict[int, list[int]] = {}
        for seq in range(200):
            nid = seq % 5
            expect.setdefault(nid, []).append(seq)
            km._enqueue(nid, (READ, nid, seq, b""))
        km._pool.shutdown(wait=True)
        assert seen == expect
