"""On-disk ABI tests: CRC, needle wire format, idx entries, superblock,
TTL, replica placement, file ids.

Golden values cross-checked against the reference implementation's
formats (citations in each module under seaweedfs_tpu/storage/).
"""

import io

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.file_id import FileId, format_needle_id_cookie, parse_needle_id_cookie
from seaweedfs_tpu.storage.needle import (
    Needle,
    CorruptNeedle,
    get_actual_size,
    padding_length,
)
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.super_block import (
    VERSION1,
    VERSION2,
    VERSION3,
    SuperBlock,
)
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.util.crc import _crc32c_py, crc32c, masked_value, needle_checksum


class TestCrc:
    def test_crc32c_check_vector(self):
        # Canonical CRC-32C check value (iSCSI test vector).
        assert crc32c(b"123456789") == 0xE3069283

    def test_crc32c_empty(self):
        assert crc32c(b"") == 0

    def test_incremental_update_matches_one_shot(self):
        data = bytes(range(256)) * 7 + b"tail"
        c = crc32c(data[:100])
        c = crc32c(data[100:], c)
        assert c == crc32c(data)

    @staticmethod
    def _crc32c_bitwise(data: bytes) -> int:
        # Independent bit-at-a-time reference implementation.
        c = 0xFFFFFFFF
        for byte in data:
            c ^= byte
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        return c ^ 0xFFFFFFFF

    def test_against_independent_bitwise_impl(self):
        rng = np.random.default_rng(0)
        for n in [0, 1, 7, 8, 9, 63, 64, 100, 1023]:
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            expected = self._crc32c_bitwise(data)
            assert _crc32c_py(data) == expected
            assert crc32c(data) == expected

    def test_masked_value(self):
        # crc.go:24: Value() = rotl17(c) + 0xa282ead8 (mod 2^32)
        c = 0x12345678
        expected = (((c << 17) | (c >> 15)) + 0xA282EAD8) & 0xFFFFFFFF
        assert masked_value(c) == expected

    def test_needle_checksum_is_masked(self):
        data = b"hello world"
        assert needle_checksum(data) == masked_value(crc32c(data))


class TestPadding:
    def test_padding_never_zero(self):
        # needle_read_write.go:287: pad = 8 - (x % 8), so 8 when aligned.
        for size in range(0, 64):
            for version in (VERSION1, VERSION2, VERSION3):
                pad = padding_length(size, version)
                assert 1 <= pad <= 8

    def test_actual_size_alignment(self):
        for size in range(0, 64):
            for version in (VERSION1, VERSION2, VERSION3):
                assert get_actual_size(size, version) % 8 == 0

    def test_v3_actual_size_example(self):
        # header 16 + size 1 + crc 4 + ts 8 = 29 → pad 3 → 32
        assert get_actual_size(1, VERSION3) == 32
        # header 16 + size 3 + crc 4 + ts 8 = 31 → pad 1 → 32
        assert get_actual_size(3, VERSION3) == 32
        # aligned case gets a FULL extra 8: 16+4+4+8 = 32 → pad 8 → 40
        assert get_actual_size(4, VERSION3) == 40


class TestNeedleRoundTrip:
    def _roundtrip(self, n: Needle, version: int) -> Needle:
        blob = n.to_bytes(version)
        assert len(blob) == n.disk_size(version)
        return Needle.from_bytes(blob, version, size=n.size)

    @pytest.mark.parametrize("version", [VERSION1, VERSION2, VERSION3])
    def test_plain_data(self, version):
        n = Needle(cookie=0xDEADBEEF, id=0x1234, data=b"some needle data")
        m = self._roundtrip(n, version)
        assert (m.cookie, m.id, m.data) == (n.cookie, n.id, n.data)

    def test_all_fields_v3(self):
        n = Needle(cookie=7, id=99, data=b"payload")
        n.name = b"file.txt"
        n.set_has_name()
        n.mime = b"text/plain"
        n.set_has_mime()
        n.last_modified = 1_600_000_000
        n.set_has_last_modified_date()
        n.ttl = TTL.parse("3h")
        n.set_has_ttl()
        n.pairs = b'{"k":"v"}'
        n.set_has_pairs()
        n.append_at_ns = 1_600_000_000_123_456_789
        m = self._roundtrip(n, VERSION3)
        assert m.name == b"file.txt"
        assert m.mime == b"text/plain"
        assert m.last_modified == 1_600_000_000
        assert m.ttl == TTL.parse("3h")
        assert m.pairs == b'{"k":"v"}'
        assert m.append_at_ns == n.append_at_ns
        assert m.data == b"payload"

    def test_empty_data_writes_empty_body(self):
        n = Needle(cookie=1, id=2, data=b"")
        blob = n.to_bytes(VERSION3)
        assert n.size == 0
        # header 16 + crc 4 + ts 8 = 28 → pad 4 → 32
        assert len(blob) == 32
        m = Needle.from_bytes(blob, VERSION3, size=0)
        assert m.data == b""

    def test_size_field_counts_body(self):
        n = Needle(cookie=1, id=2, data=b"abcde")
        n.name = b"nm"
        n.set_has_name()
        n.to_bytes(VERSION3)
        # 4 (data_size) + 5 (data) + 1 (flags) + 1 (name_size) + 2 (name)
        assert n.size == 13

    def test_crc_corruption_detected(self):
        n = Needle(cookie=1, id=2, data=b"good data here")
        blob = bytearray(n.to_bytes(VERSION3))
        blob[t.NEEDLE_HEADER_SIZE + 5] ^= 0xFF  # flip a data byte
        with pytest.raises(CorruptNeedle, match="CRC"):
            Needle.from_bytes(bytes(blob), VERSION3, size=n.size)

    def test_size_mismatch_detected(self):
        n = Needle(cookie=1, id=2, data=b"x")
        blob = n.to_bytes(VERSION3)
        with pytest.raises(CorruptNeedle, match="expected"):
            Needle.from_bytes(blob, VERSION3, size=n.size + 1)

    def test_header_layout_big_endian(self):
        n = Needle(cookie=0x01020304, id=0x0A0B0C0D0E0F1011, data=b"Z")
        blob = n.to_bytes(VERSION3)
        assert blob[0:4] == bytes([1, 2, 3, 4])
        assert blob[4:12] == bytes([0x0A, 0x0B, 0x0C, 0x0D, 0x0E, 0x0F, 0x10, 0x11])

    def test_truncated_blob_raises_corrupt(self):
        n = Needle(cookie=1, id=2, data=b"payload bytes here")
        blob = n.to_bytes(VERSION3)
        for cut in [0, 5, 15, 20, len(blob) - 12]:
            with pytest.raises(CorruptNeedle):
                Needle.from_bytes(blob[:cut], VERSION3)

    def test_flags_byte_out_of_range(self):
        # body claims data fills it entirely, leaving no room for flags
        from seaweedfs_tpu.util import bytesutil as bu

        body = bu.put_u32(4) + b"abcd"  # data_size=4, no flags byte
        blob = (
            bu.put_u32(1) + bu.put_u64(2) + bu.put_u32(len(body)) + body + bytes(16)
        )
        with pytest.raises(CorruptNeedle, match="flags"):
            Needle.from_bytes(blob, VERSION3)

    def test_long_name_truncated(self):
        n = Needle(cookie=1, id=2, data=b"d", name=b"n" * 300)
        n.set_has_name()
        m = self._roundtrip(n, VERSION2)
        assert len(m.name) == 255


class TestNativeNeedleCodec:
    """The C fast paths (native/needle_ext.c) must be bit-identical to
    the pure-Python serializer/parser across a property sweep — the
    volume write/read hot path rides them (needle_read_write.go:31
    prepareWriteBuffer / :163 ReadBytes single-pass shapes)."""

    def _random_needle(self, rng):
        import os as _os

        from seaweedfs_tpu.storage.ttl import TTL

        n = Needle(cookie=rng.randrange(1 << 32), id=rng.randrange(1 << 63))
        n.data = _os.urandom(rng.choice([0, 1, 7, 8, 100, 1024, 65536]))
        if n.data:
            if rng.random() < 0.7:
                n.name = _os.urandom(rng.randrange(0, 300))
                n.set_has_name()
            if rng.random() < 0.5:
                n.mime = b"application/x-test"
                n.set_has_mime()
            if rng.random() < 0.5:
                n.last_modified = rng.randrange(1 << 40)
                n.set_has_last_modified_date()
            if rng.random() < 0.4:
                n.ttl = TTL.parse("3m")
                n.set_has_ttl()
            if rng.random() < 0.4:
                n.pairs = _os.urandom(rng.randrange(0, 1000))
                n.set_has_pairs()
        n.append_at_ns = rng.randrange(1 << 63)
        return n

    def test_encode_record_matches_to_bytes(self):
        import copy
        import random

        from seaweedfs_tpu.storage import needle as needle_mod

        if needle_mod._needle_ext is None:
            pytest.skip("native needle codec not built")
        rng = random.Random(7)
        for _ in range(60):
            n = self._random_needle(rng)
            for version in (1, 2, 3):
                a_n, b_n = copy.deepcopy(n), copy.deepcopy(n)
                assert a_n.to_bytes(version) == bytes(b_n.encode_record(version))
                assert (a_n.size, a_n.checksum) == (b_n.size, b_n.checksum)

    def test_native_decode_matches_python(self):
        import copy
        import random

        from seaweedfs_tpu.storage import needle as needle_mod

        if needle_mod._needle_ext is None:
            pytest.skip("native needle codec not built")
        rng = random.Random(11)
        for _ in range(60):
            n = self._random_needle(rng)
            for version in (1, 2, 3):
                blob = copy.deepcopy(n).to_bytes(version)
                a = Needle.from_bytes(blob, version)  # native path
                saved = needle_mod._needle_ext
                needle_mod._needle_ext = None
                try:
                    b = Needle.from_bytes(blob, version)
                finally:
                    needle_mod._needle_ext = saved
                for f in (
                    "cookie", "id", "size", "data", "flags", "name",
                    "mime", "pairs", "last_modified", "append_at_ns",
                    "checksum",
                ):
                    assert getattr(a, f) == getattr(b, f), (version, f)
                assert str(a.ttl or "") == str(b.ttl or "")

    def test_native_decode_error_parity(self):
        from seaweedfs_tpu.storage.needle import CorruptNeedle

        n = Needle(cookie=1, id=2, data=b"hello")
        blob = n.to_bytes(3)
        corrupt = bytearray(blob)
        corrupt[20] ^= 0xFF
        with pytest.raises(CorruptNeedle, match="CRC error"):
            Needle.from_bytes(bytes(corrupt), 3)
        with pytest.raises(CorruptNeedle, match="truncated"):
            Needle.from_bytes(blob[:10], 3)
        with pytest.raises(CorruptNeedle, match="entry not found"):
            Needle.from_bytes(blob, 3, size=99)


class TestIdx:
    def test_pack_unpack(self):
        b = idx.pack_entry(0x1122334455667788, 0xAABBCCDD, 0x99887766)
        assert len(b) == 16
        assert idx.unpack_entry(b) == (0x1122334455667788, 0xAABBCCDD, 0x99887766)

    def test_walk(self):
        blob = b"".join(idx.pack_entry(k, k * 2, k * 3) for k in range(1, 2500))
        seen = []
        idx.walk_index_file(io.BytesIO(blob), lambda k, o, s: seen.append((k, o, s)))
        assert seen == [(k, k * 2, k * 3) for k in range(1, 2500)]

    def test_numpy_views_roundtrip(self):
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 1 << 63, 1000, dtype=np.uint64)
        offs = rng.integers(0, 1 << 32, 1000, dtype=np.uint64)
        sizes = rng.integers(0, 1 << 32, 1000, dtype=np.uint32)
        blob = idx.arrays_to_entries(keys, offs, sizes)
        k2, o2, s2 = idx.entries_as_arrays(blob)
        np.testing.assert_array_equal(keys, k2)
        np.testing.assert_array_equal(offs, o2)
        np.testing.assert_array_equal(sizes, s2)
        assert blob == b"".join(
            idx.pack_entry(int(k), int(o), int(s)) for k, o, s in zip(keys, offs, sizes)
        )

    def test_reference_fixture_parses(self, reference_root):
        fixture = reference_root / "weed/storage/erasure_coding/1.idx"
        data = fixture.read_bytes()
        assert len(data) % 16 == 0
        keys, offs, sizes = idx.entries_as_arrays(data)
        assert len(keys) > 0
        # every live entry's record must lie inside the .dat file
        dat_size = (reference_root / "weed/storage/erasure_coding/1.dat").stat().st_size
        live = sizes != t.TOMBSTONE_FILE_SIZE
        ends = offs[live] * 8 + sizes[live]
        assert int(ends.max()) <= dat_size + get_actual_size(0, VERSION3)


class TestSuperBlock:
    def test_roundtrip(self):
        sb = SuperBlock(
            version=VERSION3,
            replica_placement=ReplicaPlacement.parse("012"),
            ttl=TTL.parse("5d"),
            compaction_revision=7,
        )
        blob = sb.to_bytes()
        assert len(blob) == 8
        sb2 = SuperBlock.from_bytes(blob)
        assert sb2 == sb

    def test_layout(self):
        sb = SuperBlock(
            version=2,
            replica_placement=ReplicaPlacement.parse("001"),
            ttl=TTL.parse("3m"),
            compaction_revision=0x0102,
        )
        blob = sb.to_bytes()
        assert blob[0] == 2
        assert blob[1] == 1
        assert blob[2:4] == bytes([3, 1])  # count=3, unit=Minute
        assert blob[4:6] == bytes([1, 2])

    def test_extra_preserved(self):
        sb = SuperBlock(extra=b"\x0a\x03abc")
        f = io.BytesIO(sb.to_bytes())
        sb2 = SuperBlock.read_from(f)
        assert sb2.extra == b"\x0a\x03abc"


class TestTtl:
    @pytest.mark.parametrize(
        "s,minutes",
        [("3m", 3), ("4h", 240), ("5d", 7200), ("6w", 60480), ("7M", 312480), ("8y", 4204800)],
    )
    def test_parse_string(self, s, minutes):
        ttl = TTL.parse(s)
        assert str(ttl) == s
        assert ttl.minutes == minutes

    def test_bare_digits_are_minutes(self):
        assert TTL.parse("45") == TTL.parse("45m")

    def test_bytes_roundtrip(self):
        for s in ["", "3m", "255y"]:
            ttl = TTL.parse(s)
            assert TTL.from_bytes(ttl.to_bytes()) == ttl
            assert TTL.from_uint32(ttl.to_uint32()) == ttl

    def test_empty(self):
        assert TTL.parse("").to_uint32() == 0
        assert str(TTL()) == ""


class TestReplicaPlacement:
    def test_parse_and_copy_count(self):
        rp = ReplicaPlacement.parse("012")
        assert rp.diff_data_center_count == 0
        assert rp.diff_rack_count == 1
        assert rp.same_rack_count == 2
        assert rp.copy_count == 4

    def test_byte_roundtrip(self):
        for s in ["000", "001", "010", "100", "200", "112", "222"]:
            rp = ReplicaPlacement.parse(s)
            assert ReplicaPlacement.from_byte(rp.to_byte()) == rp
            assert str(rp) == s

    def test_rejects_invalid(self):
        with pytest.raises(ValueError):
            ReplicaPlacement.parse("003")

    def test_extra_chars_ignored(self):
        # reference's parser only inspects positions 0-2
        assert ReplicaPlacement.parse("0010") == ReplicaPlacement.parse("001")


class TestFileId:
    def test_format_strips_leading_zero_pairs(self):
        assert format_needle_id_cookie(0x1, 0xDEADBEEF) == "01deadbeef"
        assert format_needle_id_cookie(0x0144B2, 0x01020304) == "0144b201020304"
        assert format_needle_id_cookie(0, 0) == "0000000000"

    def test_parse_roundtrip(self):
        for key in [1, 0xFF, 0x1234567890ABCDEF]:
            for cookie in [0, 0xFFFFFFFF, 0x12345678]:
                s = format_needle_id_cookie(key, cookie)
                assert parse_needle_id_cookie(s) == (key, cookie)

    def test_file_id_string(self):
        fid = FileId(3, 0x0144B2, 0xCAFEBABE)
        assert str(fid) == "3,0144b2cafebabe"
        assert FileId.parse(str(fid)) == fid

    def test_etag_is_raw_unmasked_crc(self):
        # reference crc.go Etag(): hex of the RAW crc; masking is only
        # applied in the on-disk trailer.
        n = Needle(cookie=1, id=2, data=b"hello world")
        n.to_bytes(VERSION3)
        assert n.checksum == crc32c(b"hello world")
        assert n.etag() == f"{crc32c(b'hello world'):08x}"

    def test_parse_sets_raw_checksum(self):
        n = Needle(cookie=1, id=2, data=b"abc")
        blob = n.to_bytes(VERSION3)
        m = Needle.from_bytes(blob, VERSION3)
        assert m.checksum == crc32c(b"abc")

    def test_key_cookie_max_length(self):
        # reference rejects key+cookie hex longer than 24 chars
        with pytest.raises(ValueError, match="too long"):
            parse_needle_id_cookie("0" * 25)
        # exactly 24 is fine
        assert parse_needle_id_cookie("0" * 16 + "deadbeef") == (0, 0xDEADBEEF)

    def test_rejects_nonstrict_hex(self):
        # Go strconv.ParseUint rejects signs/prefixes/underscores/space.
        for bad in ["3,-000001deadbeef", "3,0x0001deadbeef", "3,00_01deadbeef", "x,01deadbeef", "3, 01deadbeef"]:
            with pytest.raises(ValueError):
                FileId.parse(bad)

    def test_superblock_truncated_extra_raises(self):
        sb = SuperBlock(extra=b"\x0a\x03abc")
        blob = sb.to_bytes()
        with pytest.raises(ValueError, match="extra"):
            SuperBlock.from_bytes(blob[:8])
