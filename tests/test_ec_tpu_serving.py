"""TPU codec in the SERVING path: the cluster EC lifecycle driven over
gRPC with ec.codec=tpu on every volume server.

Proves the north-star wiring (BASELINE.json config `ec.codec=tpu`):
VolumeEcShardsGenerate, VolumeEcShardsRebuild and degraded-read
reconstruction all run through the JAX bitsliced kernels and produce
files byte-identical to the cpu backend (the reference's
klauspost/reedsolomon semantics at ec_encoder.go:173 / store_ec.go:364).
"""

import json
import os
import shutil
import socket
import time
import urllib.request

import grpc
import pytest

from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.codec import new_encoder
from seaweedfs_tpu.pb import rpc, volume_pb2
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


@pytest.fixture(scope="module")
def tpu_cluster(tmp_path_factory):
    master_port = free_port()
    master = MasterServer(port=master_port, volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(2):
        vs = VolumeServer(
            [str(tmp_path_factory.mktemp(f"tpuvs{i}"))],
            port=free_port(),
            master=f"127.0.0.1:{master_port}",
            heartbeat_interval=0.2,
            max_volume_counts=[100],
            ec_codec="tpu",
        )
        vs.start()
        servers.append(vs)
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topology.data_nodes()) < 2:
        time.sleep(0.05)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_servers_select_tpu_backend(tpu_cluster):
    _, servers = tpu_cluster
    for vs in servers:
        assert vs.ec_codec == "tpu"
        assert vs.store.ec_backend == "tpu"
        assert vs._new_rs()._backend_name == "tpu"


def test_ec_lifecycle_with_tpu_codec(tpu_cluster, tmp_path):
    master, servers = tpu_cluster
    with urllib.request.urlopen(
        f"http://127.0.0.1:{master.port}/dir/assign?collection=tec", timeout=10
    ) as r:
        assign = json.loads(r.read())
    payload = bytes(range(256)) * 2000  # 512 000 B, multi-interval reads
    urllib.request.urlopen(
        urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}", data=payload, method="POST"
        ),
        timeout=10,
    ).close()
    vid = int(assign["fid"].split(",")[0])
    source = next(v for v in servers if f"127.0.0.1:{v.port}" == assign["url"])
    peer = next(v for v in servers if v is not source)

    with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
        stub = rpc.volume_stub(ch)
        stub.VolumeMarkReadonly(
            volume_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
        )
        stub.VolumeEcShardsGenerate(
            volume_pb2.VolumeEcShardsGenerateRequest(volume_id=vid, collection="tec")
        )

    base = source.store.find_volume(vid).base_name

    # 1. generate ran through the tpu backend; bytes must equal a cpu
    #    encode of the same .dat
    ref_base = str(tmp_path / "ref")
    shutil.copy(base + ".dat", ref_base + ".dat")
    ec_files.write_ec_files(ref_base, rs=new_encoder(backend="cpu"))
    for i in range(14):
        with open(base + ec_files.to_ext(i), "rb") as a, open(
            ref_base + ec_files.to_ext(i), "rb"
        ) as b:
            assert a.read() == b.read(), f"shard {i} differs from cpu encode"

    # 2. rebuild 2 deleted shards through the tpu backend, byte-checked
    for sid in (3, 11):
        os.remove(base + ec_files.to_ext(sid))
    with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
        resp = rpc.volume_stub(ch).VolumeEcShardsRebuild(
            volume_pb2.VolumeEcShardsRebuildRequest(volume_id=vid, collection="tec")
        )
    assert sorted(resp.rebuilt_shard_ids) == [3, 11]
    for sid in (3, 11):
        with open(base + ec_files.to_ext(sid), "rb") as a, open(
            ref_base + ec_files.to_ext(sid), "rb"
        ) as b:
            assert a.read() == b.read()

    # 3. degraded read: spread shards across both servers, then delete
    #    the source's copy of every DATA shard it holds so the read must
    #    reconstruct intervals through the tpu codec
    with grpc.insecure_channel(f"127.0.0.1:{peer.grpc_port}") as ch:
        rpc.volume_stub(ch).VolumeEcShardsCopy(
            volume_pb2.VolumeEcShardsCopyRequest(
                volume_id=vid,
                collection="tec",
                shard_ids=list(range(4, 14)),
                copy_ecx_file=True,
                source_data_node=f"127.0.0.1:{source.port}",
            )
        )
        rpc.volume_stub(ch).VolumeEcShardsMount(
            volume_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection="tec", shard_ids=list(range(4, 14))
            )
        )
    with grpc.insecure_channel(f"127.0.0.1:{source.grpc_port}") as ch:
        stub = rpc.volume_stub(ch)
        stub.VolumeEcShardsDelete(
            volume_pb2.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection="tec", shard_ids=list(range(4, 14))
            )
        )
        stub.VolumeEcShardsMount(
            volume_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection="tec", shard_ids=list(range(0, 4))
            )
        )
        stub.VolumeDelete(volume_pb2.VolumeDeleteRequest(volume_id=vid))

    deadline = time.time() + 10
    while time.time() < deadline:
        locs = master.topology.lookup_ec_shards(vid)
        if locs is not None and all(locs.locations[i] for i in range(14)):
            break
        time.sleep(0.1)

    # drop data shard 0 everywhere: source unmounts+removes it, so reads
    # of its intervals must reconstruct from the 13 remaining shards
    ev = source.store.find_ec_volume(vid)
    assert ev is not None and ev.backend == "tpu" and ev.rs._backend_name == "tpu"
    ev.unmount_shard(0)
    os.remove(base + ec_files.to_ext(0))

    with urllib.request.urlopen(
        f"http://{assign['url']}/{assign['fid']}", timeout=20
    ) as r:
        assert r.status == 200
        assert r.read() == payload
