"""Cluster wiring for the weedchaos scenario suite (docs/CHAOS.md).

Shared by tests/test_chaos.py and bench.py's chaos config: builders
for raft-HA master groups and proxied volume servers, an EC volume
seeded over the wire, and the write/read workloads the invariant
checkers audit. Everything here drives REAL servers over real
sockets — the point of the chaos plane is that no fault is simulated
below the syscall/wire level.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

from seaweedfs_tpu.analysis.chaos import ProxyPair
from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.client import retry as retry_mod


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def wait_for(cond, timeout=45.0, interval=0.05) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def spawn_cli(*args, env_extra: dict | None = None):
    """A real `python -m seaweedfs_tpu ...` subprocess (cpu-forced
    jax) — the SIGSTOP/SIGKILL scenarios need a separate PROCESS, and
    `env_extra` selects the serving path (WEED_NATIVE_SERVE) per arm."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", WEED_EC_CODEC="cpu",
        **(env_extra or {}),
    )
    return subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "from seaweedfs_tpu.__main__ import main; main()",
            *args,
        ],
        env=env,
        cwd="/root/repo",
        stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )


def reap_procs(procs) -> None:
    """SIGCONT (for SIGSTOP scenarios) then kill+wait each process."""
    import signal

    for p in procs:
        try:
            p.send_signal(signal.SIGCONT)
        except OSError:
            pass
        try:
            p.kill()
            p.wait(timeout=10)
        except OSError:
            pass


def start_ha_masters(tmp_factory, n: int = 3, **kw):
    """n in-process MasterServers in one raft group; blocks until a
    leader is elected. Caller stops them."""
    from seaweedfs_tpu.server.master_server import MasterServer

    ports = [free_port() for _ in range(n)]
    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    masters = [
        MasterServer(
            port=p,
            volume_size_limit_mb=64,
            vacuum_interval=0,
            peers=peers,
            raft_dir=str(tmp_factory.mktemp(f"chaos_raft{p}")),
            **kw,
        )
        for p in ports
    ]
    for m in masters:
        m.start()
    assert wait_for(
        lambda: sum(1 for m in masters if m.is_leader) == 1
    ), "no raft leader elected"
    return masters


def master_addrs(masters) -> list[str]:
    return [f"127.0.0.1:{m.port}" for m in masters]


def start_volume_server(tmp_factory, masters_csv: str, tag: str, **kw):
    """One in-process VolumeServer heartbeating at `masters_csv`.
    Pass announce="host:port" to advertise a ChaosProxy pair instead
    of the bind address (the partition lever)."""
    from seaweedfs_tpu.server.volume_server import VolumeServer

    vs = VolumeServer(
        [str(tmp_factory.mktemp(f"chaos_{tag}"))],
        port=free_port(),
        master=masters_csv,
        heartbeat_interval=0.2,
        max_volume_counts=[100],
        ec_codec="cpu",
        scrub_interval=0,
        **kw,
    )
    vs.start()
    return vs


def proxied_volume_server(tmp_factory, masters_csv: str, tag: str, **kw):
    """A volume server the CLUSTER reaches only through a ChaosProxy
    pair (HTTP + gRPC ports faulted together): returns (vs, pair).
    pair.partition()/heal() then cuts/restores the node for every peer
    that dials its master-advertised address."""
    from seaweedfs_tpu.server.volume_server import VolumeServer

    port = free_port()
    pair = ProxyPair(f"127.0.0.1:{port}")
    vs = VolumeServer(
        [str(tmp_factory.mktemp(f"chaos_{tag}"))],
        port=port,
        master=masters_csv,
        heartbeat_interval=0.2,
        max_volume_counts=[100],
        ec_codec="cpu",
        scrub_interval=0,
        announce=pair.addr,
        **kw,
    )
    vs.start()
    return vs, pair


# ---------------------------------------------------------------------------
# workloads


def put_blob(masters: list[str], data: bytes, collection: str = "",
             policy=None) -> str:
    """assign (with policy-driven master failover) + upload; returns
    the fid. Raises on failure — callers count."""
    ar, _ = op.with_master_failover(
        masters, lambda m: op.assign(m, collection=collection), policy=policy
    )
    ur = op.upload(f"{ar.url}/{ar.fid}", data, jwt=ar.auth)
    if ur.error:
        raise RuntimeError(f"upload {ar.fid}: {ur.error}")
    return ar.fid


def read_blob(masters: list[str], fid: str, collection: str = "") -> bytes:
    """Locate via any live master and download one replica."""
    def locate(m):
        url = op.lookup_file_id(m, fid)
        return url

    url, _ = op.with_master_failover(masters, locate)
    q = f"?collection={collection}" if collection else ""
    data, _ = op.download(url + q, timeout=10)
    return data


def write_fan(
    masters: list[str],
    n_writers: int = 3,
    n_writes: int = 30,
    payload_fn=None,
    policy=None,
) -> dict:
    """Concurrent writer fan for scenarios: each writer loops
    assign+upload through master failover. Returns the invariant-
    checker report: acked {fid: payload}, failed count, requests_sent
    (first attempts + granted retries, for amplification audits)."""
    payload_fn = payload_fn or (lambda w, i: f"chaos w{w} i{i} ".encode() * 50)
    acked: dict[str, bytes] = {}
    lock = threading.Lock()
    failed = [0]
    duplicates = [0]
    retries_before = retry_mod.DEFAULT_BUDGET.spent

    def writer(w: int) -> None:
        for i in range(n_writes):
            data = payload_fn(w, i)
            try:
                fid = put_blob(masters, data, policy=policy)
            except Exception:  # noqa: BLE001 - counted, audited below
                with lock:
                    failed[0] += 1
                continue
            with lock:
                if fid in acked:
                    # two writers acked the SAME fid: a replayed
                    # assign double-applied — the no_double_apply
                    # invariant reads this counter (the acked dict's
                    # keys alone can't show it: the second insert
                    # silently overwrites)
                    duplicates[0] += 1
                acked[fid] = data

    threads = [
        threading.Thread(target=writer, args=(w,), daemon=True)
        for w in range(n_writers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    attempts = n_writers * n_writes
    return {
        "acked": acked,
        "failed": failed[0],
        "duplicates": duplicates[0],
        "requests_sent": attempts + (retry_mod.DEFAULT_BUDGET.spent - retries_before),
    }


# ---------------------------------------------------------------------------
# EC seeding


def seed_ec_volume(master, collection: str, n: int = 8) -> tuple[int, dict]:
    """Write a keyset, seal + EC-encode + spread it over the live
    cluster via the shell verbs (the operator path). Returns
    (vid, {fid: payload})."""
    import io

    from seaweedfs_tpu.shell.command_env import CommandEnv
    from seaweedfs_tpu.shell.commands import do_ec_encode
    from seaweedfs_tpu.util.availability import write_keyset

    vid, keys, _src = write_keyset(
        master.port,
        collection,
        n=n,
        payload_fn=lambda i: (f"chaos ec {i} ".encode() * 1500)[: 12000 + i],
    )
    env = CommandEnv([f"127.0.0.1:{master.port}"])
    do_ec_encode(env, vid, collection, io.StringIO())
    return vid, keys


def registered_shards(master, vid: int) -> int:
    locs = master.topology.lookup_ec_shards(vid)
    if locs is None:
        return 0
    return sum(1 for nodes in locs.locations if nodes)
