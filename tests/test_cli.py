"""CLI subcommand tests: offline tools against real volume files, and
the benchmark/upload/download tools against a live in-process cluster."""

import json
import os
import socket
import time

import pytest

from seaweedfs_tpu.command import main as cli_main
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


from seaweedfs_tpu.util.availability import free_port  # noqa: E402 — collision-hardened allocator


def spawn_cli(*args):
    """A real `python -m seaweedfs_tpu ...` subprocess (cpu-forced jax)."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu", WEED_EC_CODEC="cpu")
    return subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "from seaweedfs_tpu.__main__ import main; main()",
            *args,
        ],
        env=env,
        cwd="/root/repo",
        stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )


def wait_until(pred, what, deadline_s=40):
    """Poll pred() (exceptions count as not-ready) until truthy; returns
    the elapsed seconds. Raises RuntimeError on timeout."""
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        try:
            if pred():
                return time.time() - t0
        except Exception:
            pass
        time.sleep(0.2)
    raise RuntimeError(f"timed out waiting for {what}")


def reap(procs):
    """SIGCONT (in case of SIGSTOP tests) then kill+wait each process."""
    import signal

    for p in procs:
        try:
            p.send_signal(signal.SIGCONT)
        except OSError:
            pass
        try:
            p.kill()
            p.wait(timeout=10)
        except OSError:
            pass


class TestOfflineTools:
    def _make_volume(self, tmp_path, vid=7):
        vol = Volume(str(tmp_path), vid)
        for i in range(1, 21):
            n = Needle(cookie=0x1234, id=i, data=f"needle-{i}".encode() * 10)
            n.name = f"file{i}.txt".encode()
            n.set_has_name()
            vol.write_needle(n)
        for i in (3, 7):
            vol.delete_needle(Needle(cookie=0x1234, id=i))
        vol.close()
        return vid

    def test_version(self, capsys):
        assert cli_main(["version"]) == 0
        assert "seaweedfs_tpu" in capsys.readouterr().out

    def test_scaffold(self, capsys):
        assert cli_main(["scaffold", "-config", "filer"]) == 0
        out = capsys.readouterr().out
        assert "[sqlite]" in out

    def test_scaffold_unknown(self, capsys):
        assert cli_main(["scaffold", "-config", "nope"]) == 1

    def test_fix_rebuilds_idx(self, tmp_path, capsys):
        vid = self._make_volume(tmp_path)
        idx = tmp_path / f"{vid}.idx"
        original = idx.read_bytes()
        idx.unlink()
        assert cli_main(["fix", "-dir", str(tmp_path), "-volumeId", str(vid)]) == 0
        rebuilt = idx.read_bytes()
        # 18 live entries (20 written, 2 deleted)
        assert len(rebuilt) == 18 * 16
        # reopening the volume with the rebuilt index serves the data
        vol = Volume(str(tmp_path), vid)
        n = vol.read_needle(5)
        assert n.data == b"needle-5" * 10
        assert not vol.has_needle(3)
        vol.close()

    def test_export_lists_live_needles(self, tmp_path, capsys):
        vid = self._make_volume(tmp_path)
        out_dir = tmp_path / "exported"
        out_dir.mkdir()
        assert (
            cli_main(
                [
                    "export",
                    "-dir",
                    str(tmp_path),
                    "-volumeId",
                    str(vid),
                    "-o",
                    str(out_dir),
                ]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert "file5.txt" in out
        assert (out_dir / "file5.txt").read_bytes() == b"needle-5" * 10
        # deleted needles are not exported
        assert not (out_dir / "file3.txt").exists()
        assert len(list(out_dir.iterdir())) == 18

    def test_export_to_tar_with_name_format(self, tmp_path, capsys):
        """-o name.tar produces a tar whose member names follow
        -fileNameFormat (command/export.go:44,57)."""
        import tarfile

        vid = self._make_volume(tmp_path)
        tar_path = tmp_path / "vol.tar"
        assert (
            cli_main(
                [
                    "export",
                    "-dir", str(tmp_path),
                    "-volumeId", str(vid),
                    "-o", str(tar_path),
                    "-fileNameFormat", "{{.Id}}-{{.Name}}",
                ]
            )
            == 0
        )
        with tarfile.open(tar_path) as t:
            names = t.getnames()
            assert len(names) == 18  # live needles only
            assert "5-file5.txt" in names
            assert not any("file3" in n for n in names)  # deleted
            data = t.extractfile("5-file5.txt").read()
            assert data == b"needle-5" * 10

    def test_export_newer_filter(self, tmp_path, capsys):
        """-newer excludes needles whose last_modified is older
        (command/export.go:59); needles without a timestamp (0) are
        excluded by any cutoff, like the reference's comparison."""
        import time as _time

        from seaweedfs_tpu.storage.needle import Needle

        vol = Volume(str(tmp_path), 42)
        now = int(_time.time())
        for i in range(4):
            n = Needle(cookie=1, id=i + 1, data=b"ts")
            n.last_modified = now if i < 3 else now - 10 * 24 * 3600
            n.set_has_last_modified_date()
            vol.write_needle(n)
        vol.close()

        assert (
            cli_main(
                [
                    "export",
                    "-dir", str(tmp_path),
                    "-volumeId", "42",
                    "-newer", "2099-01-01T00:00:00",
                ]
            )
            == 0
        )
        assert "0 needles" in capsys.readouterr().err
        # a cutoff between the old needle and the fresh ones keeps 3
        import datetime as _dt

        cutoff = _dt.datetime.fromtimestamp(
            now - 3600, _dt.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%S")
        assert (
            cli_main(
                [
                    "export",
                    "-dir", str(tmp_path),
                    "-volumeId", "42",
                    "-newer", cutoff,
                ]
            )
            == 0
        )
        assert "3 needles" in capsys.readouterr().err

    def test_compact(self, tmp_path, capsys):
        vid = self._make_volume(tmp_path)
        before = (tmp_path / f"{vid}.dat").stat().st_size
        assert cli_main(["compact", "-dir", str(tmp_path), "-volumeId", str(vid)]) == 0
        after = (tmp_path / f"{vid}.dat").stat().st_size
        assert after < before
        vol = Volume(str(tmp_path), vid)
        assert vol.read_needle(5).data == b"needle-5" * 10
        assert not vol.has_needle(3)
        vol.close()

    def test_help_lists_commands(self, capsys):
        assert cli_main([]) == 2
        out = capsys.readouterr().out
        for cmd in ("master", "volume", "filer", "s3", "benchmark", "shell"):
            assert cmd in out


@pytest.fixture(scope="module")
def mini_cluster(tmp_path_factory):
    mport = free_port()
    master = MasterServer(port=mport, volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        [str(tmp_path_factory.mktemp("clivol"))],
        port=free_port(),
        master=f"127.0.0.1:{mport}",
        heartbeat_interval=0.2,
        max_volume_counts=[50],
    )
    vs.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topology.data_nodes():
        time.sleep(0.05)
    yield f"127.0.0.1:{mport}"
    vs.stop()
    master.stop()


class TestClusterTools:
    def test_upload_download(self, mini_cluster, tmp_path, capsys):
        src = tmp_path / "hello.txt"
        src.write_bytes(b"cli upload payload")
        assert (
            cli_main(["upload", str(src), "-master", mini_cluster]) == 0
        )
        result = json.loads(capsys.readouterr().out)
        fid = result[0]["fid"]
        assert result[0]["error"] == ""
        out_dir = tmp_path / "dl"
        out_dir.mkdir()
        assert (
            cli_main(
                ["download", fid, "-server", mini_cluster, "-dir", str(out_dir)]
            )
            == 0
        )
        files = list(out_dir.iterdir())
        assert len(files) == 1
        assert files[0].read_bytes() == b"cli upload payload"

    def test_benchmark_small(self, mini_cluster, capsys):
        from seaweedfs_tpu.command.benchmark import run_benchmark

        results, fids = run_benchmark(
            mini_cluster, concurrency=4, num=40, size=512
        )
        assert len(fids) == 40
        titles = [t for t, _ in results]
        assert any("Writing" in t for t in titles)
        assert any("Read" in t for t in titles)
        for _, stats in results:
            assert stats.failed == 0
            assert stats.completed == 40
            report = stats.report("x", 4)
            assert "Requests per second" in report
            assert "99%" in report

    def test_shell_script(self, mini_cluster, capsys):
        assert (
            cli_main(["shell", "-master", mini_cluster, "-c", "volume.list"]) == 0
        )
        out = capsys.readouterr().out
        assert "DataCenter" in out or "volume" in out.lower()


class TestServerDaemon:
    """Boot the all-in-one `server` command as a real subprocess and
    drive it over HTTP — the README quickstart, verified."""

    def test_all_in_one_smoke(self, tmp_path):
        import json as _json
        import os
        import signal
        import socket
        import subprocess
        import sys
        import time
        import urllib.request

        from seaweedfs_tpu.util.availability import free_port

        mport, vport, fport = free_port(), free_port(), free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["WEED_EC_CODEC"] = "cpu"
        proc = subprocess.Popen(
            [
                sys.executable,
                "-c",
                # sitecustomize may bake the axon platform in before the
                # CLI runs; force cpu the way conftest does
                "import jax; jax.config.update('jax_platforms', 'cpu');"
                "from seaweedfs_tpu.__main__ import main; main()",
                "server",
                "-dir",
                str(tmp_path),
                "-master.port",
                str(mport),
                "-volume.port",
                str(vport),
                "-filer",
                "-filer.port",
                str(fport),
            ],
            env=env,
            cwd="/root/repo",
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.time() + 30
            assign = None
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/assign", timeout=2
                    ) as r:
                        assign = _json.loads(r.read())
                    if "fid" in assign:
                        break
                except OSError:
                    time.sleep(0.2)
            assert assign and "fid" in assign, f"daemon never served: {assign}"

            blob = b"all-in-one daemon smoke"
            req = urllib.request.Request(
                f"http://{assign['url']}/{assign['fid']}",
                data=blob,
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()
            with urllib.request.urlopen(
                f"http://{assign['url']}/{assign['fid']}", timeout=10
            ) as r:
                assert r.read() == blob

            # filer HTTP namespace up too
            req = urllib.request.Request(
                f"http://127.0.0.1:{fport}/smoke/hello.txt",
                data=b"via filer",
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).close()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/smoke/hello.txt", timeout=10
            ) as r:
                assert r.read() == b"via filer"
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def _needle_payload(n) -> bytes:
    """A needle's logical payload: the volume auto-gzips compressible
    uploads (util/compression.py, the reference's IsGzippable), so raw
    record comparisons decode the flag first."""
    import gzip

    data = bytes(n.data)
    return gzip.decompress(data) if n.is_gzipped() else data


class TestBackupCommand:
    def test_incremental_backup_roundtrip(self, mini_cluster, tmp_path, capsys):
        """backup pulls a volume's records locally and resumes
        incrementally (command/backup.go runBackup role)."""
        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.storage.file_id import FileId
        from seaweedfs_tpu.storage.volume import Volume

        main = cli_main

        master_addr = mini_cluster
        ar = op.assign(master_addr, collection="bak")
        payload1 = b"first backup payload " * 40
        assert not op.upload(f"{ar.url}/{ar.fid}", payload1, jwt=ar.auth).error
        vid = int(ar.fid.split(",")[0])

        rc = main(
            [
                "backup",
                "-master",
                master_addr,
                "-volumeId",
                str(vid),
                "-collection",
                "bak",
                "-dir",
                str(tmp_path),
            ]
        )
        assert rc == 0

        fid1 = FileId.parse(ar.fid)
        v = Volume(str(tmp_path), vid, "bak", create=False)
        assert _needle_payload(v.read_needle(fid1.key, cookie=fid1.cookie)) == payload1
        first_size = v.data_file_size()
        v.close()

        # write more into the SAME volume, then an incremental run
        # appends only the tail
        payload2 = b"second incremental blob"
        ar2 = op.assign(master_addr, collection="bak")
        for _ in range(300):  # bounded: a hang here must fail, not stall CI
            if int(ar2.fid.split(",")[0]) == vid:
                break
            ar2 = op.assign(master_addr, collection="bak")
        else:
            pytest.skip("assign never landed on the backed-up volume")
        assert not op.upload(f"{ar2.url}/{ar2.fid}", payload2, jwt=ar2.auth).error

        rc = main(
            [
                "backup",
                "-master",
                master_addr,
                "-volumeId",
                str(vid),
                "-collection",
                "bak",
                "-dir",
                str(tmp_path),
            ]
        )
        assert rc == 0
        fid2 = FileId.parse(ar2.fid)
        v = Volume(str(tmp_path), vid, "bak", create=False)
        assert _needle_payload(v.read_needle(fid1.key, cookie=fid1.cookie)) == payload1
        assert _needle_payload(v.read_needle(fid2.key, cookie=fid2.cookie)) == payload2
        assert v.data_file_size() > first_size
        v.close()


class TestFilerCopyCommand:
    def test_copy_tree_into_filer(self, mini_cluster, tmp_path, capsys):
        """filer.copy walks a local tree into the filer namespace
        (command/filer_copy.go role)."""
        import urllib.request

        from seaweedfs_tpu.server.filer_server import FilerServer

        master_addr = mini_cluster
        filer = FilerServer([master_addr], port=free_port(), store="memory")
        filer.start()
        try:
            src = tmp_path / "proj"
            (src / "sub").mkdir(parents=True)
            (src / "a.txt").write_bytes(b"alpha file")
            (src / "sub" / "b.bin").write_bytes(bytes(range(100)))

            rc = cli_main(
                [
                    "filer.copy",
                    str(src),
                    f"http://127.0.0.1:{filer.port}/imported/",
                ]
            )
            assert rc == 0
            assert "copied 2 files" in capsys.readouterr().out

            with urllib.request.urlopen(
                f"http://127.0.0.1:{filer.port}/imported/proj/a.txt", timeout=10
            ) as r:
                assert r.read() == b"alpha file"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{filer.port}/imported/proj/sub/b.bin", timeout=10
            ) as r:
                assert r.read() == bytes(range(100))
        finally:
            filer.stop()


class TestCrashRecovery:
    """Hard-kill (SIGKILL) a volume-server subprocess mid-life and
    restart it on the same directory: every acknowledged write must
    survive (appends flush to the OS per write; .idx tail is validated
    against .dat on load) and the node must rejoin the master."""

    def test_sigkill_volume_server_and_restart(self, tmp_path):
        import signal
        import urllib.request

        def http(url, data=None, method="GET", timeout=5):
            req = urllib.request.Request(url, data=data, method=method)
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.read()

        def assign():
            a = json.loads(http(f"http://127.0.0.1:{mport}/dir/assign"))
            return None if a.get("error") else a

        mport, vport = free_port(), free_port()
        vol_dir = tmp_path / "vol"
        vol_dir.mkdir()
        procs = [spawn_cli("master", "-port", str(mport))]
        try:
            wait_until(
                lambda: http(f"http://127.0.0.1:{mport}/cluster/status"), "master"
            )
            volume = spawn_cli(
                "volume", "-port", str(vport), "-dir", str(vol_dir),
                "-mserver", f"127.0.0.1:{mport}",
            )
            procs.append(volume)
            wait_until(assign, "cluster writable")

            blobs = {}
            for i in range(20):
                wait_until(assign, "assign")
                a = assign()
                payload = f"crash-survivor-{i:03d}".encode() * 10
                http(f"http://{a['url']}/{a['fid']}", data=payload, method="POST")
                blobs[a["fid"]] = payload
            known_fid = next(iter(blobs))

            volume.send_signal(signal.SIGKILL)  # hard crash, no cleanup
            volume.wait(timeout=10)

            procs.append(
                spawn_cli(
                    "volume", "-port", str(vport), "-dir", str(vol_dir),
                    "-mserver", f"127.0.0.1:{mport}",
                )
            )
            # readiness = an actual read succeeds against the restarted
            # server (an assign alone can race the master's stale
            # registration of the killed process)
            wait_until(
                lambda: http(f"http://127.0.0.1:{vport}/{known_fid}"),
                "restarted volume serving reads",
            )

            for fid, payload in blobs.items():
                assert http(f"http://127.0.0.1:{vport}/{fid}") == payload, fid
            # and it still accepts writes
            wait_until(assign, "post-restart assign")
            a = assign()
            http(f"http://{a['url']}/{a['fid']}", data=b"post-crash", method="POST")
            assert http(f"http://127.0.0.1:{vport}/{a['fid']}") == b"post-crash"
        finally:
            reap(procs)


class TestLivenessSweep:
    """End-to-end master liveness: SIGSTOP a volume-server subprocess
    (stream stays open, beats stop) → master sweeps it and drops its
    volume locations; SIGCONT → the woken node re-registers AND its
    volumes reappear promptly (the master requests a full heartbeat
    instead of waiting ~10 delta cycles)."""

    def test_sigstop_sweep_sigcont_recover(self, tmp_path):
        import signal
        import urllib.error
        import urllib.request

        def http_json(url, timeout=2):
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read())

        mport, vport = free_port(), free_port()
        vol_dir = tmp_path / "vol"
        vol_dir.mkdir()
        procs = [spawn_cli("master", "-port", str(mport), "-nodeTimeout", "3")]
        try:
            wait_until(
                lambda: http_json(f"http://127.0.0.1:{mport}/cluster/status"),
                "master",
            )
            volume = spawn_cli(
                "volume", "-port", str(vport), "-dir", str(vol_dir),
                "-mserver", f"127.0.0.1:{mport}",
            )
            procs.append(volume)

            def assign():
                a = http_json(f"http://127.0.0.1:{mport}/dir/assign")
                return None if a.get("error") else a

            wait_until(assign, "writable")
            a = assign()
            vid = a["fid"].split(",")[0]
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}", data=b"sweep-me", method="POST"
                ),
                timeout=5,
            ).close()

            def located():
                try:
                    out = http_json(
                        f"http://127.0.0.1:{mport}/dir/lookup?volumeId={vid}"
                    )
                except urllib.error.HTTPError:
                    return False  # 404: not located (the swept state)
                return bool(out.get("locations"))

            assert located()
            volume.send_signal(signal.SIGSTOP)  # freeze: stream survives
            wait_until(lambda: not located(), "volume swept", 30)

            volume.send_signal(signal.SIGCONT)
            dt = wait_until(located, "volume re-announced", 30)
            # the requested full beat re-announces within ~2 beat
            # intervals (2s each); without it the delta protocol would
            # wait for the 10-cycle full beat (~20s)
            assert dt < 15, "re-announcement took a full-cycle wait"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{vport}/{a['fid']}", timeout=5
            ) as r:
                assert r.read() == b"sweep-me"
        finally:
            reap(procs)
